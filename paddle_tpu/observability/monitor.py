"""Live run monitor (ISSUE 5): the in-flight half of the observability
story.

Everything before this module is post-hoc — ``aggregate`` merges worker
streams after the run, the doctor reads a finished run_dir.  This module
makes the same telemetry operable *while the chips burn*:

- :class:`StatusServer` — a per-worker stdlib HTTP thread exposing
  ``/metrics`` (Prometheus text, the same rendering the textfile
  exporter writes), ``/statusz`` (one JSON page: step, step-time
  p50/p99, live MFU, heartbeat age, watchdog state, HBM watermarks,
  compile-cache stats) and ``/healthz`` (200/503 from supervisor
  state).  ``RunSupervisor.begin_run`` starts one when
  ``PTPU_MONITOR_PORT`` is set (port + worker rank, so a localhost
  multi-worker simulation gets distinct ports).
- :class:`LiveAggregator` — the launcher-side (or in-process) watcher:
  tail-reads the still-growing ``<run_dir>/metrics/worker-*.jsonl``
  streams with the drop-tolerant reader, keeps a bounded window of
  recent records per worker, and re-runs the doctor's rule functions
  over that window every ``PTPU_MONITOR_INTERVAL`` seconds.  Verdicts
  land in a rolling ``<run_dir>/live_status.json`` and — the moment one
  first fires — as a ``monitor.alert`` record on the supervisor
  timeline, so a retrace storm at step 40 is *named* at step ~41, not
  at teardown.

The same server fronts the serving engine (ISSUE 6): construct with
``engine=`` (or ``ServingEngine.start_status_server()``) and
``/statusz`` gains a ``serving`` section — queue depth,
running/waiting counts, TTFT/TPOT p50/p99, KV-cache occupancy — while
``/healthz`` answers 503 the moment the admission queue passes
``PTPU_SHED_QUEUE_DEPTH`` (load shedding).

Env knobs: ``PTPU_MONITOR_PORT`` (status server; 0 = ephemeral),
``PTPU_MONITOR_INTERVAL`` (aggregation cadence, default 5s),
``PTPU_FLIGHT_BUFFER`` (see :mod:`flight`).  See docs/ARCHITECTURE.md
"Live monitoring" and "Serving".
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ..framework.log import vlog
from ..utils import fsio
from .aggregate import StreamTail, straggler_stats
from .sinks import metrics_dir, render_prometheus

__all__ = ["MONITOR_PORT_ENV", "MONITOR_INTERVAL_ENV", "StatusServer",
           "LiveAggregator", "default_monitor_interval",
           "maybe_start_server", "live_status_path"]

MONITOR_PORT_ENV = "PTPU_MONITOR_PORT"
MONITOR_INTERVAL_ENV = "PTPU_MONITOR_INTERVAL"

_WORKER_RE = re.compile(r"^worker-(\d+)\.jsonl$")


def default_monitor_interval() -> float:
    return float(os.environ.get(MONITOR_INTERVAL_ENV, "5"))


def live_status_path(run_dir: str) -> str:
    return os.path.join(run_dir, "live_status.json")


# ---------------------------------------------------------------------------
# per-worker status server
# ---------------------------------------------------------------------------
class StatusServer:
    """One HTTP thread per worker answering the three operator questions
    — *what are the numbers* (``/metrics``), *what is this worker doing
    right now* (``/statusz``), *is it alive* (``/healthz``).

    ``port=0`` binds an ephemeral port (read back via ``.port``);
    ``registry`` defaults to the process-global one at request time so a
    server started before the first instrument still sees everything.
    """

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 registry=None, supervisor=None,
                 worker_id: Optional[int] = None, engine=None,
                 router=None):
        self._registry = registry
        self.supervisor = supervisor
        self.engine = engine          # serving engine (ISSUE 6 SLOs)
        self.router = router          # fleet router (ISSUE 16 census)
        self.worker_id = worker_id
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.requests = 0
        self.host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from .registry import get_registry
        return get_registry()

    # -- the three pages ---------------------------------------------------
    def render_metrics(self) -> str:
        return render_prometheus(self._reg())

    def healthz(self):
        """(http_status, state_string) from supervisor state: 503 the
        moment the run is not something a load balancer / babysitter
        should route to or wait on quietly.  With a serving engine
        attached, an admission queue past ``PTPU_SHED_QUEUE_DEPTH``
        also answers 503 — the load-shedding signal a balancer drains
        on (requests already queued still complete) — and an engine in
        ``draining`` / ``stopped`` state answers 503 for the whole
        drain window (ISSUE 15) so the balancer routes elsewhere while
        in-flight work finishes."""
        if self.engine is not None:
            try:
                estate = getattr(self.engine, "state", "serving")
                if estate != "serving":
                    return 503, estate
                if self.engine.should_shed():
                    depth = self.engine.sched.queue_depth
                    return 503, f"load-shed:queue_depth={depth}"
            except Exception:  # noqa: swallow — health must answer
                pass
        sup = self.supervisor
        if sup is None:
            return 200, "ok"          # standalone server: serving = alive
        if not getattr(sup, "_running", False):
            return 503, "not-running"
        if sup.pending_rollback:
            return 503, f"rollback-pending:{sup.pending_rollback}"
        state = getattr(sup.monitor, "_last_state", None)
        from ..supervisor.heartbeat import RunState
        if state == RunState.LOST_WORKER:
            return 503, state
        return 200, state or "healthy"

    def statusz(self) -> Dict[str, Any]:
        reg = self._reg()
        snap = reg.snapshot()
        now = time.time()

        def hist(name):
            m = snap.get(name)
            if not m or m.get("type") != "histogram" or not m["count"]:
                return None
            return {"count": m["count"], "mean": m["mean"],
                    "p50": m["p50"], "p99": m["p99"]}

        def gauge(name):
            m = snap.get(name)
            return m["value"] if m and m.get("type") == "gauge" else None

        status: Dict[str, Any] = {
            "worker": self.worker_id,
            "pid": os.getpid(),
            "time": now,
            "step": gauge("step.current"),
            "loss": gauge("step.loss"),
            "step_time_ms": hist("step.time_ms"),
            "data_ms": hist("step.data_ms"),
            "mfu": gauge("step.mfu"),
            "tokens_per_sec": gauge("step.tokens_per_sec"),
        }
        hs, state = self.healthz()
        status["health"] = {"ok": hs == 200, "state": state}
        # serving SLOs (ISSUE 6): present whenever a serving engine is
        # attached or serve.* instruments exist in the registry
        serving: Dict[str, Any] = {}
        def counter(name):
            m = snap.get(name)
            return m["value"] if m and m.get("type") == "counter" else 0

        if any(k.startswith("serve.") for k in snap):
            serving = {
                "queue_depth": gauge("serve.queue_depth"),
                "waiting": gauge("serve.waiting"),
                "running": gauge("serve.running"),
                "kv_occupancy": gauge("serve.kv_occupancy"),
                "kv_blocks_used": gauge("serve.kv_blocks_used"),
                "ttft_ms": hist("serve.ttft_ms"),
                "tpot_ms": hist("serve.tpot_ms"),
                # lifecycle-guard counters (ISSUE 15) — registry-derived
                # so they render even without an attached engine; the
                # engine's richer "resilience" dict wins when present
                "resilience": {
                    "deadline_misses": counter("serve.deadline_misses"),
                    "cancelled": counter("serve.cancelled"),
                    "poisoned": counter("serve.poisoned"),
                    "spilled": counter("serve.spilled"),
                    "watchdog_restarts":
                        counter("serve.watchdog_restarts"),
                    "callback_errors": counter("serve.callback_errors"),
                },
            }
        if self.engine is not None:
            try:
                serving.update(self.engine.stats())
            except Exception:  # noqa: swallow — statusz must render
                pass
        status["serving"] = serving or None
        # serving fleet (ISSUE 16): replica census + stream/failover
        # counters — registry-derived so any worker in the fleet can
        # render it; the router's richer stats() dict wins when the
        # router itself hosts this server
        fleet: Dict[str, Any] = {}
        if any(k.startswith("fleet.") for k in snap):
            states = {}
            for key in snap:
                if key.startswith("fleet.replicas[state="):
                    states[key[len("fleet.replicas[state="):-1]] = \
                        gauge(key)
            fleet = {
                "replicas": states or None,
                "streams": gauge("fleet.streams"),
                # client-observed latency tails (ISSUE 18) — next to
                # the engine-local serve.* histograms so the gap is
                # visible at a glance
                "ttft_ms": hist("fleet.ttft_ms"),
                "tpot_ms": hist("fleet.tpot_ms"),
                "dispatch": counter("fleet.dispatch"),
                "retries": counter("fleet.retries"),
                "failovers": counter("fleet.failovers"),
                "migrations": counter("fleet.migrations"),
                "shed": counter("fleet.shed"),
                "restarts": counter("fleet.restarts"),
                "deferred": counter("fleet.deferred"),
                "breaker_trips": counter("fleet.breaker_trips"),
                "autoscale_events": counter("fleet.autoscale"),
                "recovered": counter("fleet.recovered"),
            }
        if self.router is not None:
            try:
                fleet.update(self.router.stats())
            except Exception:  # noqa: swallow — statusz must render
                pass
        status["fleet"] = fleet or None
        sup = self.supervisor
        # elasticity (ISSUE 9): present whenever an elastic coordinator
        # drives this worker or elastic.* instruments exist — the page an
        # operator checks after a preemption notice
        elastic: Dict[str, Any] = {}
        if any(k.startswith("elastic.") for k in snap):
            resizes = snap.get("elastic.resizes")
            elastic = {
                "generation": gauge("elastic.generation"),
                "world_size": gauge("elastic.world_size"),
                "dp": gauge("elastic.dp"),
                "resizes": (resizes["value"] if resizes
                            and resizes.get("type") == "counter" else 0),
            }
        coord = getattr(sup, "coordinator", None) if sup else None
        if coord is not None:
            elastic.update({
                "generation": coord.generation,
                "dp": coord.dp, "mp": coord.mp, "pp": coord.pp,
                "world_size": coord.world_size,
                "min_dp": coord.min_dp, "max_dp": coord.max_dp,
                "resizes": coord.resizes,
                "last_resize": coord.last_resize,
                "pending": getattr(sup, "pending_resize", None),
            })
        status["elastic"] = elastic or None
        # state integrity (ISSUE 11): present whenever an IntegrityGuard
        # drives this worker or integrity.* instruments exist — the page
        # an operator checks when replicas start disagreeing
        integrity: Dict[str, Any] = {}
        if any(k.startswith("integrity.") for k in snap):
            def count(name):
                m = snap.get(f"integrity.{name}")
                return m["value"] if m and m.get("type") == "counter" else 0
            integrity = {
                "last_step": gauge("integrity.last_step"),
                "interval": gauge("integrity.interval"),
                "digest": gauge("integrity.digest"),
                "workers": gauge("integrity.workers"),
                "suspects": gauge("integrity.suspects"),
                "checks": count("checks"),
                "mismatches": count("mismatches"),
                "audits": count("audits"),
                "resyncs": count("resyncs"),
            }
        ig = getattr(sup, "integrity", None) if sup else None
        if ig is not None:
            integrity.update({
                "enabled": ig.enabled,
                "interval": ig.every,
                "action": ig.action,
                "checks": ig.checks,
                "mismatches": ig.mismatches,
                "strikes": dict(ig.strikes),
                "last_digest": (ig.last_fingerprint.hex()
                                if ig.last_fingerprint is not None
                                else None),
                "last_verdict": (dict(ig.last_verdict)
                                 if ig.last_verdict is not None else None),
                "pending": (dict(sup.pending_integrity)
                            if getattr(sup, "pending_integrity", None)
                            is not None else None),
            })
        status["integrity"] = integrity or None
        # performance observatory (ISSUE 13): present whenever the bench
        # runner has mirrored matrix figures into the registry; carries
        # the perf_regression verdict (dominant mover named) when a
        # golden baseline exists to compare against
        perf: Dict[str, Any] = {}
        perf_gauges = {k: m for k, m in snap.items()
                       if k.startswith("perf.") and m.get("type") == "gauge"}
        if perf_gauges:
            scen: Dict[str, Dict[str, Any]] = {}
            for name, m in perf_gauges.items():
                if "[scenario=" not in name:
                    continue
                metric, _, rest = name.partition("[scenario=")
                label = rest[:-1]
                if metric == "perf.phase_ms" and ",phase=" in label:
                    sname, _, phase = label.partition(",phase=")
                    scen.setdefault(sname, {}).setdefault(
                        "phases_ms", {})[phase] = m["value"]
                else:
                    scen.setdefault(label, {})[
                        metric[len("perf."):]] = m["value"]
            perf["scenarios"] = scen
            # row-alike records from the gauges → the doctor's verdict
            recs = [{"kind": "bench.row", "scenario": sname,
                     "step_time_p50_ms": v.get("step_time_ms"),
                     "phases_ms": v.get("phases_ms") or {}}
                    for sname, v in scen.items()]
            try:
                from .doctor import check_perf_regression
                regressions = check_perf_regression({0: recs})
            except Exception:  # noqa: swallow — statusz must render
                regressions = []
            perf["perf_regression"] = ([
                {"scenario": f["data"].get("scenario"),
                 "dominant": f["data"].get("dominant"),
                 "title": f["title"]} for f in regressions] or None)
            # trend engine (ISSUE 14): per-scenario direction vs the
            # trailing median plus the last detected changepoint, from
            # the ledger series (step-time axis only — statusz is a
            # glance, the full report is `python -m paddle_tpu.bench
            # .trends` / bench.report)
            try:
                from ..bench import trends as bench_trends
                trend_info: Dict[str, Any] = {}
                for a in bench_trends.scan_ledger(
                        scenario_names=sorted(scen),
                        metrics=("step_p50",)):
                    cp = a.get("last_changepoint")
                    trend_info[f"{a['scenario']}/{a['mode']}"] = {
                        "trend": a.get("trend"),
                        "flakiness": a.get("flakiness"),
                        "last_changepoint": ({
                            "sha_range": cp.get("sha_range"),
                            "delta_frac": cp.get("delta_frac"),
                            "direction": cp.get("direction"),
                            "dominant_phase": cp.get("dominant_phase"),
                        } if cp else None),
                    }
                perf["trends"] = trend_info or None
            except Exception:  # noqa: swallow — statusz must render
                perf["trends"] = None
        status["perf"] = perf or None
        # MFU microscope (ISSUE 19): the bench runner mirrors each row's
        # roofline gap budget into `roofline.*` gauges — statusz shows
        # the per-scenario buckets, coverage, and the doctor's mfu_gap
        # verdict so a glance answers "where did the step time go"
        roofline: Dict[str, Any] = {}
        try:
            roof_scen: Dict[str, Dict[str, Any]] = {}
            for name, m in snap.items():
                if (not name.startswith("roofline.")
                        or m.get("type") != "gauge"
                        or "[scenario=" not in name):
                    continue
                metric, _, rest = name.partition("[scenario=")
                label = rest[:-1]
                if metric == "roofline.bucket_ms" and ",sink=" in label:
                    sname, _, sink = label.partition(",sink=")
                    roof_scen.setdefault(sname, {}).setdefault(
                        "buckets_ms", {})[sink] = m["value"]
                else:
                    roof_scen.setdefault(label, {})[
                        metric[len("roofline."):]] = m["value"]
            if roof_scen:
                roofline["scenarios"] = roof_scen
                # row-alikes from the gauges → the doctor's verdict
                # (measured = bucket sum: the budget's own invariant;
                # dominant = largest non-mxu bucket, same rule the
                # roofline block uses)
                recs = []
                for sname, v in roof_scen.items():
                    buckets = v.get("buckets_ms") or {}
                    if not buckets:
                        continue
                    gaps = {s: b for s, b in buckets.items()
                            if s != "mxu"}
                    dom = (max(gaps, key=lambda s: gaps[s])
                           if gaps and max(gaps.values()) > 0 else None)
                    recs.append({
                        "kind": "bench.row", "scenario": sname,
                        "roofline": {
                            "buckets_ms": buckets,
                            "measured_step_ms": sum(
                                float(b or 0.0)
                                for b in buckets.values()),
                            "dominant_sink": dom,
                            "coverage": v.get("coverage"),
                        }})
                try:
                    from .doctor import check_mfu_gap
                    verdicts = check_mfu_gap({0: recs})
                except Exception:  # noqa: swallow — statusz must render
                    verdicts = []
                roofline["mfu_gap"] = ([
                    {"scenario": f["data"].get("scenario"),
                     "dominant": f["data"].get("dominant"),
                     "share": f["data"].get("share"),
                     "injected": f["data"].get("injected"),
                     "title": f["title"]} for f in verdicts] or None)
        except Exception:  # noqa: swallow — statusz must render
            roofline = {}
        status["roofline"] = roofline or None
        # interconnect microscope (ISSUE 20): the bench runner mirrors
        # each row's per-collective comm sub-budget into
        # `interconnect.*` gauges — statusz shows the per-scenario
        # entries (op, axis, measured, efficiency-vs-modeled) and the
        # doctor's comm_budget verdict
        interconnect: Dict[str, Any] = {}
        try:
            ic_scen: Dict[str, Dict[str, Any]] = {}
            for name, m in snap.items():
                if (not name.startswith("interconnect.")
                        or m.get("type") != "gauge"
                        or "[scenario=" not in name):
                    continue
                metric, _, rest = name.partition("[scenario=")
                metric = metric[len("interconnect."):]
                label = rest[:-1]
                if "," in label:
                    sname, _, rest_lbl = label.partition(",")
                    labels = dict(p.partition("=")[::2]
                                  for p in rest_lbl.split(","))
                    entry = ic_scen.setdefault(sname, {}).setdefault(
                        "by_op", {}).setdefault(
                        (labels.get("op"), labels.get("axis")), {})
                    entry[metric] = m["value"]
                else:
                    ic_scen.setdefault(label, {})[metric] = m["value"]
            if ic_scen:
                scen_out: Dict[str, Any] = {}
                recs = []
                for sname, v in sorted(ic_scen.items()):
                    entries = []
                    for (op, axis), fields in sorted(
                            (v.get("by_op") or {}).items()):
                        entries.append({
                            "op": op,
                            "axis": None if axis in (None, "none") else axis,
                            "measured_ms": fields.get("entry_ms"),
                            "efficiency": fields.get("efficiency")})
                    if v.get("unattributed_ms") is not None:
                        entries.append({"op": "(unattributed)",
                                        "axis": None,
                                        "measured_ms": v["unattributed_ms"]})
                    scen_out[sname] = {
                        "comm_bucket_ms": v.get("comm_bucket_ms"),
                        "overlapped_ms": v.get("overlapped_ms"),
                        "unattributed_ms": v.get("unattributed_ms"),
                        "entries": entries,
                    }
                    recs.append({
                        "kind": "bench.row", "scenario": sname,
                        "roofline": {"measured_step_ms": gauge(
                            f"perf.step_time_ms[scenario={sname}]")},
                        "interconnect": {
                            "comm_bucket_ms": v.get("comm_bucket_ms"),
                            "overlapped_ms": v.get("overlapped_ms"),
                            "entries": entries}})
                interconnect["scenarios"] = scen_out
                try:
                    from .doctor import check_comm_budget
                    verdicts = check_comm_budget({0: recs})
                except Exception:  # noqa: swallow — statusz must render
                    verdicts = []
                interconnect["comm_budget"] = ([
                    {"scenario": f["data"].get("scenario"),
                     "op": f["data"].get("op"),
                     "axis": f["data"].get("axis"),
                     "efficiency": f["data"].get("efficiency"),
                     "share": f["data"].get("share"),
                     "title": f["title"]} for f in verdicts] or None)
        except Exception:  # noqa: swallow — statusz must render
            interconnect = {}
        status["interconnect"] = interconnect or None
        if sup is not None:
            if status["step"] is None:
                status["step"] = sup.gstep
            hb = sup.heartbeat
            status["heartbeat"] = {
                "beats": hb.beats,
                "last": hb._last_beat or None,
                "age_secs": (now - hb._last_beat) if hb.beats else None,
            }
            wd = sup.watchdog
            with wd._cond:
                armed = [e.label for e in wd._entries]
            status["watchdog"] = {"timeout_secs": wd.timeout,
                                  "timeouts": wd.timeouts,
                                  "armed": armed,
                                  "closed": wd._closed}
            status["supervisor"] = {
                "running": sup._running,
                "last_action": sup.last_action,
                "pending_rollback": sup.pending_rollback,
                "rollbacks_used": sup.rollback.used,
                "bad_batches": sup.guard.total_bad,
                "consecutive_step_failures":
                    sup.consecutive_step_failures,
            }
            fr = getattr(sup, "flight", None)
            if fr is not None:
                status["flight"] = {"records": fr.seen,
                                    "capacity": fr.capacity,
                                    "dumps": fr.dumps}
        try:
            from .memory import get_sampler
            status["memory"] = get_sampler().last_table or None
        except Exception:  # noqa: swallow
            status["memory"] = None
        try:
            from .compilation import get_tracker
            tr = get_tracker()
            status["compile"] = {fn: tr.stats(fn)
                                 for fn in tr.functions()} or None
        except Exception:  # noqa: swallow
            status["compile"] = None
        return status

    # -- plumbing ----------------------------------------------------------
    def start(self) -> "StatusServer":
        if self._httpd is not None:
            return self
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: vlog, not stderr
                vlog(2, "monitor: %s", fmt % args)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                server.requests += 1
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(200,
                                   server.render_metrics().encode("utf-8"),
                                   "text/plain; version=0.0.4")
                    elif path == "/statusz":
                        self._send(200, json.dumps(
                            server.statusz(), indent=1,
                            default=str).encode("utf-8"))
                    elif path in ("/healthz", "/"):
                        code, state = server.healthz()
                        self._send(code, json.dumps(
                            {"ok": code == 200,
                             "state": state}).encode("utf-8"))
                    else:
                        self._send(404, b'{"error": "not found"}')
                except Exception as e:  # a broken page must not kill serving
                    try:
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode("utf-8"))
                    except OSError:  # noqa: swallow
                        pass  # client hung up mid-error

        self._httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="ptpu-status-server",
                                        daemon=True)
        self._thread.start()
        vlog(0, "monitor: status server on %s:%d (/metrics /statusz "
             "/healthz)", self.host, self.port)
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "StatusServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def maybe_start_server(supervisor=None, worker_id: Optional[int] = None,
                       registry=None) -> Optional[StatusServer]:
    """Start a :class:`StatusServer` when ``PTPU_MONITOR_PORT`` is set.

    A nonzero base port is offset by the worker rank (worker 3 of a
    localhost simulation serves on base+3); 0 requests an ephemeral
    port per worker.  Returns None when the knob is unset or the bind
    fails — monitoring must never take the run down with it."""
    raw = os.environ.get(MONITOR_PORT_ENV)
    if raw is None or raw.strip() == "":
        return None
    try:
        base = int(raw)
    except ValueError:
        vlog(0, "monitor: bad %s=%r — not starting a status server",
             MONITOR_PORT_ENV, raw)
        return None
    wid = int(worker_id or 0)
    port = base + wid if base > 0 else 0
    try:
        return StatusServer(port=port, registry=registry,
                            supervisor=supervisor,
                            worker_id=wid).start()
    except OSError as e:
        vlog(0, "monitor: cannot bind status server on port %d: %s",
             port, e)
        return None


# ---------------------------------------------------------------------------
# in-flight cross-worker aggregation
# ---------------------------------------------------------------------------
class LiveAggregator:
    """Re-runs the doctor's rule functions over a sliding window of the
    still-growing worker streams (ISSUE 5).

    Each :meth:`poll` tail-reads every ``worker-*.jsonl`` under
    ``<run_dir>/metrics`` (new files are picked up as workers appear),
    appends the records to a bounded per-worker window, evaluates the
    retrace-storm / HBM / straggler / data-starvation rules on the
    window, rewrites ``<run_dir>/live_status.json`` atomically, and —
    for every verdict *not seen before* — records a ``monitor.alert``
    event (through ``report``, typically the launcher's
    ``SupervisorReport``, whose metrics mirror puts it on the shared
    timeline).  Use ``start()`` for the background-thread form the
    launcher babysitter runs, or call ``poll()`` from your own loop.
    """

    def __init__(self, run_dir: str, interval: Optional[float] = None,
                 window: int = 512, report=None, registry=None,
                 clock=time.time):
        self.run_dir = run_dir
        self.interval = (default_monitor_interval() if interval is None
                         else float(interval))
        self.window = int(window)
        self.report = report
        self._registry = registry
        self._clock = clock
        # poll() runs on both the background thread (_run) and the main
        # thread (stop()'s final sweep, or a caller's own loop); all
        # window/alert state is shared and guarded.  An RLock so the
        # helpers can self-acquire under a poll() that already holds it.
        self._poll_lock = threading.RLock()
        self._tails: Dict[int, StreamTail] = {}      # guarded_by: _poll_lock
        self._windows: Dict[int, deque] = {}         # guarded_by: _poll_lock
        self._alerted: set = set()                   # guarded_by: _poll_lock
        self.alerts: List[Dict[str, Any]] = []       # guarded_by: _poll_lock
        self.polls = 0                               # guarded_by: _poll_lock
        self._last_poll = 0.0                        # guarded_by: _poll_lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from .registry import get_registry
        return get_registry()

    # -- discovery + tailing -----------------------------------------------
    def _discover(self) -> None:
        mdir = metrics_dir(self.run_dir)
        if not os.path.isdir(mdir):
            return
        with self._poll_lock:
            for name in sorted(os.listdir(mdir)):
                m = _WORKER_RE.match(name)
                if m and int(m.group(1)) not in self._tails:
                    wid = int(m.group(1))
                    self._tails[wid] = StreamTail(os.path.join(mdir, name))
                    self._windows[wid] = deque(maxlen=self.window)

    def _ingest(self) -> int:
        self._discover()
        fresh = 0
        with self._poll_lock:
            for wid, tail in self._tails.items():
                recs = tail.poll()
                if recs:
                    self._windows[wid].extend(recs)
                    fresh += len(recs)
        return fresh

    # -- rules over the window ---------------------------------------------
    def _evaluate(self) -> List[Dict[str, Any]]:
        from . import doctor
        with self._poll_lock:
            workers = {wid: list(w)
                       for wid, w in self._windows.items() if w}
        if not workers:
            return []
        findings: List[Dict[str, Any]] = []
        findings += doctor.check_memory(workers)
        findings += doctor.check_compilation(workers)
        findings += doctor.check_straggler(workers)
        findings += doctor.check_data_starved(workers)
        findings += doctor.check_comm_bound(workers)
        findings += doctor.check_perf_regression(workers)
        findings += doctor.check_perf_trend(workers)
        findings += doctor.check_serving(workers)
        findings += doctor.check_fleet(workers)
        findings += doctor.check_fleet_flapping(workers)
        findings += doctor.check_fleet_slo_burn(workers)
        findings += doctor.check_tail_latency(workers)
        findings += doctor.check_mfu_gap(workers)
        findings += doctor.check_comm_budget(workers)
        findings.sort(key=lambda f: (-f["severity"], f["kind"]))
        return findings

    @staticmethod
    def _alert_key(finding: Dict[str, Any]) -> tuple:
        data = finding.get("data") or {}
        return (finding["kind"], data.get("function"), data.get("device"),
                data.get("worker"), data.get("scenario"))

    def _raise_alerts(self, findings: List[Dict[str, Any]]) -> None:
        for f in findings:
            key = self._alert_key(f)
            with self._poll_lock:
                if key in self._alerted:
                    continue
                self._alerted.add(key)
                alert = {"kind": f["kind"], "severity": f["severity"],
                         "title": f["title"], "evidence": f["evidence"],
                         "first_seen": float(self._clock())}
                self.alerts.append(alert)
            vlog(0, "monitor: ALERT [%d] %s: %s", f["severity"],
                 f["kind"], f["title"])
            reg = self._reg()
            reg.counter("monitor.alerts").inc()
            reg.emit("monitor.alert", verdict=f["kind"],
                     severity=f["severity"], title=f["title"])
            if self.report is not None:
                try:
                    self.report.record("monitor.alert", verdict=f["kind"],
                                       severity=f["severity"],
                                       title=f["title"],
                                       evidence=f["evidence"])
                except Exception as e:  # alerting is best-effort
                    vlog(1, "monitor: alert record failed: %r", e)

    # -- the poll ----------------------------------------------------------
    def poll(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """One tail-read + rule pass; throttled to ``interval`` unless
        ``force``.  Returns the status dict written to
        ``live_status.json`` (None when throttled)."""
        with self._poll_lock:
            now = float(self._clock())
            if not force and now - self._last_poll < self.interval:
                return None
            self._last_poll = now
            self.polls += 1
        self._ingest()
        findings = self._evaluate()
        self._raise_alerts(findings)
        status = self._status(now, findings)
        try:
            fsio.atomic_write_bytes(
                live_status_path(self.run_dir),
                json.dumps(status, indent=1,
                           default=str).encode("utf-8"))
        except OSError as e:
            vlog(1, "monitor: live_status.json write failed: %s", e)
        return status

    def _status(self, now: float,
                findings: List[Dict[str, Any]]) -> Dict[str, Any]:
        with self._poll_lock:
            last_step: Dict[str, Any] = {}
            records_seen: Dict[str, int] = {}
            for wid, window in self._windows.items():
                steps = [r.get("step") for r in window
                         if r.get("kind") == "step"
                         and r.get("step") is not None]
                last_step[str(wid)] = steps[-1] if steps else None
                records_seen[str(wid)] = len(window)
            drops: Dict[str, int] = {}
            for tail in self._tails.values():
                for k, v in tail.drops.items():
                    drops[k] = drops.get(k, 0) + v
            workers = {wid: list(w) for wid, w in self._windows.items() if w}
            return {
                "ts": now,
                "run_dir": os.path.abspath(self.run_dir),
                "polls": self.polls,
                "workers": sorted(self._tails),
                "last_step": last_step,
                "window_records": records_seen,
                "dropped": drops,
                "healthy": not findings,
                "findings": findings,
                # snapshot: the caller serializes this dict after the
                # lock is released, while alerts may keep growing
                "alerts": list(self.alerts),
                "straggler": straggler_stats(workers) if len(workers) > 1
                else None,
            }

    # -- background-thread form --------------------------------------------
    def start(self) -> "LiveAggregator":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ptpu-live-aggregator", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll(force=True)
            except Exception as e:  # the babysitter must outlive its rules
                vlog(0, "monitor: live aggregation pass failed: %r", e)

    def stop(self) -> None:
        """Stop the thread and run one final forced poll, so the status
        file reflects the stream tails at teardown."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            self.poll(force=True)
        except Exception as e:  # noqa: swallow
            vlog(1, "monitor: final poll failed: %r", e)
