"""Cross-worker metrics aggregation (ISSUE 3).

Each worker of a run streams ``<run_dir>/metrics/worker-<i>.jsonl``; the
launcher (or anyone, via ``python -m paddle_tpu.observability.aggregate
<run_dir>``) merges them into ``<run_dir>/metrics/summary.json``: per-
worker and run-wide step-time percentiles, token totals, mean/max MFU,
and an event census (how many of each record kind, including the
supervisor events sharing the timeline) — the one file a dashboard or a
post-mortem reads first.

Torn trailing lines (a worker died mid-append) are skipped, not fatal:
the stream is JSONL precisely so a partial write costs one record.
"""
from __future__ import annotations

import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

from ..framework.log import vlog
from ..utils import fsio
from .sinks import metrics_dir

__all__ = ["read_worker_stream", "StreamTail", "aggregate_run",
           "straggler_stats", "export_chrome_trace",
           "SCHEMA_VERSION", "KNOWN_SCHEMA_VERSIONS"]

_WORKER_RE = re.compile(r"^worker-(\d+)\.jsonl$")

# version of the record/summary schema this reader understands.  Records
# carry no schema_version (= v1) or one the reader knows; anything newer
# is skipped with drop accounting so old tooling stays usable against
# new runs (and vice versa) instead of mis-parsing them.
SCHEMA_VERSION = 1
KNOWN_SCHEMA_VERSIONS = (1,)


def _parse_stream_lines(text: str, drops: Dict[str, int]
                        ) -> List[Dict[str, Any]]:
    """The shared drop-tolerant JSONL line parser: torn/garbled lines and
    foreign ``schema_version`` records are skipped with accounting."""
    records: List[Dict[str, Any]] = []
    drops.setdefault("torn_lines", 0)
    drops.setdefault("unknown_schema", 0)
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            drops["torn_lines"] += 1
            continue  # torn tail from a mid-append death
        if not isinstance(rec, dict):
            drops["torn_lines"] += 1
            continue
        if rec.get("schema_version",
                   SCHEMA_VERSION) not in KNOWN_SCHEMA_VERSIONS:
            drops["unknown_schema"] += 1
            continue
        records.append(rec)
    return records


def read_worker_stream(path: str,
                       drops: Optional[Dict[str, int]] = None
                       ) -> List[Dict[str, Any]]:
    """Parse one worker JSONL file, skipping torn/garbled lines and
    records from a schema this reader doesn't know.

    ``drops``, when given, accumulates the loss accounting:
    ``torn_lines`` (unparseable — a mid-append death) and
    ``unknown_schema`` (valid JSON, foreign ``schema_version``)."""
    if drops is None:
        drops = {}
    drops.setdefault("torn_lines", 0)
    drops.setdefault("unknown_schema", 0)
    try:
        raw = fsio.read_bytes(path)
    except OSError:
        return []
    return _parse_stream_lines(raw.decode("utf-8", errors="replace"),
                               drops)


class StreamTail:
    """Incremental reader of one still-growing worker JSONL stream
    (ISSUE 5: the live monitor's view).

    Unlike :func:`read_worker_stream` this keeps a byte offset and only
    parses bytes appended since the last :meth:`poll` — and it never
    consumes past the last newline, so a line the writer is mid-append
    on is read complete on the NEXT poll instead of counting as torn.
    A shrunken file (rotation/truncation) resets the offset and rereads.
    """

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.drops: Dict[str, int] = {"torn_lines": 0,
                                      "unknown_schema": 0}

    def poll(self) -> List[Dict[str, Any]]:
        """Records appended since the previous poll (possibly empty)."""
        try:
            with open(self.path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < self.offset:   # truncated/rotated under us
                    self.offset = 0
                if size == self.offset:
                    return []
                f.seek(self.offset)
                chunk = f.read(size - self.offset)
        except OSError:
            return []
        # stop at the last complete line; a partial tail is not torn,
        # just not finished yet
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        self.offset += end + 1
        return _parse_stream_lines(
            chunk[:end].decode("utf-8", errors="replace"), self.drops)


def _pct(sorted_vals: List[float], p: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _step_stats(steps: List[Dict[str, Any]]) -> Dict[str, Any]:
    times = sorted(float(s["step_time_ms"]) for s in steps
                   if s.get("step_time_ms") is not None)
    mfus = [float(s["mfu"]) for s in steps if s.get("mfu") is not None]
    toks = [float(s["tokens"]) for s in steps
            if s.get("tokens") is not None]
    tps = [float(s["tokens_per_sec"]) for s in steps
           if s.get("tokens_per_sec") is not None]
    out: Dict[str, Any] = {"steps": len(steps)}
    if times:
        out["step_time_ms"] = {
            "mean": sum(times) / len(times), "min": times[0],
            "max": times[-1], "p50": _pct(times, 50),
            "p90": _pct(times, 90), "p99": _pct(times, 99)}
    if toks:
        out["total_tokens"] = sum(toks)
    if tps:
        out["tokens_per_sec_mean"] = sum(tps) / len(tps)
    if mfus:
        out["mfu"] = {"mean": sum(mfus) / len(mfus), "max": max(mfus),
                      "last": mfus[-1]}
    return out


def straggler_stats(workers: Dict[int, List[Dict[str, Any]]]
                    ) -> Optional[Dict[str, Any]]:
    """Cross-worker skew analysis (ISSUE 4).

    Aligns each worker's ``step`` records by step index and, for every
    step at least two workers reported, measures the **spread** — the
    slowest minus the fastest worker's ``step_time_ms``.  Returns
    ``p50``/``p99`` of the spread (absolute and relative to the median
    step time), plus the attribution: which worker was slowest how
    often, and each worker's mean step time.  ``None`` for runs with no
    alignable steps (single worker, or no step records)."""
    per_step: Dict[Any, Dict[int, float]] = {}
    for wid, records in workers.items():
        for r in records:
            if r.get("kind") != "step" or r.get("step") is None \
                    or r.get("step_time_ms") is None:
                continue
            # a worker that rolled back revisits a step; keep the last
            per_step.setdefault(r["step"], {})[wid] = float(
                r["step_time_ms"])
    aligned = {s: times for s, times in per_step.items()
               if len(times) >= 2}
    if not aligned:
        return None
    spreads, all_times = [], []
    slowest_count: Dict[int, int] = {}
    for _s, times in sorted(aligned.items(), key=lambda kv: str(kv[0])):
        vals = sorted(times.values())
        spreads.append(vals[-1] - vals[0])
        all_times.extend(vals)
        worst = max(times, key=lambda w: times[w])
        slowest_count[worst] = slowest_count.get(worst, 0) + 1
    spreads.sort()
    all_times.sort()
    median_step = _pct(all_times, 50) or 0.0
    p50, p99 = _pct(spreads, 50), _pct(spreads, 99)
    worker_means = {
        str(wid): (sum(t for times in aligned.values()
                       if wid in times for t in [times[wid]])
                   / max(1, sum(1 for times in aligned.values()
                                if wid in times)))
        for wid in workers}
    straggler = max(slowest_count, key=lambda w: slowest_count[w])
    return {
        "aligned_steps": len(aligned),
        "spread_ms": {"p50": p50, "p99": p99, "max": spreads[-1]},
        "relative_spread": {
            "p50": (p50 / median_step) if median_step else None,
            "p99": (p99 / median_step) if median_step else None},
        "median_step_ms": median_step,
        "slowest_counts": {str(w): c
                           for w, c in sorted(slowest_count.items())},
        "worker_mean_step_ms": worker_means,
        "straggler": straggler,
        "straggler_fraction": slowest_count[straggler] / len(aligned),
    }


def aggregate_run(run_dir: str,
                  out_path: Optional[str] = None) -> Optional[dict]:
    """Merge every ``worker-*.jsonl`` under ``<run_dir>/metrics`` into
    ``summary.json`` (atomic write through fsio).  Returns the summary
    dict, or None when the run produced no metrics at all."""
    mdir = metrics_dir(run_dir)
    if not os.path.isdir(mdir):
        return None
    workers: Dict[int, List[Dict[str, Any]]] = {}
    drops: Dict[str, int] = {}
    for name in sorted(os.listdir(mdir)):
        m = _WORKER_RE.match(name)
        if not m:
            continue
        workers[int(m.group(1))] = read_worker_stream(
            os.path.join(mdir, name), drops=drops)
    if not workers:
        return None

    all_records: List[Dict[str, Any]] = []
    per_worker: Dict[str, Any] = {}
    for wid, records in sorted(workers.items()):
        all_records.extend(records)
        steps = [r for r in records if r.get("kind") == "step"]
        kinds: Dict[str, int] = {}
        for r in records:
            k = str(r.get("kind"))
            kinds[k] = kinds.get(k, 0) + 1
        per_worker[str(wid)] = {"records": len(records),
                                "kinds": kinds,
                                **_step_stats(steps)}

    kinds_total: Dict[str, int] = {}
    for r in all_records:
        k = str(r.get("kind"))
        kinds_total[k] = kinds_total.get(k, 0) + 1
    ts = [float(r["ts"]) for r in all_records if "ts" in r]
    summary = {
        "schema_version": SCHEMA_VERSION,
        "run_dir": os.path.abspath(run_dir),
        "workers": sorted(workers),
        "records": len(all_records),
        "dropped": drops,
        "kinds": dict(sorted(kinds_total.items())),
        "supervisor_events": {k: v for k, v in sorted(kinds_total.items())
                              if k.startswith("supervisor.")},
        "time_range": ([min(ts), max(ts)] if ts else None),
        "overall": _step_stats(
            [r for r in all_records if r.get("kind") == "step"]),
        "per_worker": per_worker,
        "straggler": straggler_stats(workers),
    }
    out_path = out_path or os.path.join(mdir, "summary.json")
    fsio.atomic_write_bytes(
        out_path, json.dumps(summary, indent=1, default=str,
                             sort_keys=False).encode("utf-8"))
    vlog(1, "observability: aggregated %d workers → %s", len(workers),
         out_path)
    return summary


def _read_all_workers(run_dir: str
                      ) -> Dict[int, List[Dict[str, Any]]]:
    mdir = metrics_dir(run_dir)
    workers: Dict[int, List[Dict[str, Any]]] = {}
    if not os.path.isdir(mdir):
        return workers
    for name in sorted(os.listdir(mdir)):
        m = _WORKER_RE.match(name)
        if m:
            workers[int(m.group(1))] = read_worker_stream(
                os.path.join(mdir, name))
    return workers


def export_chrome_trace(run_dir: str,
                        out_path: Optional[str] = None) -> Optional[int]:
    """Merge every worker stream into ONE multi-process Chrome/Perfetto
    timeline (ISSUE 18 satellite).

    The per-process ``tracing.export_chrome_trace`` stamps everything
    with its own ``os.getpid()``, so naively concatenating worker
    streams collapses all processes onto whatever pid the reader runs
    as.  Here each ``worker-<i>.jsonl`` stream gets its own pid = i,
    announced with a ``process_name`` metadata event (label taken from
    the stream's own ``trace.span`` ``proc`` field — ``router`` for
    worker-0, ``replica-<k>`` for engine workers — falling back to
    ``worker-<i>``), and tracks within a process get ``thread_name``
    metadata: one track per request, plus a shared ``decode`` track for
    batch-level decode spans and a ``steps`` track for train/serve step
    records.  Timestamps are wall-clock µs, matching
    :func:`..requesttrace.chrome_trace_events`, so the two exports line
    up when opened together.

    Writes ``<run_dir>/metrics/trace.json`` unless ``out_path`` is
    given; returns the event count, or None when the run has no
    metrics."""
    workers = _read_all_workers(run_dir)
    if not workers:
        return None
    events: List[Dict[str, Any]] = []
    for wid, records in sorted(workers.items()):
        pid = wid
        proc = next((str(r["proc"]) for r in records
                     if str(r.get("kind", "")).startswith("trace.")
                     and r.get("proc")), None)
        label = proc or f"worker-{wid}"
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        tids: Dict[str, int] = {}

        def track(name: str, pid=pid, tids=tids) -> int:
            if name not in tids:
                tids[name] = len(tids) + 1
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": tids[name],
                               "args": {"name": name}})
            return tids[name]

        for r in records:
            kind = r.get("kind")
            if kind == "trace.span":
                t0, dur = r.get("t0"), r.get("dur_ms")
                if t0 is None or dur is None:
                    continue
                if r.get("requests") is not None:   # batch decode span
                    tname = "decode"
                else:
                    tname = str(r.get("request_id")
                                or r.get("trace_id") or "spans")
                events.append({
                    "name": str(r.get("name")), "ph": "X",
                    "cat": str(r.get("component") or "span"),
                    "pid": pid, "tid": track(tname),
                    "ts": float(t0) * 1e6, "dur": float(dur) * 1e3,
                    "args": {k: r[k] for k in
                             ("trace_id", "component", "residents")
                             if r.get(k) is not None}})
            elif kind == "step" and r.get("ts") is not None \
                    and r.get("step_time_ms") is not None:
                dur = float(r["step_time_ms"])
                events.append({
                    "name": f"step {r.get('step', '?')}", "ph": "X",
                    "cat": "step", "pid": pid, "tid": track("steps"),
                    "ts": (float(r["ts"]) - dur / 1e3) * 1e6,
                    "dur": dur * 1e3,
                    "args": {k: r[k] for k in ("step", "tokens", "mfu")
                             if r.get(k) is not None}})
    out_path = out_path or os.path.join(metrics_dir(run_dir),
                                        "trace.json")
    fsio.atomic_write_bytes(
        out_path, json.dumps({"traceEvents": events,
                              "displayTimeUnit": "ms"}).encode("utf-8"))
    vlog(1, "observability: chrome trace %d events → %s", len(events),
         out_path)
    return len(events)


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    chrome = None
    if "--chrome" in args:
        i = args.index("--chrome")
        try:
            chrome = args[i + 1]
        except IndexError:
            chrome = ""
        del args[i:i + 2]
    if len(args) != 1:
        print("usage: python -m paddle_tpu.observability.aggregate "  # noqa: print
              "<run_dir> [--chrome out.json]", file=sys.stderr)
        return 2
    summary = aggregate_run(args[0])
    if summary is None:
        print(f"no metrics under {args[0]}", file=sys.stderr)  # noqa: print
        return 1
    if chrome is not None:
        n = export_chrome_trace(args[0], chrome or None)
        summary["chrome_trace_events"] = n
    print(json.dumps(summary, indent=1, default=str))  # noqa: print
    return 0


if __name__ == "__main__":
    sys.exit(main())
