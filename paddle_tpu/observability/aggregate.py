"""Cross-worker metrics aggregation (ISSUE 3).

Each worker of a run streams ``<run_dir>/metrics/worker-<i>.jsonl``; the
launcher (or anyone, via ``python -m paddle_tpu.observability.aggregate
<run_dir>``) merges them into ``<run_dir>/metrics/summary.json``: per-
worker and run-wide step-time percentiles, token totals, mean/max MFU,
and an event census (how many of each record kind, including the
supervisor events sharing the timeline) — the one file a dashboard or a
post-mortem reads first.

Torn trailing lines (a worker died mid-append) are skipped, not fatal:
the stream is JSONL precisely so a partial write costs one record.
"""
from __future__ import annotations

import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

from ..framework.log import vlog
from ..utils import fsio
from .sinks import metrics_dir

__all__ = ["read_worker_stream", "aggregate_run"]

_WORKER_RE = re.compile(r"^worker-(\d+)\.jsonl$")


def read_worker_stream(path: str) -> List[Dict[str, Any]]:
    """Parse one worker JSONL file, skipping torn/garbled lines."""
    records = []
    try:
        raw = fsio.read_bytes(path)
    except OSError:
        return records
    for line in raw.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail from a mid-append death
        if isinstance(rec, dict):
            records.append(rec)
    return records


def _pct(sorted_vals: List[float], p: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _step_stats(steps: List[Dict[str, Any]]) -> Dict[str, Any]:
    times = sorted(float(s["step_time_ms"]) for s in steps
                   if s.get("step_time_ms") is not None)
    mfus = [float(s["mfu"]) for s in steps if s.get("mfu") is not None]
    toks = [float(s["tokens"]) for s in steps
            if s.get("tokens") is not None]
    tps = [float(s["tokens_per_sec"]) for s in steps
           if s.get("tokens_per_sec") is not None]
    out: Dict[str, Any] = {"steps": len(steps)}
    if times:
        out["step_time_ms"] = {
            "mean": sum(times) / len(times), "min": times[0],
            "max": times[-1], "p50": _pct(times, 50),
            "p90": _pct(times, 90), "p99": _pct(times, 99)}
    if toks:
        out["total_tokens"] = sum(toks)
    if tps:
        out["tokens_per_sec_mean"] = sum(tps) / len(tps)
    if mfus:
        out["mfu"] = {"mean": sum(mfus) / len(mfus), "max": max(mfus),
                      "last": mfus[-1]}
    return out


def aggregate_run(run_dir: str,
                  out_path: Optional[str] = None) -> Optional[dict]:
    """Merge every ``worker-*.jsonl`` under ``<run_dir>/metrics`` into
    ``summary.json`` (atomic write through fsio).  Returns the summary
    dict, or None when the run produced no metrics at all."""
    mdir = metrics_dir(run_dir)
    if not os.path.isdir(mdir):
        return None
    workers: Dict[int, List[Dict[str, Any]]] = {}
    for name in sorted(os.listdir(mdir)):
        m = _WORKER_RE.match(name)
        if not m:
            continue
        workers[int(m.group(1))] = read_worker_stream(
            os.path.join(mdir, name))
    if not workers:
        return None

    all_records: List[Dict[str, Any]] = []
    per_worker: Dict[str, Any] = {}
    for wid, records in sorted(workers.items()):
        all_records.extend(records)
        steps = [r for r in records if r.get("kind") == "step"]
        kinds: Dict[str, int] = {}
        for r in records:
            k = str(r.get("kind"))
            kinds[k] = kinds.get(k, 0) + 1
        per_worker[str(wid)] = {"records": len(records),
                                "kinds": kinds,
                                **_step_stats(steps)}

    kinds_total: Dict[str, int] = {}
    for r in all_records:
        k = str(r.get("kind"))
        kinds_total[k] = kinds_total.get(k, 0) + 1
    ts = [float(r["ts"]) for r in all_records if "ts" in r]
    summary = {
        "run_dir": os.path.abspath(run_dir),
        "workers": sorted(workers),
        "records": len(all_records),
        "kinds": dict(sorted(kinds_total.items())),
        "supervisor_events": {k: v for k, v in sorted(kinds_total.items())
                              if k.startswith("supervisor.")},
        "time_range": ([min(ts), max(ts)] if ts else None),
        "overall": _step_stats(
            [r for r in all_records if r.get("kind") == "step"]),
        "per_worker": per_worker,
    }
    out_path = out_path or os.path.join(mdir, "summary.json")
    fsio.atomic_write_bytes(
        out_path, json.dumps(summary, indent=1, default=str,
                             sort_keys=False).encode("utf-8"))
    vlog(1, "observability: aggregated %d workers → %s", len(workers),
         out_path)
    return summary


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) != 1:
        print("usage: python -m paddle_tpu.observability.aggregate "  # noqa: print
              "<run_dir>", file=sys.stderr)
        return 2
    summary = aggregate_run(args[0])
    if summary is None:
        print(f"no metrics under {args[0]}", file=sys.stderr)  # noqa: print
        return 1
    print(json.dumps(summary, indent=1, default=str))  # noqa: print
    return 0


if __name__ == "__main__":
    sys.exit(main())
