"""Interconnect microscope (ISSUE 20) — per-collective wire-time
attribution of the roofline's ``comm`` sink.

PR 19's MFU microscope reconciles the achieved-vs-peak gap but folds
every collective into one ``comm`` lump.  This module is the comm-side
sibling: a per-``device_kind`` ICI spec table (aggregate link Gbps,
link count, torus topology) plus an algorithm-aware cost model per
collective that turns each observed collective's payload bytes,
participant count, and mesh axis into a modeled wire time, then
reconciles modeled vs measured per (op, axis) into an efficiency table
and a **per-collective sub-budget** of the roofline's ``comm`` bucket.

Cost model (ring schedules on a torus; ``n`` = participants):

==================  =====================================================
collective          wire bytes shipped per device / payload
==================  =====================================================
``all_reduce``      ``2(n-1)/n``  (reduce-scatter + all-gather ring)
``reduce_scatter``  ``(n-1)/n``
``all_gather``      ``(n-1)/n``
``broadcast``       ``(n-1)/n``   (masked-psum lowering)
``all_to_all``      ``(n-1)/n × max(1, n/4)``  (bisection penalty — a
                    2D torus bisects at ~n/4 links, so large fan-outs
                    serialize on the cut)
``ppermute``/p2p    ``1``         (every byte crosses once)
``split``/barrier   ``0``         (no payload on the wire)
==================  =====================================================

Modeled wire time = payload × factor / ring bandwidth, where ring
bandwidth is two links' worth (a bidirectional ring uses both
neighbors) at ``ici_gbps / links`` per link.

Sub-budget doctrine (mirrors the roofline's ``residual``): entries
carry the RAW measured per-step milliseconds from the
``collective.<op>.ms[axis=..]`` histogram deltas, and an explicit
``"(unattributed)"`` entry equals ``comm_bucket − Σ attributed`` —
signed, so nested collectives (``reduce`` calls ``all_reduce``) or
trace-time-only observations never silently break the invariant that
**entries sum to the roofline comm bucket exactly, by construction**.
Unknown device kinds degrade honestly: measured attribution still
happens, but ``modeled_ms``/``efficiency`` come back None rather than
pretending nominal ICI figures describe the hardware.

Exposed vs overlapped: the roofline's compiled-HLO op table (split by
collective opcode, with ``replica_groups`` participant counts) gives an
HLO-side modeled comm time; the measured collective phase is the
*exposed* part, and ``max(0, hlo_modeled − exposed)`` estimates what
XLA's schedule overlapped behind compute.

Knobs: ``PTPU_INTERCONNECT_TEST_INFLATE=<op>:<axis>:<frac>`` — the
synthetic drill (per-collective sibling of
``PTPU_ROOFLINE_TEST_INFLATE``): claim ``frac`` of the comm bucket for
the named (op, axis), rescale the other attributed entries, and mark
the block ``injected``; CI uses it to prove the doctor names exactly
the injected collective op + axis.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = ["ICI_SPECS", "INFLATE_ENV", "ici_spec", "wire_factor",
           "modeled_wire_time_ms", "build_block", "degraded_block",
           "attributed_total_ms", "unattributed_ms"]

# Per-chip ICI specs by TPU generation (public datasheet figures):
# aggregate inter-chip interconnect bandwidth in Gbps across all links,
# the link count, and the torus the links form.  Per-link GB/s falls
# out as ici_gbps / links / 8.
ICI_SPECS = {
    "v2":  {"ici_gbps": 496.0,  "links": 4, "topology": "2d_torus"},
    "v3":  {"ici_gbps": 656.0,  "links": 4, "topology": "2d_torus"},
    "v4":  {"ici_gbps": 2400.0, "links": 6, "topology": "3d_torus"},
    "v5e": {"ici_gbps": 1600.0, "links": 4, "topology": "2d_torus"},
    "v5p": {"ici_gbps": 4800.0, "links": 6, "topology": "3d_torus"},
    "v6e": {"ici_gbps": 3584.0, "links": 4, "topology": "2d_torus"},
}

# mirrors observability.mfu._NOMINAL_GEN: the figure used when the
# device kind is unknown, so the math always produces a number — but
# build_block refuses to *trust* it (modeled_ms=None when known=False)
_NOMINAL_GEN = "v5e"

INFLATE_ENV = "PTPU_INTERCONNECT_TEST_INFLATE"

# the explicit remainder entry's op name (never a real collective)
UNATTRIBUTED = "(unattributed)"

# HLO collective opcode → the python-surface op name the cost model
# keys on (ragged all-to-all shares all_to_all's bisection penalty)
HLO_OPCODE_OPS = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "ragged-all-to-all": "all_to_all",
    "collective-permute": "send_recv_permute",
    "collective-broadcast": "broadcast",
}


def ici_spec(device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Resolve a device kind to its ICI spec — the comm-side mirror of
    :func:`~paddle_tpu.observability.mfu.device_spec`, same lookup
    doctrine: substring match on the kind, ``PALLAS_AXON_TPU_GEN``
    override, and an honest ``known=False`` with nominal figures for
    CPU dev boxes / future generations."""
    if device_kind is None:
        import jax
        device_kind = getattr(jax.devices()[0], "device_kind", "")
    kind = (device_kind or "").lower()
    for gen, spec in ICI_SPECS.items():
        if gen in kind:
            return {"device_kind": device_kind, "gen": gen, "known": True,
                    **spec}
    env_gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if env_gen in ICI_SPECS:
        return {"device_kind": device_kind, "gen": env_gen, "known": True,
                **ICI_SPECS[env_gen]}
    return {"device_kind": device_kind, "gen": None, "known": False,
            **ICI_SPECS[_NOMINAL_GEN]}


def wire_factor(op: str, participants: Any) -> float:
    """Wire bytes shipped per device as a multiple of the payload size
    for one ``op`` over ``participants`` ranks (the module-docstring
    table).  Single-rank groups (or unknown sizes) ship nothing."""
    try:
        n = int(participants or 0)
    except (TypeError, ValueError):
        n = 0
    if n <= 1:
        return 0.0
    base = str(op).replace("-", "_")
    if base in ("all_reduce", "sync_gradients"):
        return 2.0 * (n - 1) / n
    if base in ("all_gather", "reduce_scatter", "broadcast", "reduce",
                "scatter", "collective_broadcast"):
        return (n - 1) / n
    if base in ("all_to_all", "ragged_all_to_all"):
        return ((n - 1) / n) * max(1.0, n / 4.0)
    if base in ("send_recv_permute", "p2p_push", "collective_permute",
                "ppermute"):
        return 1.0
    if base in ("split", "barrier"):
        return 0.0
    # unknown collective: assume every payload byte crosses once rather
    # than silently modeling it free
    return 1.0


def modeled_wire_time_ms(op: str, payload_bytes: Any, participants: Any,
                         spec: Dict[str, Any]) -> float:
    """Best-case wire time (ms) for one collective call: wire bytes at
    the bidirectional-ring bandwidth (two links at ``ici_gbps/links``
    per link).  Callers must gate on ``spec["known"]`` before treating
    this as an attribution — on unknown kinds it is nominal math."""
    factor = wire_factor(op, participants)
    try:
        payload = float(payload_bytes or 0.0)
    except (TypeError, ValueError):
        payload = 0.0
    if factor <= 0.0 or payload <= 0.0:
        return 0.0
    links = max(1, int(spec.get("links") or 1))
    link_bytes_per_s = float(spec.get("ici_gbps") or 0.0) / links / 8.0 * 1e9
    ring_bytes_per_s = 2.0 * link_bytes_per_s
    if ring_bytes_per_s <= 0.0:
        return 0.0
    return payload * factor / ring_bytes_per_s * 1e3


# --------------------------------------------------------------------------
# sub-budget assembly
# --------------------------------------------------------------------------

def _apply_inflation(entries: List[Dict[str, Any]],
                     comm_bucket_ms: float) -> Optional[Dict[str, Any]]:
    """The synthetic drill (``PTPU_INTERCONNECT_TEST_INFLATE=
    <op>:<axis>:<frac>``): claim ``frac`` of the comm bucket for the
    named (op, axis) — creating the entry when no real observation
    exists — and rescale the other attributed entries so the remainder
    math stays consistent.  Returns the ``injected`` marker; a drilled
    block is labeled, never passed off as a real attribution."""
    raw = os.environ.get(INFLATE_ENV, "").strip()
    if not raw or comm_bucket_ms <= 0:
        return None
    parts = raw.split(":")
    if len(parts) != 3:
        return None
    op, axis = parts[0].strip(), parts[1].strip()
    try:
        frac = float(parts[2])
    except ValueError:
        return None
    if not op or not axis:
        return None
    frac = min(max(frac, 0.0), 1.0)
    target = frac * comm_bucket_ms
    named = None
    for e in entries:
        if e["op"] == op and e["axis"] == axis:
            named = e
            break
    if named is None:
        named = {"op": op, "axis": axis, "participants": None,
                 "calls": 0.0, "payload_bytes": 0.0, "wire_bytes": 0.0,
                 "measured_ms": 0.0, "modeled_ms": None,
                 "efficiency": None}
        entries.append(named)
    others = sum(e["measured_ms"] for e in entries if e is not named)
    scale = (max(0.0, (comm_bucket_ms - target) / others)
             if others > 1e-12 else 0.0)
    for e in entries:
        if e is not named:
            e["measured_ms"] *= scale
    named["measured_ms"] = target
    return {"op": op, "axis": axis, "frac": frac}


def build_block(comm_bucket_ms: float,
                per_op: Optional[List[Dict[str, Any]]] = None, *,
                hlo_comm: Optional[Dict[str, Dict[str, Any]]] = None,
                spec: Optional[Dict[str, Any]] = None,
                default_participants: Optional[int] = None,
                degraded: Optional[str] = None) -> Dict[str, Any]:
    """Assemble the per-collective sub-budget of the roofline ``comm``
    bucket for one scenario.

    ``per_op`` carries the harness's per-(op, axis) deltas over the
    timed window, already normalized per step: ``{"op", "axis",
    "participants", "calls", "ms", "payload_bytes"}``.  ``hlo_comm`` is
    the roofline fit's per-opcode comm table (``gap_budget``'s
    ``comm_ops``) for the exposed-vs-overlapped estimate;
    ``default_participants`` backfills HLO ops whose ``replica_groups``
    didn't name a group size.  Entries (with the signed
    ``"(unattributed)"`` remainder) sum to ``comm_bucket_ms`` exactly.
    """
    spec = spec or ici_spec()
    known = bool(spec.get("known"))
    bucket = float(comm_bucket_ms or 0.0)

    entries: List[Dict[str, Any]] = []
    for rec in per_op or []:
        op = str(rec.get("op") or "")
        if not op or op == UNATTRIBUTED:
            continue
        n = rec.get("participants")
        payload = float(rec.get("payload_bytes") or 0.0)
        measured = float(rec.get("ms") or 0.0)
        factor = wire_factor(op, n)
        modeled = (modeled_wire_time_ms(op, payload, n, spec)
                   if known else None)
        eff = None
        if modeled is not None and measured > 0 and modeled > 0:
            eff = modeled / measured
        entries.append({
            "op": op,
            "axis": rec.get("axis"),
            "participants": (int(n) if isinstance(n, (int, float)) and n
                             else None),
            "calls": float(rec.get("calls") or 0.0),
            "payload_bytes": payload,
            "wire_bytes": payload * factor,
            "measured_ms": measured,
            "modeled_ms": modeled,
            "efficiency": eff,
        })
    entries.sort(key=lambda e: e["measured_ms"], reverse=True)

    injected = _apply_inflation(entries, bucket)

    attributed = sum(e["measured_ms"] for e in entries)
    unatt = bucket - attributed
    modeled_total = sum(e["modeled_ms"] for e in entries
                        if isinstance(e["modeled_ms"], (int, float)))
    entries.append({
        "op": UNATTRIBUTED, "axis": None, "participants": None,
        "calls": 0.0, "payload_bytes": 0.0, "wire_bytes": 0.0,
        "measured_ms": unatt, "modeled_ms": None, "efficiency": None,
    })

    hlo_ops: Dict[str, Dict[str, Any]] = {}
    hlo_modeled: Optional[float] = 0.0 if known else None
    for opcode in sorted(hlo_comm or {}):
        rec = (hlo_comm or {})[opcode]
        n = rec.get("participants") or default_participants or 0
        b = float(rec.get("bytes") or 0.0)
        opname = HLO_OPCODE_OPS.get(opcode, opcode)
        t = (modeled_wire_time_ms(opname, b, n, spec) if known else None)
        hlo_ops[opcode] = {"count": int(rec.get("count") or 0),
                           "bytes": b,
                           "participants": int(n) if n else None,
                           "modeled_ms": (round(t, 6)
                                          if t is not None else None)}
        if t is not None and hlo_modeled is not None:
            hlo_modeled += t

    exposed = bucket
    overlapped = (max(0.0, hlo_modeled - exposed)
                  if hlo_modeled is not None else None)

    def _r(v):
        return round(v, 6) if isinstance(v, (int, float)) else v

    for e in entries:
        for k in ("calls", "payload_bytes", "wire_bytes", "measured_ms",
                  "modeled_ms", "efficiency"):
            e[k] = _r(e[k])
    return {
        "device": {k: spec.get(k) for k in
                   ("device_kind", "gen", "known", "ici_gbps", "links",
                    "topology")},
        "comm_bucket_ms": _r(bucket),
        "entries": entries,
        "modeled_ms_total": _r(modeled_total if known else None),
        "unattributed_ms": _r(unatt),
        "exposed_ms": _r(exposed),
        "hlo_modeled_ms": _r(hlo_modeled),
        "overlapped_ms": _r(overlapped),
        "hlo_ops": hlo_ops,
        "injected": injected,
        "degraded": degraded,
    }


def degraded_block(comm_bucket_ms: float, *,
                   reason: str = "no per-collective deltas captured",
                   spec: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """A schema-valid sub-budget with no per-op attribution — the whole
    bucket lands in ``"(unattributed)"``.  ``schema.new_row`` synthesizes
    this when a producer passes no interconnect block, so every v3
    row's entries still sum to the comm bucket."""
    return build_block(comm_bucket_ms, None, spec=spec, degraded=reason)


def attributed_total_ms(block: Dict[str, Any]) -> float:
    """Σ measured over the real (non-remainder) entries."""
    return sum(float(e.get("measured_ms") or 0.0)
               for e in (block.get("entries") or [])
               if e.get("op") != UNATTRIBUTED)


def unattributed_ms(block: Dict[str, Any]) -> float:
    """The signed remainder entry's measured milliseconds."""
    for e in (block.get("entries") or []):
        if e.get("op") == UNATTRIBUTED:
            return float(e.get("measured_ms") or 0.0)
    return 0.0


# --------------------------------------------------------------------------
# CLI: ledger reconciliation check (the CI roofline-tier gate)
# --------------------------------------------------------------------------

def _format_table(by_scenario: Dict[str, Dict[str, Any]]) -> str:
    lines = ["Interconnect sub-budgets (newest row per scenario, "
             "ms/step):"]
    for name in sorted(by_scenario):
        ic = by_scenario[name]
        dev = ic.get("device") or {}
        hdr = ("  %-14s comm=%.3fms  unattributed=%.3fms  gen=%s"
               % (name, float(ic.get("comm_bucket_ms") or 0.0),
                  unattributed_ms(ic), dev.get("gen") or "unknown"))
        if ic.get("overlapped_ms") is not None:
            hdr += "  overlapped=%.3fms" % float(ic["overlapped_ms"])
        if ic.get("injected"):
            hdr += "  [injected drill]"
        if ic.get("degraded"):
            hdr += "  [degraded: %s]" % ic["degraded"]
        lines.append(hdr)
        for e in ic.get("entries") or []:
            if e.get("op") == UNATTRIBUTED:
                continue
            eff = e.get("efficiency")
            lines.append(
                "    %-18s axis=%-9s n=%-4s measured=%8.3fms "
                "modeled=%s eff=%s"
                % (e.get("op"), e.get("axis"),
                   e.get("participants") or "?",
                   float(e.get("measured_ms") or 0.0),
                   ("%8.3fms" % e["modeled_ms"]
                    if isinstance(e.get("modeled_ms"), (int, float))
                    else "      --"),
                   ("%.2f" % eff if isinstance(eff, (int, float))
                    else "--")))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m paddle_tpu.observability.interconnect`` — print the
    per-collective sub-budget for the newest ledger row per scenario
    and fail when any row's entries don't sum to its roofline ``comm``
    bucket (or the row lacks an interconnect block entirely)."""
    import argparse

    from ..bench import ledger as bench_ledger

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.interconnect",
        description="per-collective comm sub-budget reconciliation "
                    "over the ledger")
    p.add_argument("--ledger", default=None, help="ledger path "
                   "(default benchmarks/ledger.jsonl)")
    p.add_argument("--mode", default="smoke", choices=("smoke", "full"))
    p.add_argument("--max-unattributed-frac", type=float, default=None,
                   help="bound on the (unattributed) share of a nonzero "
                        "comm bucket (default from golden thresholds)")
    args = p.parse_args(argv)
    drops: Dict[str, int] = {}
    rows = bench_ledger.read_ledger(args.ledger, drops=drops)
    if any(drops.values()):
        print("ledger drops: %s" % drops)  # noqa: print — CLI report
    frac = args.max_unattributed_frac
    if frac is None:
        frac = bench_ledger.threshold(bench_ledger.load_golden(),
                                      "interconnect_max_unattributed_frac")
    newest: Dict[str, Dict[str, Any]] = {}
    for row in rows:
        if row.get("mode") != args.mode:
            continue
        if not isinstance(row.get("scenario"), str):
            continue
        newest[row["scenario"]] = row  # ledger order: newest last wins
    if not newest:
        print("no %s rows in ledger" % args.mode)  # noqa: print — CLI report
        return 1
    failures: List[str] = []
    table: Dict[str, Dict[str, Any]] = {}
    for name, row in sorted(newest.items()):
        ic = row.get("interconnect")
        if not isinstance(ic, dict):
            failures.append("%s: no interconnect block (schema v%s row)"
                            % (name, row.get("schema_version")))
            continue
        table[name] = ic
        bucket = float(ic.get("comm_bucket_ms") or 0.0)
        total = sum(float(e.get("measured_ms") or 0.0)
                    for e in (ic.get("entries") or []))
        tol = max(0.01, 0.005 * abs(bucket))
        if abs(total - bucket) > tol:
            failures.append(
                "%s: entries sum %.4fms != comm bucket %.4fms"
                % (name, total, bucket))
        rl_comm = ((row.get("roofline") or {}).get("buckets_ms")
                   or {}).get("comm")
        if isinstance(rl_comm, (int, float)) and \
                abs(float(rl_comm) - bucket) > tol:
            failures.append(
                "%s: comm bucket %.4fms != roofline comm %.4fms"
                % (name, bucket, float(rl_comm)))
        if bucket > 0:
            un_frac = abs(unattributed_ms(ic)) / bucket
            if un_frac > frac:
                failures.append(
                    "%s: unattributed %.0f%% of comm bucket exceeds "
                    "%.0f%% bound" % (name, 100 * un_frac, 100 * frac))
    print(_format_table(table))  # noqa: print — CLI report
    if failures:
        print("RECONCILIATION FAILURES:")  # noqa: print — CLI report
        for f in failures:
            print("  " + f)  # noqa: print — CLI report
        return 1
    print("reconciliation OK: %d scenario(s); entries sum to the comm "  # noqa: print — CLI report
          "bucket exactly" % len(table))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
