"""Crash flight recorder (ISSUE 5).

The JSONL telemetry stream is buffered and lossy-by-contract — a hard
death (SIGKILL after a watchdog verdict, an OOM the allocator doesn't
survive, a segfault inside a Mosaic kernel) loses the in-memory tail of
the timeline, which is exactly the part a post-mortem needs.  The
:class:`FlightRecorder` is the black box for that case: a bounded
in-memory ring of the last N event records (attached to the metrics
registry as one more sink, so it sees the same timeline every other sink
sees) plus the most recent span closures, dumped durably to
``<run_dir>/flight/worker-<i>.json`` on any abnormal exit:

- the supervisor's fault path (``RunSupervisor.end_run(status!=
  "completed")`` — a fit() that raised);
- SIGTERM/SIGINT (chained onto whatever handler was installed, e.g. the
  elastic checkpointer's preemption flush);
- ``atexit``, as the backstop for a run that never reached ``end_run``.

The ring is ``PTPU_FLIGHT_BUFFER`` records deep (default 512).  The
doctor (:mod:`paddle_tpu.observability.doctor`) ingests flight bundles
as a first-class evidence stream: records present only in the bundle
(the lost JSONL tail) are folded into that worker's timeline, so a run
whose stream was torn mid-append still gets a ranked diagnosis.
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..framework.log import vlog
from ..utils import fsio

__all__ = ["FLIGHT_BUFFER_ENV", "FlightRecorder", "default_capacity",
           "flight_dir", "read_flight_bundles"]

FLIGHT_BUFFER_ENV = "PTPU_FLIGHT_BUFFER"
_FLIGHT_RE_PREFIX = "worker-"
_FLIGHT_SUFFIX = ".json"


def default_capacity() -> int:
    return max(16, int(os.environ.get(FLIGHT_BUFFER_ENV, "512")))


def flight_dir(run_dir: str) -> str:
    return os.path.join(run_dir, "flight")


class FlightRecorder:
    """Bounded ring of the most recent telemetry records, dumped on
    abnormal exit.

    Attach it as a registry sink (``get_registry().add_sink(fr)``) so it
    rides the same event fan-out as the JSONL writer; :meth:`install`
    arms the signal/atexit dump paths, :meth:`dump` is the explicit one
    (the supervisor's fault path calls it directly).  ``write`` is a
    deque append — cheap enough to sit on the hot path unconditionally.
    """

    def __init__(self, run_dir: str, worker_id: Optional[int] = None,
                 capacity: Optional[int] = None):
        if worker_id is None:
            import jax
            worker_id = jax.process_index()
        self.run_dir = run_dir
        self.worker_id = int(worker_id)
        self.capacity = (default_capacity() if capacity is None
                         else max(1, int(capacity)))
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.seen = 0
        self.dumps = 0
        self._installed = False
        self._prev_handlers: Dict[int, Any] = {}
        self._atexit_armed = False

    @property
    def path(self) -> str:
        return os.path.join(flight_dir(self.run_dir),
                            f"{_FLIGHT_RE_PREFIX}{self.worker_id}"
                            f"{_FLIGHT_SUFFIX}")

    # -- sink protocol -----------------------------------------------------
    def write(self, record: Dict[str, Any]) -> None:
        # locked so a dump racing a concurrent emit (the exact moment a
        # crash dump happens) never hits "deque mutated during iteration"
        with self._lock:
            self._ring.append(record)
            self.seen += 1

    def flush(self) -> None:
        pass  # nothing durable until a dump is warranted

    def close(self) -> None:
        pass  # detach is not abnormal exit; the ring stays dumpable

    # -- the dump ----------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Durably write the ring (+ recent span closures) as
        ``<run_dir>/flight/worker-<i>.json``; returns the path, or None
        when the write failed (a dying process must not die harder
        because its black box had an I/O error)."""
        records = self.snapshot()
        try:
            from .tracing import trace_events
            spans = trace_events()[-self.capacity:]
        except Exception:  # noqa: swallow
            spans = []  # tracing state is a bonus, never a dependency
        payload = {
            "worker": self.worker_id,
            "pid": os.getpid(),
            "reason": str(reason),
            "ts": time.time(),
            "capacity": self.capacity,
            "records_seen": self.seen,
            "records": records,
            "spans": spans,
        }
        try:
            os.makedirs(flight_dir(self.run_dir), exist_ok=True)
            fsio.atomic_write_bytes(
                self.path,
                json.dumps(payload, default=str).encode("utf-8"))
        except OSError as e:
            vlog(0, "flight: dump to %s failed: %s", self.path, e)
            return None
        self.dumps += 1
        vlog(0, "flight: dumped %d records (%s) → %s", len(records),
             reason, self.path)
        return self.path

    # -- abnormal-exit arming ----------------------------------------------
    def install(self, signals=(signal.SIGTERM, signal.SIGINT)) -> None:
        """Arm the dump on ``signals`` (chaining any existing handler —
        the elastic checkpointer's SIGTERM flush keeps working) and on
        interpreter exit.  Signal handlers can only be set from the main
        thread; elsewhere only the atexit backstop is armed."""
        if self._installed:
            return
        self._installed = True
        for sig in signals:
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._make_handler(sig))
            except ValueError:  # not the main thread
                vlog(1, "flight: cannot install handler for signal %s "
                     "off the main thread", sig)
        if not self._atexit_armed:
            self._atexit_armed = True
            atexit.register(self._atexit_dump)

    def uninstall(self) -> None:
        """Restore chained signal handlers and disarm the atexit dump
        (a run that ended cleanly leaves no bundle)."""
        if not self._installed:
            return
        self._installed = False
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # noqa: swallow
                pass  # off-main-thread teardown: leave the chain in place
        self._prev_handlers.clear()
        if self._atexit_armed:
            self._atexit_armed = False
            atexit.unregister(self._atexit_dump)

    def _make_handler(self, sig):
        def handler(signum, frame):
            self.dump(reason=f"signal-{signum}")
            prev = self._prev_handlers.get(sig)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
        return handler

    def _atexit_dump(self) -> None:
        # only an ABNORMAL exit dumps: a clean end_run uninstalls first
        if self._installed:
            self.dump(reason="atexit")


def read_flight_bundles(run_dir: str) -> Dict[int, Dict[str, Any]]:
    """{worker_id: bundle} for every readable
    ``<run_dir>/flight/worker-<i>.json`` (garbled bundles are skipped —
    a half-written black box reads as no black box)."""
    fdir = flight_dir(run_dir)
    bundles: Dict[int, Dict[str, Any]] = {}
    if not os.path.isdir(fdir):
        return bundles
    for name in sorted(os.listdir(fdir)):
        if not (name.startswith(_FLIGHT_RE_PREFIX)
                and name.endswith(_FLIGHT_SUFFIX)):
            continue
        try:
            payload = json.loads(
                fsio.read_bytes(os.path.join(fdir, name)))
            bundles[int(payload["worker"])] = payload
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return bundles
