"""Run doctor (ISSUE 4): post-run diagnosis of the silent MFU killers.

``python -m paddle_tpu.observability.doctor <run_dir>`` reads everything
a run left behind — the per-worker JSONL timelines under
``<run_dir>/metrics/``, the cross-worker ``summary.json`` (recomputed if
stale/absent), and the supervisor's post-mortem reports — and emits a
ranked ``<run_dir>/diagnosis.json`` plus a human-readable report.

Diagnosis taxonomy (each finding carries a 0–100 severity and concrete
evidence lines):

- ``oom``            — a ``memory.oom`` postmortem record exists; the
                       watermark table names the fullest device.
- ``retrace_storm``  — ``compile.retrace_storm`` records (or a high
                       retrace count) name the function and the argument
                       whose signature churn forced the recompiles.
- ``hbm_creep``      — per-device ``bytes_in_use`` trends upward across
                       ``memory`` samples, or the peak watermark sits
                       near ``bytes_limit``.
- ``straggler``      — cross-worker step-time spread (p50/p99, from
                       :func:`aggregate.straggler_stats`) attributes the
                       consistently slowest worker, with per-worker
                       ``collective.<op>.ms`` evidence from each
                       worker's final ``metrics.snapshot`` record (a
                       straggler computes while its peers wait in the
                       collective).
- ``comm_bound``     — a ``collective.<op>.ms`` histogram's p50 exceeds
                       a configurable fraction (``PTPU_COMM_BOUND_FRAC``,
                       default 0.25) of the p50 step time: the run pays
                       more for moving bytes than the overlap can hide —
                       compress the dp sync or shard the weight update
                       (``distributed/comm``, ISSUE 8).
- ``comm_budget``    — the interconnect microscope's per-collective
                       sub-budget (bench rows, ISSUE 20) shows the
                       roofline's exposed-comm bucket dominating the
                       step; the verdict names the dominant (op, axis)
                       and its efficiency vs the ICI cost model.
- ``data_starved``   — data-wait dominates the step-time breakdown.
- ``perf_trend``     — the ledger *series* for a benched scenario shows
                       an upward step-time changepoint (named by git-sha
                       range and dominant phase, via ``bench.trends``)
                       or a flagged upward drift — the multi-commit
                       creep a pairwise golden comparison can't see.
- ``unstable``       — the supervisor logged rollbacks / watchdog
                       timeouts / step failures (corroborating context,
                       ranked below the causes above).
- ``serve_poisoned`` — the serving engine quarantined request(s)
                       (``serve.quarantine`` records name the step kind
                       and error; durable records land under
                       ``<run_dir>/serve_quarantine/``).
- ``serve_deadline_misses`` — requests were evicted past their
                       deadline: the engine is underprovisioned for its
                       SLO (raise ``max_seqs`` / the KV pool, or shed
                       earlier).
- ``tail_latency``   — request traces (ISSUE 18) name the dominant
                       component of the p99-slowest requests by excess
                       over the fleet-median breakdown (queue vs
                       retry/backoff vs prefill vs decode vs
                       failover-recompute vs preempt-recompute) — the
                       request-centric view of "why is p99 slow".

Verdicts are mirrored into ``supervisor_report.json`` (kind
``doctor.verdict``) so the run's one post-mortem file carries the
diagnosis too.  See docs/ARCHITECTURE.md "Run doctor".
"""
from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

from ..framework.log import vlog
from ..utils import fsio
from .aggregate import (SCHEMA_VERSION, aggregate_run, read_worker_stream,
                        straggler_stats, _WORKER_RE)
from .registry import split_labels
from .sinks import metrics_dir

__all__ = ["diagnose", "render_report", "main", "check_compilation",
           "check_memory", "check_straggler", "check_data_starved",
           "check_comm_bound", "check_supervisor",
           "check_perf_regression", "check_perf_trend", "check_serving",
           "check_fleet", "check_fleet_flapping",
           "check_fleet_slo_burn", "check_tail_latency",
           "check_mfu_gap", "check_comm_budget"]

# tunables: thresholds a finding must clear before it is reported
RETRACE_WARN = 3            # retraces (not first compiles) per function
HBM_NEAR_LIMIT = 0.92       # peak/limit utilization
HBM_CREEP_FRAC = 0.05       # in_use growth first→last sample, fraction
STRAGGLER_REL_SPREAD = 0.2  # p99 spread / median step time
DATA_STARVED_FRAC = 0.3     # data_ms / step_time_ms
COMM_BOUND_FRAC = 0.25      # collective.<op>.ms p50 / step p50 (override
                            # with PTPU_COMM_BOUND_FRAC)
MFU_GAP_FRAC = 0.25         # dominant roofline gap sink / measured step
                            # (override with PTPU_MFU_GAP_FRAC)


def _finding(kind: str, severity: float, title: str,
             evidence: List[str], **data) -> Dict[str, Any]:
    return {"kind": kind, "severity": int(max(0, min(100, severity))),
            "title": title, "evidence": evidence, "data": data}


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def _read_workers(run_dir: str,
                  flight_workers: Optional[List[int]] = None
                  ) -> Dict[int, List[Dict[str, Any]]]:
    """Per-worker timelines: the JSONL streams, plus any crash flight
    bundles (ISSUE 5) folded in — a worker whose stream tail was lost
    (buffered records died with the process) gets the ring the flight
    recorder dumped, deduped against what the stream did land."""
    mdir = metrics_dir(run_dir)
    workers: Dict[int, List[Dict[str, Any]]] = {}
    if os.path.isdir(mdir):
        for name in sorted(os.listdir(mdir)):
            m = _WORKER_RE.match(name)
            if m:
                workers[int(m.group(1))] = read_worker_stream(
                    os.path.join(mdir, name))
    from .flight import read_flight_bundles
    for wid, bundle in read_flight_bundles(run_dir).items():
        recs = [r for r in bundle.get("records", [])
                if isinstance(r, dict)]
        if not recs:
            continue
        stream = workers.setdefault(wid, [])
        seen = {(r.get("ts"), r.get("kind")) for r in stream}
        fresh = [r for r in recs
                 if (r.get("ts"), r.get("kind")) not in seen]
        if fresh:
            stream.extend(fresh)
            stream.sort(key=lambda r: r.get("ts") or 0.0)
            if flight_workers is not None:
                flight_workers.append(wid)
            vlog(1, "doctor: worker %d — %d records recovered from the "
                 "flight bundle", wid, len(fresh))
    return workers


def _read_supervisor_events(run_dir: str) -> List[Dict[str, Any]]:
    events: List[Dict[str, Any]] = []
    for name in ("supervisor_report.json", "launcher_report.json"):
        path = os.path.join(run_dir, name)
        try:
            payload = json.loads(fsio.read_bytes(path))
        except (OSError, ValueError):
            continue
        for e in payload.get("events", []):
            if isinstance(e, dict):
                events.append({**e, "_source": name})
    return events


# -- checks (each returns a list of findings) ------------------------------
def check_compilation(workers) -> List[Dict[str, Any]]:
    findings = []
    storms: Dict[str, Dict[str, Any]] = {}
    retraces: Dict[str, int] = {}
    culprit_freq: Dict[str, Dict[str, int]] = {}
    for wid, records in workers.items():
        for r in records:
            if r.get("kind") == "compile.retrace_storm":
                fn = str(r.get("function"))
                storms.setdefault(fn, {"count": 0, "worker": wid,
                                       "culprit": r.get("culprit")})
                storms[fn]["count"] += 1
                if r.get("culprit"):
                    storms[fn]["culprit"] = r["culprit"]
            elif r.get("kind") == "compile" and r.get("retrace"):
                fn = str(r.get("function"))
                retraces[fn] = retraces.get(fn, 0) + 1
                for c in r.get("changed") or []:
                    freq = culprit_freq.setdefault(fn, {})
                    freq[c["arg"]] = freq.get(c["arg"], 0) + 1
    for fn, info in storms.items():
        n = retraces.get(fn, info["count"])
        culprit = info["culprit"]
        if not culprit and culprit_freq.get(fn):
            culprit = max(culprit_freq[fn], key=culprit_freq[fn].get)
        detail = _culprit_detail(workers, fn, culprit)
        ev = [f"{info['count']} retrace storm(s) on {fn} "
              f"({n} retraces total)",
              f"offending argument: {culprit!r}"
              + (f" — {detail}" if detail else "")]
        findings.append(_finding(
            "retrace_storm", 60 + 10 * min(3, info["count"]),
            f"retrace storm in {fn} driven by argument {culprit!r}",
            ev, function=fn, retraces=n, storms=info["count"],
            argument=culprit))
    for fn, n in retraces.items():
        if fn in storms or n < RETRACE_WARN:
            continue
        culprit = (max(culprit_freq[fn], key=culprit_freq[fn].get)
                   if culprit_freq.get(fn) else None)
        findings.append(_finding(
            "retrace_storm", 40 + 5 * min(6, n),
            f"{n} retraces of {fn} (most-changed argument {culprit!r})",
            [f"{n} retraces beyond the first compile",
             f"signature churn concentrated in {culprit!r}"],
            function=fn, retraces=n, storms=0, argument=culprit))
    return findings


def _culprit_detail(workers, fn: str, culprit) -> Optional[str]:
    """One concrete shape transition for the evidence line."""
    if culprit is None:
        return None
    for records in workers.values():
        for r in records:
            if r.get("kind") != "compile" or r.get("function") != fn:
                continue
            for c in r.get("changed") or []:
                if c["arg"] == culprit and c.get("detail"):
                    return c["detail"]
    return None


def check_memory(workers) -> List[Dict[str, Any]]:
    findings = []
    series: Dict[str, List[Dict[str, Any]]] = {}
    oom: Optional[Dict[str, Any]] = None
    for records in workers.values():
        for r in records:
            if r.get("kind") == "memory":
                for dev, row in (r.get("devices") or {}).items():
                    series.setdefault(dev, []).append(row)
            elif r.get("kind") == "memory.oom":
                oom = r
    if oom is not None:
        devices = oom.get("devices") or {}
        fullest = max(devices,
                      key=lambda d: devices[d].get("utilization", 0),
                      default=None)
        ev = [f"memory.oom postmortem at step {oom.get('step')}: "
              f"{oom.get('error') or 'allocator error'}"]
        if fullest:
            row = devices[fullest]
            ev.append(
                f"fullest device {fullest}: "
                f"{_fmt_bytes(row.get('bytes_in_use', 0))} in use / "
                f"{_fmt_bytes(row.get('bytes_limit', 0))} limit "
                f"(peak {_fmt_bytes(row.get('peak_bytes_in_use', 0))})")
        findings.append(_finding(
            "oom", 95, f"device OOM (fullest device: {fullest})", ev,
            step=oom.get("step"), device=fullest))
    for dev, rows in series.items():
        in_use = [r["bytes_in_use"] for r in rows if "bytes_in_use" in r]
        limit = next((r["bytes_limit"] for r in rows
                      if r.get("bytes_limit")), None)
        peak = max((r.get("peak_bytes_in_use", 0) for r in rows),
                   default=0)
        if limit and peak / limit >= HBM_NEAR_LIMIT:
            findings.append(_finding(
                "hbm_creep", 70 + 20 * min(1.0, peak / limit - 0.9) / 0.1,
                f"HBM watermark near limit on {dev}",
                [f"peak {_fmt_bytes(peak)} of {_fmt_bytes(limit)} limit "
                 f"({peak / limit:.1%})"],
                device=dev, peak=peak, limit=limit))
        elif len(in_use) >= 3 and in_use[0] > 0:
            growth = (in_use[-1] - in_use[0]) / in_use[0]
            # monotone-ish creep, not one transient spike
            rising = sum(b >= a for a, b in zip(in_use, in_use[1:]))
            if growth >= HBM_CREEP_FRAC and rising >= 0.7 * (len(in_use) - 1):
                findings.append(_finding(
                    "hbm_creep", 35 + 100 * min(0.4, growth),
                    f"HBM usage creeping on {dev} (+{growth:.1%})",
                    [f"bytes_in_use {_fmt_bytes(in_use[0])} → "
                     f"{_fmt_bytes(in_use[-1])} across "
                     f"{len(in_use)} samples"],
                    device=dev, growth=growth, samples=len(in_use)))
    return findings


def _collective_skew_evidence(workers, straggler: int) -> List[str]:
    """Compare per-worker collective histograms from the final
    ``metrics.snapshot`` records: a straggler shows *less* collective
    wait than its peers (they wait for it)."""
    per_worker: Dict[int, Dict[str, float]] = {}
    for wid, records in workers.items():
        snap = next((r for r in reversed(records)
                     if r.get("kind") == "metrics.snapshot"), None)
        if not snap:
            continue
        # aggregate across the label family (ISSUE 20: the histograms
        # carry [axis=..,n=..] suffixes now) so one op's wait is not
        # split across its axes — sum the sums, sum the counts
        sums: Dict[str, List[float]] = {}
        for name, m in (snap.get("snapshot") or {}).items():
            base, _labels = split_labels(name)
            if (base.startswith("collective.") and base.endswith(".ms")
                    and isinstance(m, dict) and m.get("count")):
                agg = sums.setdefault(base, [0.0, 0.0])
                agg[0] += float(m.get("sum") or 0.0)
                agg[1] += float(m["count"])
        per_worker[wid] = {op: s / c for op, (s, c) in sums.items() if c}
    if len(per_worker) < 2:
        return []
    ev = []
    ops = sorted({op for d in per_worker.values() for op in d})
    best_op, best_gap = None, 0.0
    for op in ops:
        vals = {w: d[op] for w, d in per_worker.items() if op in d}
        if straggler not in vals or len(vals) < 2:
            continue
        others = [v for w, v in vals.items() if w != straggler]
        gap = (sum(others) / len(others)) - vals[straggler]
        if gap > best_gap:
            best_op, best_gap = op, gap
    if best_op is not None and best_gap > 0:
        op_label = best_op[len("collective."):-len(".ms")]
        ev.append(
            f"peers wait in {op_label}: mean {best_gap:.1f}ms longer "
            f"than worker {straggler} (the straggler computes while "
            f"the fleet blocks)")
    return ev


def check_straggler(workers, summary=None) -> List[Dict[str, Any]]:
    stats = (summary or {}).get("straggler") or straggler_stats(workers)
    if not stats:
        return []
    rel = (stats.get("relative_spread") or {}).get("p99")
    if rel is None or rel < STRAGGLER_REL_SPREAD:
        return []
    wid = stats["straggler"]
    frac = stats["straggler_fraction"]
    means = stats.get("worker_mean_step_ms") or {}
    ev = [f"p99 cross-worker step spread "
          f"{stats['spread_ms']['p99']:.1f}ms = {rel:.0%} of the "
          f"median step ({stats['median_step_ms']:.1f}ms) across "
          f"{stats['aligned_steps']} aligned steps",
          f"worker {wid} slowest on {frac:.0%} of aligned steps"]
    if means:
        ev.append("mean step ms per worker: " + ", ".join(
            f"w{w}={m:.1f}" for w, m in sorted(means.items())))
    ev += _collective_skew_evidence(workers, wid)
    sev = 50 + 40 * min(1.0, rel) * frac
    return [_finding(
        "straggler", sev,
        f"worker {wid} is a straggler ({frac:.0%} of steps, "
        f"p99 spread {rel:.0%} of step time)",
        ev, worker=wid, fraction=frac, relative_spread_p99=rel,
        spread_ms=stats["spread_ms"])]


def check_data_starved(workers) -> List[Dict[str, Any]]:
    data_ms, step_ms = [], []
    for records in workers.values():
        for r in records:
            if r.get("kind") == "step" and r.get("step_time_ms"):
                step_ms.append(float(r["step_time_ms"]))
                data_ms.append(float(r.get("data_ms") or 0.0))
    if len(step_ms) < 3:
        return []
    frac = sum(data_ms) / max(1e-9, sum(step_ms))
    if frac < DATA_STARVED_FRAC:
        return []
    return [_finding(
        "data_starved", 30 + 50 * min(1.0, frac),
        f"data pipeline starving the device ({frac:.0%} of step time)",
        [f"data-wait is {frac:.0%} of total step time across "
         f"{len(step_ms)} steps"], fraction=frac)]


def check_comm_bound(workers, frac: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
    """ISSUE 8: a collective whose p50 latency eats more than ``frac``
    of the p50 step time makes the run *communication-bound*.  Works on
    any window of records (live monitor included): step p50 comes from
    ``step`` records in the window, falling back to the ``step.time_ms``
    histogram in the final ``metrics.snapshot``; collective p50s come
    from the snapshot's ``collective.<op>.ms`` histograms."""
    if frac is None:
        frac = float(os.environ.get("PTPU_COMM_BOUND_FRAC",
                                    COMM_BOUND_FRAC))
    findings = []
    worst: Dict[str, Dict[str, Any]] = {}
    for wid, records in workers.items():
        step_ms = sorted(float(r["step_time_ms"]) for r in records
                         if r.get("kind") == "step"
                         and r.get("step_time_ms"))
        snap = next((r for r in reversed(records)
                     if r.get("kind") == "metrics.snapshot"), None)
        snapshot = (snap or {}).get("snapshot") or {}
        step_p50 = (step_ms[len(step_ms) // 2] if step_ms
                    else (snapshot.get("step.time_ms") or {}).get("p50"))
        if not step_p50:
            continue
        for name, m in snapshot.items():
            # ISSUE 20: accept both the labeled family
            # (collective.<op>.ms[axis=..,n=..]) and the legacy
            # unlabeled name; each family member is judged on its own
            # p50 and only the worst per op is kept, so labels never
            # double-count an op's wait
            base, labels = split_labels(name)
            if not (base.startswith("collective.") and base.endswith(".ms")
                    and isinstance(m, dict) and m.get("count")):
                continue
            p50 = m.get("p50")
            if p50 is None or p50 < frac * step_p50:
                continue
            op = base[len("collective."):-len(".ms")]
            cur = worst.get(op)
            if cur is None or p50 / step_p50 > cur["ratio"]:
                worst[op] = {"worker": wid, "p50_ms": p50,
                             "step_p50_ms": step_p50,
                             "ratio": p50 / step_p50,
                             "count": int(m["count"]),
                             "axis": labels.get("axis")}
    for op, info in sorted(worst.items(), key=lambda kv: -kv[1]["ratio"]):
        axis_note = (f" on axis {info['axis']}" if info.get("axis")
                     else "")
        findings.append(_finding(
            "comm_bound", 45 + 45 * min(1.0, info["ratio"]),
            f"communication-bound: {op} p50 is {info['ratio']:.0%} of "
            f"the step time" + axis_note,
            [f"collective.{op}.ms p50 {info['p50_ms']:.1f}ms vs step "
             f"p50 {info['step_p50_ms']:.1f}ms on worker "
             f"{info['worker']} ({info['count']} calls; threshold "
             f"{frac:.0%})",
             "compress the dp gradient sync (CommConfig dtype=int8/"
             "bfloat16) or shard the weight update (ShardedOptimizer) — "
             "see docs/ARCHITECTURE.md 'Communication'"],
            op=op, **{k: v for k, v in info.items() if k != "op"}))
    return findings


def check_perf_regression(workers, golden=None) -> List[Dict[str, Any]]:
    """ISSUE 13: ``bench.row`` records in the telemetry window vs the
    checked-in ``benchmarks/golden.json`` — a row whose step p50 sits
    more than the golden's ``step_time_regression_frac`` above the
    blessed row becomes a ``perf_regression`` finding that NAMES the
    dominant mover (the perfdiff attribution), so /statusz and the
    post-run report say *which phase* slowed, not just "slower"."""
    from ..bench import diff as perfdiff
    from ..bench import ledger as bench_ledger
    if golden is None:
        golden = bench_ledger.load_golden()
    if not golden:
        return []
    thr = bench_ledger.threshold(golden, "step_time_regression_frac")
    latest: Dict[str, Dict[str, Any]] = {}
    for records in workers.values():
        for r in records:
            if r.get("kind") != "bench.row":
                continue
            name = r.get("scenario")
            if isinstance(name, str):
                latest[name] = r   # newest record per scenario wins
    findings = []
    for name, rec in sorted(latest.items()):
        base = (golden.get("scenarios") or {}).get(name)
        p50 = rec.get("step_time_p50_ms")
        if not base or not isinstance(p50, (int, float)):
            continue
        # reshape the telemetry record into a row-alike for perfdiff
        cur = {"scenario": name, "step_time_ms": {"p50": p50, "p99": p50},
               "phases_ms": rec.get("phases_ms") or {},
               "compile": {"wall_ms": rec.get("compile_wall_ms")},
               "device_kind": rec.get("device_kind")}
        report = perfdiff.diff_rows(base, cur, thr)
        if not report["regression"]:
            continue
        att = report["attribution"]
        dom = att["dominant"] or "unattributed"
        mover = next((m for m in att["movers"]
                      if m["phase"] == att["dominant"]), None)
        ev = [f"step p50 {report['base_p50_ms']:.2f}ms (golden) -> "
              f"{report['cur_p50_ms']:.2f}ms "
              f"({report['ratio']:.2f}x, threshold "
              f"{1.0 + thr:.2f}x)"]
        if mover:
            ev.append(f"dominant mover: {dom} "
                      f"{mover['base_ms']:.2f}ms -> {mover['cur_ms']:.2f}ms "
                      f"({mover['delta_ms']:+.2f}ms/step)")
        ev.append("full attribution: python -m paddle_tpu.bench.diff "
                  f"--golden --scenario {name}")
        ratio = report["ratio"] or 1.0
        findings.append(_finding(
            "perf_regression", 40 + 40 * min(1.0, ratio - 1.0 - thr),
            f"perf regression in {name}: {dom} moved "
            f"({ratio:.2f}x step time vs golden)",
            ev, scenario=name, dominant=dom, ratio=ratio,
            base_p50_ms=report["base_p50_ms"],
            cur_p50_ms=report["cur_p50_ms"]))
    return findings


def check_perf_trend(workers, rows=None) -> List[Dict[str, Any]]:
    """ISSUE 14: series-aware verdicts over the perf ledger, gated on
    ``bench.row`` records in the telemetry window (a run that benched
    nothing gets no trend findings — the global ledger is someone else's
    history).  For each benched scenario, ``bench.trends`` analyzes its
    sha-deduped series; the newest upward step-time changepoint (named
    by git-sha range and dominant phase) and/or a flagged upward drift
    become one ``perf_trend`` finding with the drift magnitude."""
    scenarios = set()
    for records in workers.values():
        for r in records:
            if (r.get("kind") == "bench.row"
                    and isinstance(r.get("scenario"), str)):
                scenarios.add(r["scenario"])
    if not scenarios:
        return []
    from ..bench import trends
    findings: List[Dict[str, Any]] = []
    for a in trends.scan_ledger(rows=rows,
                                scenario_names=sorted(scenarios)):
        step = a["metrics"].get("step_p50") or {}
        ups = [cp for cp in (step.get("changepoints") or [])
               if cp["direction"] == "up"]
        cp = ups[-1] if ups else None
        drift = step.get("drift")
        drifting = bool(drift and drift.get("flagged")
                        and drift["direction"] == "up")
        if cp is None and not drifting:
            continue
        ev: List[str] = []
        magnitude = 0.0
        title_bits: List[str] = []
        if cp is not None:
            before, at = cp.get("sha_range") or (None, None)
            dom = cp.get("dominant_phase") or "unattributed"
            ev.append(
                f"step p50 shifted {cp['delta_frac']:+.1%} at sha range "
                f"{(before or '?')[:8]}..{(at or '?')[:8]} "
                f"({cp['before_median']:.2f}ms -> "
                f"{cp['after_median']:.2f}ms), dominant phase: {dom}")
            magnitude = max(magnitude, cp["delta_frac"])
            title_bits.append(f"{cp['delta_frac']:+.1%} shift "
                              f"ending at {(at or '?')[:8]} ({dom})")
        if drifting:
            ev.append(
                f"step p50 drifting {drift['total_frac']:+.1%} across "
                f"{step.get('n')} commits "
                f"({drift['slope_per_point']:+.3g}ms/commit, residual "
                f"noise ±{drift['residual_sigma_frac']:.1%})")
            magnitude = max(magnitude, drift["total_frac"])
            title_bits.append(f"{drift['total_frac']:+.1%} drift")
        ev.append("series report: python -m paddle_tpu.bench.trends "
                  f"--scenario {a['scenario']}")
        findings.append(_finding(
            "perf_trend", 35 + 45 * min(1.0, magnitude / 0.5),
            f"perf trend in {a['scenario']}: " + ", ".join(title_bits),
            ev, scenario=a["scenario"], mode=a["mode"],
            delta_frac=magnitude,
            sha_range=(cp.get("sha_range") if cp else None),
            dominant=(cp.get("dominant_phase") if cp else None),
            drift_frac=(drift.get("total_frac") if drifting else None),
            flakiness=a.get("flakiness")))
    return findings


def check_supervisor(events) -> List[Dict[str, Any]]:
    if not events:
        return []
    counts: Dict[str, int] = {}
    for e in events:
        k = str(e.get("kind"))
        counts[k] = counts.get(k, 0) + 1
    bad = {k: v for k, v in counts.items()
           if k in ("rollback", "watchdog_timeout", "step_failure",
                    "guard_rollback", "worker_lost", "budget_exhausted")}
    if not bad:
        return []
    total = sum(bad.values())
    ev = [f"{v}× {k}" for k, v in sorted(bad.items())]
    return [_finding(
        "unstable", 25 + 5 * min(10, total),
        "supervisor intervened during the run",
        ev, events=bad)]


def check_integrity(events) -> List[Dict[str, Any]]:
    """State-integrity verdicts (ISSUE 11): ``desync`` when replicas
    voted a digest mismatch, ``sdc_suspect`` when a replay audit pinned
    the damage outside the computed path (hardware SDC signature)."""
    findings: List[Dict[str, Any]] = []
    desyncs = [e for e in events if e.get("kind") == "integrity.desync"]
    audits = [e for e in events if e.get("kind") == "integrity.audit"]
    heals = [e for e in events if e.get("kind") == "integrity.heal"]
    sdc = [e for e in audits if e.get("verdict") == "sdc_suspect"]
    nondet = [e for e in audits if e.get("verdict") == "nondeterminism"]
    if sdc:
        ev = [f"replay audit at step {e.get('step')}: replays agree "
              f"({e.get('replay')}) but live state reads {e.get('live')} "
              "— damaged outside the computed path" for e in sdc[:4]]
        ev.append("suspect the device: re-run the burn-in "
                  "(tools/burnin), cordon the host if it reproduces")
        findings.append(_finding(
            "sdc_suspect", 80 + 5 * min(4, len(sdc)),
            f"{len(sdc)} replay audit(s) indict silent data corruption",
            ev, audits=len(sdc)))
    if desyncs:
        suspects: Dict[str, int] = {}
        for e in desyncs:
            for w in (e.get("suspects") or []):
                suspects[str(w)] = suspects.get(str(w), 0) + 1
        healed: Dict[str, int] = {}
        for h in heals:
            a = str(h.get("action"))
            healed[a] = healed.get(a, 0) + 1
        ev = [f"{len(desyncs)}× digest mismatch across replicas "
              f"(steps {sorted(set(e.get('step') for e in desyncs))})"]
        if suspects:
            ev.append("suspect worker(s) by majority vote: " + ", ".join(
                f"worker {w} ({n}×)" for w, n in sorted(suspects.items())))
        if any(e.get("ambiguous") for e in desyncs):
            ev.append("at least one split had no majority (ambiguous) — "
                      "both sides were rolled back")
        if nondet:
            ev.append(f"{len(nondet)} replay audit(s) reproduced "
                      "DIFFERENT digests from identical inputs — "
                      "software nondeterminism, not hardware")
        if healed:
            ev.append("healing actions: " + ", ".join(
                f"{n}× {a}" for a, n in sorted(healed.items())))
        findings.append(_finding(
            "desync", 60 + 5 * min(6, len(desyncs)),
            "replica state digests diverged during the run",
            ev, count=len(desyncs), suspects=suspects))
    return findings


def check_serving(workers) -> List[Dict[str, Any]]:
    """Serving-resilience verdicts (ISSUE 15): ``serve_poisoned`` when
    the engine quarantined requests (each left a ``serve.quarantine``
    timeline record naming the step kind and error), and
    ``serve_deadline_misses`` when requests were evicted past their
    deadline — sustained misses mean the engine is underprovisioned for
    its SLO, not that requests are broken."""
    findings: List[Dict[str, Any]] = []
    quarantines: List[Dict[str, Any]] = []
    misses: List[Dict[str, Any]] = []
    for recs in workers.values():
        for r in recs:
            k = r.get("kind")
            if k == "serve.quarantine":
                quarantines.append(r)
            elif k == "serve.deadline_miss":
                misses.append(r)
    if quarantines:
        errors: Dict[str, int] = {}
        for q in quarantines:
            e = str(q.get("error"))
            errors[e] = errors.get(e, 0) + 1
        ev = [f"{q.get('request_id')}: {q.get('step_kind')} step — "
              f"{q.get('error')}" for q in quarantines[:4]]
        ev.append("durable records under "
                  "<run_dir>/serve/replica-<i>/quarantine/; every "
                  "co-batched request completed token-exact")
        findings.append(_finding(
            "serve_poisoned", 55 + 5 * min(6, len(quarantines)),
            f"{len(quarantines)} request(s) quarantined as poisoned",
            ev, count=len(quarantines), errors=errors))
    if misses:
        ttft = sum(1 for m in misses if m.get("miss") == "ttft")
        ev = [f"{len(misses)}× deadline eviction "
              f"({ttft} before first token)"]
        ev.append("requests: " + ", ".join(
            str(m.get("request_id")) for m in misses[:6]))
        ev.append("sustained misses = engine underprovisioned for the "
                  "SLO: raise max_seqs / the KV pool, or shed earlier")
        findings.append(_finding(
            "serve_deadline_misses", 30 + 5 * min(8, len(misses)),
            f"{len(misses)} request(s) evicted past their deadline",
            ev, count=len(misses), ttft_misses=ttft))
    return findings


def check_fleet(workers) -> List[Dict[str, Any]]:
    """Serving-fleet verdict (ISSUE 16): ``fleet_failover`` when the
    router re-homed live streams off a dead replica.  Failover itself
    is the system WORKING — clients saw nothing — but a replica died,
    and dying replicas are the thing to fix, so the verdict names the
    dead replicas and how many streams each failover moved."""
    findings: List[Dict[str, Any]] = []
    failovers: List[Dict[str, Any]] = []
    deaths: List[Dict[str, Any]] = []
    for recs in workers.values():
        for r in recs:
            k = r.get("kind")
            if k == "fleet.failover":
                failovers.append(r)
            elif (k == "fleet.replica_state"
                  and r.get("state") == "dead"):
                deaths.append(r)
    if not failovers:
        return findings
    by_replica: Dict[str, int] = {}
    for f in failovers:
        src = str(f.get("from_replica"))
        by_replica[src] = by_replica.get(src, 0) + 1
    ev = [f"{f.get('request_id')}: replica {f.get('from_replica')} -> "
          f"{f.get('to_replica')} ({f.get('why')}, "
          f"{f.get('accepted_tokens')} tokens journaled)"
          for f in failovers[:4]]
    ev.append("streams re-entered via the recompute-prefill path — "
              "completions stay token-exact (journaled prompt + "
              "accepted tokens re-admitted as pending tail)")
    if deaths:
        ev.append("replica deaths observed: " + ", ".join(
            f"replica {d.get('replica')}" for d in deaths[:6]))
    findings.append(_finding(
        "fleet_failover", 50 + 5 * min(6, len(failovers)),
        f"{len(failovers)} stream failover(s) off dead replica(s) "
        f"{sorted(by_replica)}",
        ev, count=len(failovers), by_replica=by_replica,
        deaths=len(deaths)))
    return findings


def check_fleet_flapping(workers) -> List[Dict[str, Any]]:
    """Flap verdict (ISSUE 17): ``fleet_flapping`` when a replica's
    circuit breaker tripped — the replica is alive by census but its
    transport fails intermittently.  The verdict names each flapping
    replica with its trip count, and escalates when the retry budget
    had to shed or defer work (the storm the breaker exists to
    prevent was actually knocking)."""
    findings: List[Dict[str, Any]] = []
    trips: Dict[str, int] = {}
    reopened: Dict[str, int] = {}
    budget_sheds = 0
    deferred = 0
    for recs in workers.values():
        for r in recs:
            k = r.get("kind")
            if k == "fleet.breaker" and r.get("state") == "open":
                rep = str(r.get("replica"))
                trips[rep] = trips.get(rep, 0) + 1
                if r.get("prev") == "half_open":
                    reopened[rep] = reopened.get(rep, 0) + 1
            elif k == "fleet.shed" and r.get("why") == "retry_budget":
                budget_sheds += 1
            elif k == "fleet.deferred":
                deferred += 1
    if not trips:
        return findings
    total = sum(trips.values())
    ev = [f"replica {rep}: breaker opened {n}× "
          + (f"({reopened[rep]}× from a failed half-open probe)"
             if rep in reopened else "(first trip)")
          for rep, n in sorted(trips.items())]
    ev.append("flapping ≠ dead: the replica answers /healthz but its "
              "transport fails intermittently — check its host before "
              "restarting it")
    if budget_sheds or deferred:
        ev.append(f"retry-budget pressure: {budget_sheds} submission(s) "
                  f"degraded to load-shed, {deferred} failover "
                  f"re-dispatch(es) deferred — the fleet was absorbing "
                  f"a retry storm")
    findings.append(_finding(
        "fleet_flapping",
        45 + 5 * min(5, total) + (10 if budget_sheds else 0),
        f"replica(s) {sorted(trips)} flapping "
        f"({total} breaker trip(s))",
        ev, trips=trips, reopened=reopened,
        budget_sheds=budget_sheds, deferred=deferred))
    return findings


def check_fleet_slo_burn(workers) -> List[Dict[str, Any]]:
    """Autoscaler verdict (ISSUE 17): ``fleet_slo_burn`` when the SLO
    burn-rate loop had to act.  Scale-ups that stayed under the
    ceiling are the system working (low severity, still worth a row —
    capacity was bought); ``blocked_at_max`` is the one operators page
    on: the SLO kept burning and the autoscaler had nothing left to
    give."""
    findings: List[Dict[str, Any]] = []
    ups: List[Dict[str, Any]] = []
    blocked: List[Dict[str, Any]] = []
    for recs in workers.values():
        for r in recs:
            if r.get("kind") != "fleet.autoscale":
                continue
            if r.get("action") == "up":
                ups.append(r)
            elif r.get("action") == "blocked_at_max":
                blocked.append(r)
    if not ups and not blocked:
        return findings
    ev = [f"scale-up to {u.get('target')} replicas "
          f"(burn {u.get('burn')}): {u.get('why')}" for u in ups[:4]]
    ev += [f"BLOCKED at {b.get('replicas')} replicas "
           f"(burn {b.get('burn')}): {b.get('why')}"
           for b in blocked[:4]]
    if blocked:
        ev.append("the fleet hit PTPU_FLEET_MAX while the SLO still "
                  "burned — raise the ceiling or shed earlier")
    findings.append(_finding(
        "fleet_slo_burn",
        (70 + 5 * min(4, len(blocked))) if blocked
        else 20 + 5 * min(4, len(ups)),
        (f"SLO burn exhausted the fleet ceiling "
         f"({len(blocked)} blocked-at-max event(s))") if blocked
        else f"SLO burn drove {len(ups)} scale-up(s)",
        ev, scale_ups=len(ups), blocked_at_max=len(blocked)))
    return findings


def check_tail_latency(workers) -> List[Dict[str, Any]]:
    """Request-trace verdict (ISSUE 18): assemble every ``trace.*``
    record in the window into per-request waterfalls and name the
    dominant component of the p99-slowest ones.  Severity scales with
    how far the tail sits above the median — a tail that is just the
    median again is healthy dispersion, not a finding."""
    from .requesttrace import TraceAssembler, tail_latency_attribution
    merged: List[Dict[str, Any]] = []
    for recs in workers.values():
        merged.extend(r for r in recs
                      if str(r.get("kind", "")).startswith("trace."))
    if not merged:
        return []
    result = TraceAssembler().from_records(merged)
    att = tail_latency_attribution(result["traces"])
    if att is None:
        return []
    p99, med = att["p99_ms"], att["median_ms"]
    ratio = p99 / med if med > 0 else 1.0
    if ratio < 1.2:
        return []                     # flat tail — nothing to attribute
    dom = att["dominant"]
    worst = att["slow"][0] if att["slow"] else {}
    ev = [f"p99 {p99:.1f}ms vs median {med:.1f}ms ({ratio:.1f}x) over "
          f"{result['complete']} complete trace(s)",
          f"dominant excess component: {dom} "
          f"(+{att['excess'].get(dom, 0.0):.1f}ms over the median "
          f"breakdown across the slow set)"]
    if worst:
        ev.append(f"slowest: {worst.get('request_id')} "
                  f"{worst.get('latency_ms'):.1f}ms, breakdown "
                  f"{worst.get('components')}")
    ev.append("waterfalls: python -m "
              "paddle_tpu.observability.requesttrace <run_dir>")
    return [_finding(
        "tail_latency", 30 + 30 * min(1.0, (ratio - 1.2) / 3.0),
        f"p99 latency dominated by {dom} ({ratio:.1f}x the median)",
        ev, dominant=dom, p99_ms=p99, median_ms=med,
        excess=att["excess"], slow=att["slow"][:4],
        orphan_spans=len(result["orphan_spans"]))]


def check_mfu_gap(workers) -> List[Dict[str, Any]]:
    """MFU-microscope verdict (ISSUE 19): ``bench.row`` records carry a
    slim roofline gap budget; when one named sink eats more than
    ``PTPU_MFU_GAP_FRAC`` (default 0.25) of the measured step, the doctor
    names it.  ``unknown_device`` and ``residual`` get honest wording —
    they mean the microscope could not attribute, not that the step is
    fine.  A synthetic drill row (``injected``) is flagged as such so the
    CI assertion and a human reading the report both see it is staged."""
    frac = float(os.environ.get("PTPU_MFU_GAP_FRAC", MFU_GAP_FRAC))
    newest: Dict[str, Dict[str, Any]] = {}
    for records in workers.values():
        for r in records:
            if r.get("kind") != "bench.row":
                continue
            roof = r.get("roofline")
            if not isinstance(roof, dict) or not isinstance(
                    roof.get("buckets_ms"), dict):
                continue
            name = str(r.get("scenario"))
            prev = newest.get(name)
            if prev is None or (r.get("ts") or 0) >= (prev.get("ts") or 0):
                newest[name] = r
    findings = []
    for name in sorted(newest):
        r = newest[name]
        roof = r["roofline"]
        buckets = roof["buckets_ms"]
        measured = float(roof.get("measured_step_ms") or 0.0)
        if measured <= 0:
            continue
        dom = roof.get("dominant_sink")
        dom_ms = float(buckets.get(dom, 0.0) or 0.0) if dom else 0.0
        share = dom_ms / measured
        if dom is None or dom == "mxu" or share <= frac:
            continue
        cov = roof.get("coverage")
        if dom == "unknown_device":
            what = ("device kind is not in the roofline table — the "
                    "whole compute phase is unattributable (fix: add "
                    "the device to observability.mfu.DEVICE_SPECS)")
        elif dom == "residual":
            what = ("time the roofline model cannot explain — treat "
                    "the rest of this budget as a lower bound, not a "
                    "diagnosis")
        else:
            what = {
                "memory_bound": "HBM-bandwidth-bound ops dominate — the "
                                "MXU is waiting on memory",
                "comm": "exposed (unoverlapped) collectives dominate",
                "host": "host-side data/readback gaps dominate",
                "padding": "batch/sequence padding burns the largest "
                           "share of compute",
            }.get(dom, dom)
        ev = [f"dominant gap sink: {dom} {dom_ms:.2f}ms of "
              f"{measured:.2f}ms measured ({share:.0%}, threshold "
              f"{frac:.0%})",
              "buckets: " + ", ".join(
                  f"{k}={float(v or 0.0):.2f}ms"
                  for k, v in buckets.items())]
        if cov is not None:
            ev.append(f"model coverage {float(cov):.0%} "
                      "(1 - |residual|/measured)")
        if roof.get("injected"):
            ev.append("NOTE: synthetic drill — this gap was injected "
                      "via PTPU_ROOFLINE_TEST_INFLATE")
        ev.append("full budget: python -m "
                  "paddle_tpu.observability.roofline")
        findings.append(_finding(
            "mfu_gap", 25 + 40 * min(1.0, (share - frac) / 0.5),
            f"{name}: MFU gap dominated by {dom} "
            f"({share:.0%} of the step) — {what}",
            ev, scenario=name, dominant=dom, share=share,
            measured_step_ms=measured, coverage=cov,
            injected=bool(roof.get("injected")),
            mfu=r.get("mfu")))
    return findings


def check_comm_budget(workers, frac: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
    """Interconnect-microscope verdict (ISSUE 20): ``bench.row`` records
    carry a slim per-collective sub-budget of the roofline's exposed-comm
    bucket.  When that bucket eats more than ``PTPU_COMM_BOUND_FRAC``
    (default 0.25) of the measured step — or a synthetic drill entry was
    injected — the doctor names the dominant (op, axis) and its
    efficiency vs the ICI cost model.  When ``(unattributed)`` holds the
    largest share the wording is honest: the microscope saw exposed comm
    time it could not pin to a named collective (trace-time observation
    sees jitted collectives once per trace, not per step)."""
    if frac is None:
        frac = float(os.environ.get("PTPU_COMM_BOUND_FRAC",
                                    COMM_BOUND_FRAC))
    from .interconnect import UNATTRIBUTED
    newest: Dict[str, Dict[str, Any]] = {}
    for records in workers.values():
        for r in records:
            if r.get("kind") != "bench.row":
                continue
            ic = r.get("interconnect")
            if not isinstance(ic, dict) or not isinstance(
                    ic.get("entries"), list):
                continue
            name = str(r.get("scenario"))
            prev = newest.get(name)
            if prev is None or (r.get("ts") or 0) >= (prev.get("ts") or 0):
                newest[name] = r
    findings = []
    for name in sorted(newest):
        r = newest[name]
        ic = r["interconnect"]
        roof = r.get("roofline") or {}
        measured = float(roof.get("measured_step_ms") or 0.0)
        bucket = float(ic.get("comm_bucket_ms") or 0.0)
        injected = ic.get("injected")
        share = bucket / measured if measured > 0 else 0.0
        if not injected and (measured <= 0 or share <= frac):
            continue
        entries = [e for e in ic["entries"]
                   if isinstance(e, dict) and e.get("op")]
        attributed = [e for e in entries if e["op"] != UNATTRIBUTED]
        unatt = next((float(e.get("measured_ms") or 0.0) for e in entries
                      if e["op"] == UNATTRIBUTED), 0.0)
        dom = max(attributed,
                  key=lambda e: float(e.get("measured_ms") or 0.0),
                  default=None)
        dom_ms = float(dom.get("measured_ms") or 0.0) if dom else 0.0
        ev = [f"exposed-comm bucket {bucket:.2f}ms of {measured:.2f}ms "
              f"measured ({share:.0%}, threshold {frac:.0%})"]
        if dom is not None and dom_ms >= unatt and dom_ms > 0:
            op = dom["op"]
            axis = dom.get("axis") or "?"
            eff = dom.get("efficiency")
            what = f"{op}[axis={axis}]"
            line = (f"dominant collective: {what} {dom_ms:.2f}ms "
                    f"({dom.get('participants') or '?'} participants)")
            if isinstance(dom.get("modeled_ms"), (int, float)):
                line += f", ICI-modeled wire time {dom['modeled_ms']:.3f}ms"
            if isinstance(eff, (int, float)):
                line += f", efficiency vs modeled {eff:.0%}"
            ev.append(line)
            data_op, data_axis, data_eff = op, dom.get("axis"), eff
        else:
            what = UNATTRIBUTED
            ev.append(
                f"largest share is {UNATTRIBUTED} ({unatt:.2f}ms): comm "
                "time the per-collective counters did not capture — a "
                "lower bound on the exposed collectives, not a diagnosis")
            data_op, data_axis, data_eff = UNATTRIBUTED, None, None
        if isinstance(ic.get("overlapped_ms"), (int, float)):
            ev.append(f"estimated overlapped (hidden) comm: "
                      f"{ic['overlapped_ms']:.2f}ms")
        if injected:
            ev.append("NOTE: synthetic drill — this entry was injected "
                      "via PTPU_INTERCONNECT_TEST_INFLATE")
        ev.append("full sub-budget: python -m "
                  "paddle_tpu.observability.interconnect")
        findings.append(_finding(
            "comm_budget",
            25 + 40 * min(1.0, max(0.0, share - frac) / 0.5),
            f"{name}: exposed comm dominated by {what} "
            f"({share:.0%} of the step)",
            ev, scenario=name, op=data_op, axis=data_axis,
            efficiency=data_eff, share=share, comm_bucket_ms=bucket,
            unattributed_ms=unatt, injected=injected,
            degraded=bool(ic.get("degraded"))))
    return findings


def diagnose(run_dir: str, write: bool = True) -> Optional[Dict[str, Any]]:
    """Run every check against ``run_dir``; returns the diagnosis dict
    (findings ranked most-severe first) or ``None`` when the run left no
    telemetry at all.  ``write=True`` also lands
    ``<run_dir>/diagnosis.json`` (atomic) and mirrors the verdicts into
    the supervisor report."""
    flight_workers: List[int] = []
    workers = _read_workers(run_dir, flight_workers=flight_workers)
    if not workers:
        return None
    # the cross-worker summary: reuse a fresh one, else recompute.  It is
    # built from the JSONL streams only — when flight bundles recovered a
    # lost tail, the in-memory `workers` view is the richer one, so the
    # checks below get that and the summary only seeds straggler stats.
    summary = aggregate_run(run_dir)
    if flight_workers:
        summary = None  # recompute skew over the recovered timelines
    events = _read_supervisor_events(run_dir)
    findings: List[Dict[str, Any]] = []
    findings += check_memory(workers)           # oom outranks everything
    findings += check_compilation(workers)
    findings += check_straggler(workers, summary)
    findings += check_data_starved(workers)
    findings += check_comm_bound(workers)
    findings += check_perf_regression(workers)
    findings += check_perf_trend(workers)
    findings += check_integrity(events)
    findings += check_serving(workers)
    findings += check_fleet(workers)
    findings += check_fleet_flapping(workers)
    findings += check_fleet_slo_burn(workers)
    findings += check_tail_latency(workers)
    findings += check_mfu_gap(workers)
    findings += check_comm_budget(workers)
    findings += check_supervisor(events)
    findings.sort(key=lambda f: (-f["severity"], f["kind"]))
    diagnosis = {
        "schema_version": SCHEMA_VERSION,
        "run_dir": os.path.abspath(run_dir),
        "workers": sorted(workers),
        "flight_workers": sorted(flight_workers),
        "records": sum(len(r) for r in workers.values()),
        "supervisor_events": len(events),
        "healthy": not findings,
        "findings": findings,
    }
    if write:
        fsio.atomic_write_bytes(
            os.path.join(run_dir, "diagnosis.json"),
            json.dumps(diagnosis, indent=1, default=str).encode("utf-8"))
        _mirror_to_supervisor(run_dir, findings)
    return diagnosis


def _mirror_to_supervisor(run_dir: str,
                          findings: List[Dict[str, Any]]) -> None:
    """Append one ``doctor.verdict`` event per finding to the run's
    supervisor report, so the post-mortem file carries the diagnosis."""
    path = os.path.join(run_dir, "supervisor_report.json")
    if not os.path.exists(path):
        return
    try:
        from ..supervisor.report import SupervisorReport
        report = SupervisorReport.load(path)
        for f in findings:
            report.record("doctor.verdict", verdict=f["kind"],
                          severity=f["severity"], title=f["title"])
        if not findings:
            report.record("doctor.verdict", verdict="healthy",
                          severity=0, title="no findings")
    except (OSError, ValueError, KeyError) as e:
        vlog(0, "doctor: could not mirror verdicts into %s: %s", path, e)


def render_report(diagnosis: Dict[str, Any]) -> str:
    """The human-readable half of the diagnosis."""
    lines = [f"run doctor — {diagnosis['run_dir']}",
             f"workers: {len(diagnosis['workers'])}, "
             f"records: {diagnosis['records']}, "
             f"supervisor events: {diagnosis['supervisor_events']}"]
    if diagnosis.get("flight_workers"):
        lines.append(
            "flight-recorder evidence recovered for worker(s): "
            + ", ".join(str(w) for w in diagnosis["flight_workers"]))
    if diagnosis["healthy"]:
        lines.append("no findings — the run looks healthy.")
        return "\n".join(lines)
    lines.append(f"{len(diagnosis['findings'])} finding(s), "
                 "most severe first:")
    for i, f in enumerate(diagnosis["findings"], 1):
        lines.append(f"  {i}. [{f['severity']:3d}] {f['kind']}: "
                     f"{f['title']}")
        for ev in f["evidence"]:
            lines.append(f"       - {ev}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    if len(args) != 1:
        print("usage: python -m paddle_tpu.observability.doctor "  # noqa: print
              "[--json] <run_dir>", file=sys.stderr)
        return 2
    diagnosis = diagnose(args[0])
    if diagnosis is None:
        print(f"no telemetry under {args[0]} — nothing to "  # noqa: print
              "diagnose", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(diagnosis, indent=1, default=str))  # noqa: print
    else:
        print(render_report(diagnosis))  # noqa: print
    return 0


if __name__ == "__main__":
    sys.exit(main())
