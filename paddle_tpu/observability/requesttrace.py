"""Per-request fleet tracing: end-to-end waterfalls + tail attribution
(ISSUE 18).

Every latency number the serving tier published before this module was
engine-local: ``serve.ttft_ms`` starts when the *engine* admits a
request, so router queueing, dispatch retries, breaker backoff,
failover re-prefill and preemption recompute — the components that
dominate p99 under load — were invisible.  This module closes the gap
with a request-centric trace assembled from the step-centric telemetry
the PR 3 registry/JSONL spine already carries:

- the router mints a ``trace_id`` per submission
  (:func:`mint_trace_id`); the id rides the spill-format record dict
  through ``HttpReplica``/``worker.py`` into
  ``ServingEngine.admit_record`` and the scheduler's
  :class:`~paddle_tpu.inference.scheduler.SequenceState`, and is made
  durable in the fleet WAL ``open`` record so ``Router(recover=)``
  re-attaches with the *same* id;
- router and engine emit ``trace.span`` records
  (:func:`emit_span`) onto whatever sinks the registry carries — one
  JSONL stream per process, merged here;
- engine decode steps are batch-level, so the step span carries its
  resident ``(request_id, trace_id)`` list and the assembler amortizes
  the step across residents (:func:`TraceAssembler.add_record`);
- :class:`TraceAssembler` merges the router stream, the per-replica
  worker streams and the fleet journal into one waterfall per request,
  with a **coverage** metric (fraction of the client-observed window
  explained by the span union) and a per-component breakdown;
- :func:`tail_latency_attribution` names the dominant component of the
  p99 slowest traces by *excess over the fleet-median breakdown* — the
  comparison that lets failover-recompute beat decode even though
  decode dominates every trace in absolute terms;
- :func:`chrome_trace_events` exports one Perfetto timeline: one pid
  per process (``process_name`` metadata), one tid per request
  (``thread_name`` metadata), spans nested under each request's track.

Component → attribution buckets: recompute components absorb the
re-queue wait they induce (time a stream spends re-queued on the
survivor after a failover is failover cost, not "queue"), so the
doctor's verdict names the *cause*, not the symptom.

Knobs: ``PTPU_TRACE_REQUESTS`` (default on; "0" disables minting, so
no spans are emitted anywhere), ``PTPU_TRACE_SAMPLE`` (fraction of
requests traced, deterministic per ``request_id`` hash — no RNG, so a
re-dispatched request keeps its sampling decision).

CLI::

    python -m paddle_tpu.observability.requesttrace <run_dir> \
        [--out traces.json] [--chrome trace.json] [--json]
"""
from __future__ import annotations

import functools
import json
import math
import os
import re
import time
import uuid
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils import fsio
from .aggregate import read_worker_stream

__all__ = ["TRACE_REQUESTS_ENV", "TRACE_SAMPLE_ENV", "tracing_enabled",
           "sample_fraction", "sampled", "mint_trace_id", "emit_span",
           "emit_decode_span", "emit_stall_span", "component_bucket",
           "emission_cost", "TraceAssembler",
           "assemble_run", "tail_latency_attribution",
           "chrome_trace_events", "export_chrome_trace", "main"]

TRACE_REQUESTS_ENV = "PTPU_TRACE_REQUESTS"
TRACE_SAMPLE_ENV = "PTPU_TRACE_SAMPLE"

_WORKER_RE = re.compile(r"^worker-(\d+)\.jsonl$")

#: component → attribution bucket for breakdowns and the doctor's
#: ``tail_latency`` verdict.  Recompute components absorb their induced
#: re-queue / re-dispatch time so the verdict names the cause.
COMPONENT_BUCKETS = {
    "queue": "queue",
    "dispatch": "dispatch",
    "retry_backoff": "retry_backoff",
    "prefill": "prefill",
    "decode": "decode",
    "failover": "failover_recompute",
    "failover_recompute": "failover_recompute",
    "migration": "migration",
    "migration_recompute": "migration",
    "preempt": "preempt_recompute",
    "preempt_recompute": "preempt_recompute",
    "quarantine": "quarantine",
    "callback": "callback",
    "stall": "stall",
    "deliver": "deliver",
}


# -- trace context ---------------------------------------------------------
def tracing_enabled() -> bool:
    """``PTPU_TRACE_REQUESTS`` gate — default on."""
    return os.environ.get(TRACE_REQUESTS_ENV, "1").lower() not in (
        "0", "false", "no", "off")


def sample_fraction() -> float:
    """``PTPU_TRACE_SAMPLE`` in [0, 1]; default 1.0 (trace everything)."""
    try:
        frac = float(os.environ.get(TRACE_SAMPLE_ENV, "1"))
    except ValueError:
        return 1.0
    return min(1.0, max(0.0, frac))


def sampled(request_id: str) -> bool:
    """Deterministic per-request sampling decision: a stable hash of
    the request id against the sample fraction, so the same request
    keeps its decision across re-dispatch/recovery and across
    processes (no RNG, no shared state)."""
    frac = sample_fraction()
    if frac >= 1.0:
        return True
    if frac <= 0.0:
        return False
    h = zlib.crc32(str(request_id).encode("utf-8")) & 0xFFFFFFFF
    return (h / float(0xFFFFFFFF)) < frac


def mint_trace_id(request_id: str) -> Optional[str]:
    """A fresh trace id for ``request_id``, or ``None`` when tracing
    is disabled or the request falls outside the sample."""
    if not tracing_enabled() or not sampled(request_id):
        return None
    return uuid.uuid4().hex[:16]


def component_bucket(component: str) -> str:
    return COMPONENT_BUCKETS.get(component, component)


# -- emission --------------------------------------------------------------
class _EmissionCost:
    """Wall-clock accounting of the span-emission hot path (record
    construction + sink writes).  Off by default — when enabled, every
    ``emit_*`` call below adds its duration here, giving a direct
    measurement of what tracing costs the serving loop.  The bench's
    ``serve_fleet`` scenario uses this to price tracing against step
    p50: at millisecond-scale steps, A/B run differencing has a noise
    floor far above the 1% budget, while direct accounting resolves
    microseconds.  Single accumulator, no lock — intended for
    single-threaded bench harnesses, not production concurrency."""

    def __init__(self) -> None:
        self.enabled = False
        self.seconds = 0.0
        self.count = 0

    def start(self) -> None:
        self.enabled = True
        self.seconds = 0.0
        self.count = 0

    def stop(self) -> None:
        self.enabled = False

    def add(self, dt: float) -> None:
        self.seconds += dt
        self.count += 1


#: process-wide emission-cost meter (see :class:`_EmissionCost`)
emission_cost = _EmissionCost()


def _costed(fn):
    """Route a function through :data:`emission_cost` when metering is
    on; zero-branch passthrough otherwise."""
    @functools.wraps(fn)
    def wrap(*args, **kwargs):
        if not emission_cost.enabled:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            emission_cost.add(time.perf_counter() - t0)
    return wrap


@_costed
def emit_span(registry, trace_id: Optional[str], request_id: str,
              name: str, component: str, t0: float, t1: float,
              proc: str, **fields) -> None:
    """One ``trace.span`` record; no-op when the request is untraced.
    ``t0``/``t1`` are wall-clock (comparable across processes on one
    host — the fleet is single-host by construction)."""
    if trace_id is None:
        return
    t0 = float(t0)
    t1 = float(t1)
    registry.emit("trace.span", trace_id=trace_id,
                  request_id=request_id, name=str(name),
                  component=str(component), t0=t0, t1=t1,
                  dur_ms=max(0.0, t1 - t0) * 1e3, proc=str(proc),
                  **fields)


@_costed
def emit_decode_span(registry, requests: Sequence[Tuple[str, Optional[str]]],
                     residents: int, t0: float, t1: float,
                     proc: str) -> None:
    """One batch-level decode span.  ``requests`` lists the *traced*
    residents as ``(request_id, trace_id)``; ``residents`` counts every
    resident (traced or not) so the assembler's amortized share stays
    honest under partial sampling."""
    traced = [[str(r), t] for r, t in requests if t is not None]
    if not traced:
        return
    t0 = float(t0)
    t1 = float(t1)
    registry.emit("trace.span", name="decode_batch", component="decode",
                  t0=t0, t1=t1, dur_ms=max(0.0, t1 - t0) * 1e3,
                  proc=str(proc), residents=max(1, int(residents)),
                  requests=traced)


@_costed
def emit_stall_span(registry, requests: Sequence[Tuple[str, Optional[str]]],
                    t0: float, t1: float, proc: str,
                    component: str = "stall", cause: str = "") -> None:
    """One batch-level stall span: residents that were live on the
    engine but NOT served by this step (the scheduler ran someone
    else's prefill, a recompute, a quarantine bisect).  Unlike the
    amortized decode share, every stalled request experiences the
    *full* step duration, so ``residents`` stays 1.  ``component``
    names the cause when the serving step was induced work (a failover
    re-prefill's head-of-line stall is failover cost, not bad luck)."""
    t0 = float(t0)
    t1 = float(t1)
    if t1 <= t0:
        return
    traced = [[str(r), t] for r, t in requests if t is not None]
    if not traced:
        return
    registry.emit("trace.span", name="stall", component=str(component),
                  t0=t0, t1=t1, dur_ms=(t1 - t0) * 1e3, proc=str(proc),
                  residents=1, requests=traced, cause=str(cause))


# -- assembly --------------------------------------------------------------
def _merged(intervals: List[Tuple[float, float]]
            ) -> List[Tuple[float, float]]:
    """Union of ``[t0, t1]`` intervals as a sorted disjoint list."""
    out: List[Tuple[float, float]] = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        elif b > a:
            out.append((a, b))
    return out


def _merged_length(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of ``[t0, t1]`` intervals."""
    return sum(b - a for a, b in _merged(intervals))


def _residue_length(base: List[Tuple[float, float]],
                    minus: List[Tuple[float, float]]) -> float:
    """Length of union(base) NOT covered by union(minus)."""
    total = 0.0
    for a, b in _merged(base):
        cut = a
        for c, d in _merged(minus):
            if d <= cut:
                continue
            if c >= b:
                break
            if c > cut:
                total += c - cut
            cut = max(cut, min(d, b))
            if cut >= b:
                break
        if cut < b:
            total += b - cut
    return total


def _median(values: List[float]) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class TraceAssembler:
    """Folds ``trace.request`` / ``trace.span`` / ``trace.request_end``
    records (from any number of per-process streams) into one waterfall
    per request.

    Feed it records in any order via :meth:`add_record` /
    :meth:`add_records`, optionally cross-check against the fleet WAL
    via :meth:`add_journal`, then :meth:`assemble`.
    """

    def __init__(self):
        self._open: Dict[str, Dict[str, Any]] = {}     # trace_id -> rec
        self._end: Dict[str, Dict[str, Any]] = {}
        self._spans: Dict[str, List[Dict[str, Any]]] = {}
        self._journal: Dict[str, Dict[str, Any]] = {}  # trace_id -> rec
        self.records_seen = 0

    # -- ingest ------------------------------------------------------------
    def add_record(self, rec: Dict[str, Any]) -> None:
        kind = rec.get("kind")
        if kind == "trace.request":
            tid = rec.get("trace_id")
            if tid is None:
                return
            self.records_seen += 1
            prev = self._open.get(tid)
            if prev is None or float(rec.get("t0", math.inf)) < \
                    float(prev.get("t0", math.inf)):
                self._open[tid] = rec
        elif kind == "trace.request_end":
            tid = rec.get("trace_id")
            if tid is None:
                return
            self.records_seen += 1
            prev = self._end.get(tid)
            if prev is None or float(rec.get("t1", -math.inf)) > \
                    float(prev.get("t1", -math.inf)):
                self._end[tid] = rec
        elif kind == "trace.span":
            self.records_seen += 1
            if rec.get("requests") is not None:
                # batch-level span (decode_batch, stall): fan out to
                # every listed resident, amortizing over ``residents``
                # (1 for stalls — each stalled request eats the full
                # step)
                residents = max(1, int(rec.get("residents", 1)))
                dur = float(rec.get("dur_ms", 0.0))
                name = ("decode" if rec.get("name") == "decode_batch"
                        else str(rec.get("name")))
                comp = str(rec.get("component", name))
                for entry in rec.get("requests", []):
                    try:
                        rid, tid = entry[0], entry[1]
                    except (TypeError, IndexError):
                        continue
                    if tid is None:
                        continue
                    self._spans.setdefault(tid, []).append({
                        "name": name, "component": comp,
                        "request_id": rid,
                        "t0": float(rec.get("t0", 0.0)),
                        "t1": float(rec.get("t1", 0.0)),
                        "dur_ms": dur,
                        "amortized_ms": dur / residents,
                        "proc": rec.get("proc")})
            else:
                tid = rec.get("trace_id")
                if tid is None:
                    return
                self._spans.setdefault(tid, []).append({
                    "name": rec.get("name"),
                    "component": rec.get("component"),
                    "request_id": rec.get("request_id"),
                    "t0": float(rec.get("t0", 0.0)),
                    "t1": float(rec.get("t1", 0.0)),
                    "dur_ms": float(rec.get("dur_ms", 0.0)),
                    "amortized_ms": None,
                    "proc": rec.get("proc")})

    def add_records(self, records: Iterable[Dict[str, Any]]) -> None:
        for rec in records:
            self.add_record(rec)

    def add_journal(self, rec: Dict[str, Any]) -> None:
        """One recovered WAL stream (``JournalStore`` record shape)."""
        tid = rec.get("trace_id")
        if tid is not None:
            self._journal[tid] = rec

    # -- assemble ----------------------------------------------------------
    def _one(self, tid: str) -> Dict[str, Any]:
        spans = sorted(self._spans.get(tid, []),
                       key=lambda s: (s["t0"], s["t1"]))
        opened = self._open.get(tid)
        ended = self._end.get(tid)
        t0 = float(opened["t0"]) if opened is not None else (
            min((s["t0"] for s in spans), default=None))
        t1 = float(ended["t1"]) if ended is not None else (
            max((s["t1"] for s in spans), default=None))
        latency_ms = (t1 - t0) * 1e3 if (t0 is not None and
                                         t1 is not None) else None
        components: Dict[str, float] = {}
        intervals: List[Tuple[float, float]] = []
        deliver: List[Tuple[float, float]] = []
        for s in spans:
            a, b = s["t0"], s["t1"]
            if t0 is not None:
                a = max(a, t0)
            if t1 is not None:
                b = min(b, t1)
            bucket = component_bucket(s.get("component") or "other")
            if bucket == "deliver":
                # lowest-priority residue bucket: the router's
                # progress-observation window overlaps generation, so
                # it is charged only what no other span explains (poll
                # starvation, HTTP lag) — see below
                if b > a:
                    deliver.append((a, b))
                continue
            share = s["amortized_ms"] if s["amortized_ms"] is not None \
                else s["dur_ms"]
            components[bucket] = components.get(bucket, 0.0) + share
            if b > a:
                intervals.append((a, b))
        if deliver:
            residue = _residue_length(deliver, intervals) * 1e3
            if residue > 1e-6:
                components["deliver"] = residue
            intervals = intervals + deliver
        if latency_ms is not None and latency_ms > 0:
            coverage = min(1.0, _merged_length(intervals)
                           / ((t1 - t0) or 1.0))
        elif spans:
            coverage = 1.0 if latency_ms == 0.0 else 0.0
        else:
            coverage = 0.0
        request_id = None
        for src in (opened, ended):
            if src is not None and src.get("request_id") is not None:
                request_id = src["request_id"]
                break
        if request_id is None and spans:
            request_id = next((s["request_id"] for s in spans
                               if s.get("request_id") is not None), None)
        wal = self._journal.get(tid)
        return {"trace_id": tid, "request_id": request_id,
                "t0": t0, "t1": t1, "latency_ms": latency_ms,
                "complete": opened is not None and ended is not None,
                "reason": (ended or {}).get("reason"),
                "tokens": (ended or {}).get("tokens"),
                "spans": spans,
                "procs": sorted({s.get("proc") for s in spans
                                 if s.get("proc") is not None}),
                "components": {k: round(v, 3)
                               for k, v in sorted(components.items())},
                "coverage": round(coverage, 4),
                "wal": None if wal is None else {
                    "tokens": len(wal.get("tokens", [])),
                    "finished": bool(wal.get("finished")),
                    "reason": wal.get("reason")}}

    def assemble(self) -> Dict[str, Any]:
        """All traces plus integrity accounting.  A span whose
        ``trace_id`` has neither lifecycle record is an **orphan** —
        the continuity tests assert there are none."""
        ids = set(self._open) | set(self._end) | set(self._spans)
        traces = [self._one(tid) for tid in ids]
        traces.sort(key=lambda t: (t["t0"] is None, t["t0"] or 0.0))
        orphans = sorted(tid for tid in self._spans
                         if tid not in self._open and tid not in self._end)
        wal_ids = set(self._journal)
        return {"traces": traces,
                "complete": sum(1 for t in traces if t["complete"]),
                "orphan_spans": orphans,
                "wal_streams": len(wal_ids),
                "wal_matched": len(wal_ids & ids),
                "records_seen": self.records_seen}

    def from_records(self, records: Iterable[Dict[str, Any]]
                     ) -> Dict[str, Any]:
        self.add_records(records)
        return self.assemble()


def assemble_run(run_dir: str) -> Dict[str, Any]:
    """Merge ``<run_dir>/metrics/worker-*.jsonl`` (router = worker-0,
    replica *i* = worker-*i+1*) and the fleet WAL into per-request
    waterfalls."""
    from .sinks import metrics_dir
    asm = TraceAssembler()
    drops: Dict[str, int] = {}
    mdir = metrics_dir(run_dir)
    streams = 0
    try:
        listing = sorted(os.listdir(mdir))
    except OSError:
        listing = []
    for name in listing:
        if not _WORKER_RE.match(name):
            continue
        streams += 1
        asm.add_records(read_worker_stream(os.path.join(mdir, name),
                                           drops))
    # the fleet WAL cross-checks stream identity (and survives a
    # SIGKILLed metrics stream outright)
    from ..inference.fleet.journal import JournalStore, journal_dir
    jdir = journal_dir(run_dir)
    if os.path.isdir(jdir):
        store = JournalStore(run_dir)
        for name in sorted(os.listdir(jdir)):
            if not (name.endswith(".jsonl") or name.endswith(".jsonl.done")):
                continue
            rec = store._read_one(os.path.join(jdir, name),
                                  quarantine=False)
            if rec is not None:
                asm.add_journal(rec)
    out = asm.assemble()
    out["run_dir"] = run_dir
    out["streams"] = streams
    out["drops"] = drops
    return out


# -- tail attribution ------------------------------------------------------
def tail_latency_attribution(traces: List[Dict[str, Any]],
                             tail_pct: float = 99.0
                             ) -> Optional[Dict[str, Any]]:
    """Name the dominant component of the p99-slowest traces.

    Dominance is judged by **excess over the median trace's
    per-component breakdown**, not absolute share — decode dominates
    every healthy trace in absolute terms, so "what does the tail pay
    *extra* for" is the question that points at failover recompute,
    retry backoff or queueing.  Returns ``None`` with fewer than two
    complete traces (no tail to attribute)."""
    done = [t for t in traces
            if t.get("complete") and t.get("latency_ms") is not None]
    if len(done) < 2:
        return None
    lats = sorted(t["latency_ms"] for t in done)
    rank = max(1, int(math.ceil(tail_pct / 100.0 * len(lats))))
    thresh = lats[rank - 1]
    slow = [t for t in done if t["latency_ms"] >= thresh]
    rest = [t for t in done if t["latency_ms"] < thresh] or done
    comps = sorted({c for t in done for c in t["components"]})
    baseline = {c: _median([t["components"].get(c, 0.0) for t in rest])
                for c in comps}
    excess = {c: 0.0 for c in comps}
    for t in slow:
        for c in comps:
            excess[c] += max(0.0, t["components"].get(c, 0.0)
                             - baseline[c])
    if any(v > 0.0 for v in excess.values()):
        dominant = max(excess, key=lambda c: excess[c])
    else:
        # degenerate tail (all traces identical): largest absolute
        agg: Dict[str, float] = {}
        for t in slow:
            for c, v in t["components"].items():
                agg[c] = agg.get(c, 0.0) + v
        dominant = max(agg, key=lambda c: agg[c]) if agg else "unknown"
    return {"dominant": dominant,
            "p99_ms": round(thresh, 3),
            "median_ms": round(_median(lats), 3),
            "baseline": {c: round(v, 3) for c, v in baseline.items()},
            "excess": {c: round(v, 3) for c, v in excess.items()},
            "slow": [{"request_id": t["request_id"],
                      "trace_id": t["trace_id"],
                      "latency_ms": round(t["latency_ms"], 3),
                      "coverage": t["coverage"],
                      "components": t["components"]}
                     for t in sorted(slow,
                                     key=lambda t: -t["latency_ms"])]}


# -- chrome export ---------------------------------------------------------
def chrome_trace_events(traces: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Perfetto/chrome://tracing events: one pid per fleet process
    (``process_name`` metadata), one tid per request
    (``thread_name`` = request id), every span an ``X`` duration event
    nested under its request's track in the process it ran in."""
    procs = sorted({s.get("proc") or "unknown"
                    for t in traces for s in t["spans"]})
    pid_of = {p: i + 1 for i, p in enumerate(procs)}
    events: List[Dict[str, Any]] = []
    for proc, pid in pid_of.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": proc}})
    ordered = sorted(traces, key=lambda t: (t["t0"] is None,
                                            t["t0"] or 0.0))
    for tix, t in enumerate(ordered):
        tid = tix + 1
        label = str(t.get("request_id") or t["trace_id"])
        for pid in sorted({pid_of[s.get("proc") or "unknown"]
                           for s in t["spans"]}):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": label}})
        for s in t["spans"]:
            events.append({
                "name": s["name"], "ph": "X", "cat": s["component"],
                "pid": pid_of[s.get("proc") or "unknown"], "tid": tid,
                "ts": s["t0"] * 1e6,
                "dur": max(0.0, s["t1"] - s["t0"]) * 1e6,
                "args": {"trace_id": t["trace_id"],
                         "component": s["component"],
                         "amortized_ms": s["amortized_ms"]}})
    return events


def export_chrome_trace(path: str,
                        traces: List[Dict[str, Any]]) -> int:
    """Write the merged fleet timeline; returns the event count."""
    events = chrome_trace_events(traces)
    fsio.atomic_write_bytes(
        path, json.dumps({"traceEvents": events,
                          "displayTimeUnit": "ms"}).encode())
    return len(events)


# -- CLI -------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.requesttrace",
        description="Assemble per-request fleet traces from a run dir.")
    ap.add_argument("run_dir")
    ap.add_argument("--out", default=None,
                    help="write traces JSON here "
                         "(default <run_dir>/traces.json)")
    ap.add_argument("--chrome", default=None,
                    help="also write a chrome://tracing timeline here")
    ap.add_argument("--json", action="store_true",
                    help="print the full result as JSON")
    args = ap.parse_args(argv)
    result = assemble_run(args.run_dir)
    verdict = tail_latency_attribution(result["traces"])
    result["tail_latency"] = verdict
    out = args.out or os.path.join(args.run_dir, "traces.json")
    fsio.atomic_write_bytes(out, json.dumps(result, indent=2,
                                            sort_keys=True).encode())
    if args.chrome:
        export_chrome_trace(args.chrome, result["traces"])
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))  # noqa: print
    else:
        for t in result["traces"]:
            lat = ("%8.1fms" % t["latency_ms"]
                   if t["latency_ms"] is not None else "   (open)")
            print(f"{t['request_id'] or t['trace_id']:>12} {lat} "  # noqa: print
                  f"cov={t['coverage']:.2f} "
                  f"procs={','.join(t['procs'])} "
                  f"{t['components']}")
        print(f"{result['complete']}/{len(result['traces'])} complete, "  # noqa: print
              f"{len(result['orphan_spans'])} orphan span ids, "
              f"wal {result['wal_matched']}/{result['wal_streams']}")
        if verdict:
            print(f"tail_latency: dominant={verdict['dominant']} "  # noqa: print
                  f"p99={verdict['p99_ms']:.1f}ms "
                  f"median={verdict['median_ms']:.1f}ms")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
