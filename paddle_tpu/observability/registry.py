"""Process-wide metrics registry (ISSUE 3).

Three instrument kinds, one namespace:

- :class:`Counter` — monotone float, ``inc(n)``;
- :class:`Gauge` — last-value-wins, ``set(v)``;
- :class:`Histogram` — exact count/sum/min/max plus a bounded reservoir
  (algorithm R) for percentiles, so a million observations cost a fixed
  few KB.

Instruments are cheap enough for hot paths: an ``inc()`` is one lock
acquire and one float add (well under a microsecond), and nothing ever
touches a sink — sinks only see *event records* pushed through
:meth:`MetricsRegistry.emit`, which returns immediately when no sink is
attached.  That split is the whole design: instruments accumulate
always, events flow only when someone is listening.

The process-global registry (:func:`get_registry`) auto-attaches a JSONL
:class:`~paddle_tpu.observability.sinks.MetricsWriter` when
``PTPU_METRICS_DIR`` is set, so any entry point — ``bench.py``, a user
script, a launcher-spawned worker — lands on the same
``<dir>/worker-<i>.jsonl`` stream without plumbing.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "split_labels"]

METRICS_DIR_ENV = "PTPU_METRICS_DIR"


def split_labels(name: str) -> "tuple[str, Dict[str, str]]":
    """Split an instrument name into ``(base, labels)``.

    Labels ride as a name suffix by convention —
    ``collective.all_reduce.ms[axis=dp,n=8]`` →
    ``("collective.all_reduce.ms", {"axis": "dp", "n": "8"})`` — so the
    registry itself stays label-agnostic.  Unlabeled names come back
    with an empty dict; every reader that aggregates a metric family
    must parse through this helper so labeled and legacy-unlabeled
    series sum without double-counting.
    """
    if not name.endswith("]"):
        return name, {}
    i = name.find("[")
    if i < 0:
        return name, {}
    base, body = name[:i], name[i + 1:-1]
    labels: Dict[str, str] = {}
    for part in body.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k.strip()] = v.strip()
    return base, labels


class Counter:
    """Monotone counter.  ``inc()`` is hot-path safe (< 1 µs/call)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-value instrument (run state, lr scale, live MFU...)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Exact count/sum/min/max + bounded reservoir for percentiles.

    The reservoir is algorithm R: every observation has ``max_samples/n``
    probability of being retained, so percentile estimates stay unbiased
    while memory stays fixed regardless of run length.
    """

    __slots__ = ("name", "max_samples", "_lock", "_samples", "count",
                 "sum", "min", "max", "_rng")

    def __init__(self, name: str, max_samples: int = 512,
                 seed: Optional[int] = None):
        self.name = name
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._samples: List[float] = []
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._rng = random.Random(seed)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._samples) < self.max_samples:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.max_samples:
                    self._samples[j] = v

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
        idx = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
        return s[idx]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
            s = sorted(self._samples)

        def pct(p):
            if not s:
                return None
            return s[min(len(s) - 1,
                         max(0, int(round(p / 100.0 * (len(s) - 1)))))]

        return {"type": "histogram", "count": count, "sum": total,
                "min": lo, "max": hi,
                "mean": (total / count) if count else None,
                "p50": pct(50), "p90": pct(90), "p99": pct(99)}


class MetricsRegistry:
    """Name → instrument map plus the sink fan-out.

    ``emit(kind, **fields)`` stamps a wall-clock ``ts`` and hands the
    record to every attached sink; with no sink it is a two-instruction
    no-op, which is what lets every layer emit unconditionally.
    """

    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._sinks: List[Any] = []
        self._clock = clock

    # -- instruments -------------------------------------------------------
    def _get(self, name: str, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, max_samples: int = 512) -> Histogram:
        return self._get(name, Histogram, max_samples=max_samples)

    # -- sinks -------------------------------------------------------------
    def add_sink(self, sink) -> Any:
        """Attach a sink (``write(record)`` / ``flush()`` / ``close()``).
        Sinks with a ``bind(registry)`` hook get this registry for
        snapshot-style output (Prometheus, stderr summaries)."""
        bind = getattr(sink, "bind", None)
        if bind is not None:
            bind(self)
        with self._lock:
            self._sinks.append(sink)
        return sink

    def remove_sink(self, sink, close: bool = True) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)
        if close:
            sink.close()

    @property
    def sinks(self) -> List[Any]:
        with self._lock:
            return list(self._sinks)

    # -- events ------------------------------------------------------------
    def emit(self, kind: str, ts: Optional[float] = None, **fields) -> None:
        """Push one event record to every sink (no-op with no sinks)."""
        sinks = self._sinks
        if not sinks:
            return
        record = {"ts": float(self._clock() if ts is None else ts),
                  "kind": str(kind)}
        record.update(fields)
        for sink in list(sinks):
            try:
                sink.write(record)
            except Exception as e:
                # a broken sink must never take the run down with it
                from ..framework.log import vlog
                vlog(0, "observability: sink %r dropped a record: %s",
                     type(sink).__name__, e)

    def flush(self) -> None:
        for sink in self.sinks:
            try:
                sink.flush()
            except Exception as e:
                from ..framework.log import vlog
                vlog(0, "observability: sink %r flush failed: %s",
                     type(sink).__name__, e)

    # -- reporting ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """{name: instrument snapshot} for every registered instrument."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.snapshot() for name, m in sorted(metrics.items())}

    def reset(self) -> None:
        """Drop every instrument (tests); sinks stay attached."""
        with self._lock:
            self._metrics.clear()


_global_lock = threading.Lock()
_global: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-global registry.  First call honors
    ``PTPU_METRICS_DIR``: when set, a JSONL
    :class:`~paddle_tpu.observability.sinks.MetricsWriter` for this
    worker is attached under that directory."""
    global _global
    with _global_lock:
        if _global is None:
            _global = MetricsRegistry()
            metrics_dir = os.environ.get(METRICS_DIR_ENV)
            if metrics_dir:
                from .sinks import MetricsWriter
                try:
                    _global.add_sink(MetricsWriter(metrics_dir))
                except OSError as e:
                    from ..framework.log import vlog
                    vlog(0, "observability: cannot attach %s=%s: %s",
                         METRICS_DIR_ENV, metrics_dir, e)
        return _global
