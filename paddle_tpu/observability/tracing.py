"""Typed span tracing (ISSUE 3).

``with span("data_load"): ...`` / ``with span("step"): ...`` nest on a
per-thread stack; a nested span's identity is its *path* ("step/dispatch"),
so the same leaf name under different parents stays distinguishable.
Every span feeds three consumers at once:

- the existing :mod:`paddle_tpu.profiler` host-annotation machinery
  (``RecordEvent`` → jax TraceAnnotation + the flat host table), so spans
  land inside the XPlane device timeline exactly like hand-written
  annotations;
- an aggregated **span tree** (path → count / total ms / self ms, where
  self excludes child spans) — surfaced by ``Profiler.summary()``;
- a bounded in-memory buffer of completed spans, exportable as a
  chrome://tracing JSON via :func:`export_chrome_trace`.

All three are process-wide and thread-safe; the buffer is bounded
(``PTPU_TRACE_BUFFER`` spans, default 65536) so tracing never grows
without bound on long runs.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from ..utils import fsio

__all__ = ["span", "span_tree_totals", "export_chrome_trace",
           "reset_tracing", "trace_events"]

TRACE_BUFFER_ENV = "PTPU_TRACE_BUFFER"

_tls = threading.local()
_lock = threading.Lock()
# path -> [count, total_s, self_s]
_tree: Dict[str, list] = {}
_buffer: deque = deque(
    maxlen=int(os.environ.get(TRACE_BUFFER_ENV, "65536")))


class span:
    """Nesting context manager timing one region of host code.

    >>> with span("step"):
    ...     with span("dispatch"):
    ...         ...        # recorded as "step/dispatch"

    ``elapsed`` (seconds) is available after exit — callers that need the
    number (hapi's step breakdown) read it instead of re-timing.
    """

    __slots__ = ("name", "path", "elapsed", "_t0", "_wall0", "_child",
                 "_event")

    def __init__(self, name: str):
        self.name = str(name)
        self.path = self.name
        self.elapsed = 0.0
        self._child = 0.0

    def __enter__(self) -> "span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        if stack:
            self.path = stack[-1].path + "/" + self.name
        stack.append(self)
        # feed the profiler's host-annotation machinery (TraceAnnotation
        # into the device timeline + the flat host table)
        from .. import profiler
        self._event = profiler.RecordEvent(self.path)
        self._event.begin()
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        self._event.end()
        self.elapsed = dt
        stack = _tls.stack
        stack.pop()
        if stack:
            stack[-1]._child += dt
        self_s = max(0.0, dt - self._child)
        tid = threading.get_ident()
        with _lock:
            row = _tree.get(self.path)
            if row is None:
                _tree[self.path] = [1, dt, self_s]
            else:
                row[0] += 1
                row[1] += dt
                row[2] += self_s
            _buffer.append((self.path, self._wall0, dt, tid))


def span_tree_totals(reset: bool = False) -> Dict[str, Dict[str, float]]:
    """Aggregated span stats: path → {count, total_ms, self_ms} (self
    excludes time spent inside child spans)."""
    with _lock:
        out = {path: {"count": row[0], "total_ms": row[1] * 1e3,
                      "self_ms": row[2] * 1e3}
               for path, row in sorted(_tree.items())}
        if reset:
            _tree.clear()
    return out


def trace_events() -> list:
    """The buffered completed spans as chrome trace events (µs units)."""
    with _lock:
        items = list(_buffer)
    pid = os.getpid()
    return [{"name": path, "ph": "X", "ts": wall0 * 1e6, "dur": dur * 1e6,
             "pid": pid, "tid": tid}
            for path, wall0, dur, tid in items]


def export_chrome_trace(path: str, reset: bool = False) -> int:
    """Write the buffered spans as a chrome://tracing / Perfetto JSON;
    returns the number of events written."""
    events = trace_events()
    payload = json.dumps({"traceEvents": events,
                          "displayTimeUnit": "ms"}).encode("utf-8")
    fsio.atomic_write_bytes(path, payload)
    if reset:
        with _lock:
            _buffer.clear()
    return len(events)


def reset_tracing() -> None:
    """Drop the span tree and the trace buffer (tests)."""
    with _lock:
        _tree.clear()
        _buffer.clear()


def current_span() -> Optional[Any]:
    """The innermost open span on this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None
