"""Pluggable metric sinks (ISSUE 3).

- :class:`MetricsWriter` — the run-scoped JSONL stream,
  ``<dir>/worker-<i>.jsonl``, every byte through the fsync'd
  ``utils/fsio`` seam (so the fault harness can tear/fail telemetry
  writes like any other durable write).  Buffered, and deliberately
  lossy-but-alive under I/O faults: a failed flush keeps the records for
  the next attempt, a full buffer drops the oldest and counts the drops
  — telemetry must never take the run down with it.
- :class:`StderrSummary` — one periodic human-readable line through the
  package logger (``PTPU_METRICS_INTERVAL`` seconds, default 30).
- :class:`PrometheusTextfile` — node-exporter textfile-collector format
  snapshot of every registered instrument, rewritten atomically on the
  same interval.

A sink is anything with ``write(record)`` / ``flush()`` / ``close()``;
an optional ``bind(registry)`` hook receives the registry on attach for
snapshot-style output.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, List, Optional

from ..framework.log import get_logger
from ..utils import fsio

__all__ = ["MetricsWriter", "StderrSummary", "PrometheusTextfile",
           "render_prometheus", "metrics_dir", "default_interval"]

INTERVAL_ENV = "PTPU_METRICS_INTERVAL"


def default_interval() -> float:
    return float(os.environ.get(INTERVAL_ENV, "30"))


def metrics_dir(run_dir: str) -> str:
    """Where a run's telemetry lives: ``<run_dir>/metrics``."""
    return os.path.join(run_dir, "metrics")


class MetricsWriter:
    """JSONL event sink: one ``{"ts", "kind", ...}`` object per line.

    ``directory`` is the metrics directory itself (use
    :func:`metrics_dir` to derive it from a run dir).  ``worker_id``
    defaults to ``jax.process_index()`` so multi-host runs shard into
    ``worker-0.jsonl`` / ``worker-1.jsonl`` / ... streams the launcher's
    aggregator merges back together.
    """

    def __init__(self, directory: str, worker_id: Optional[int] = None,
                 flush_every: int = 32, flush_secs: Optional[float] = None,
                 max_buffered: int = 4096):
        if worker_id is None:
            import jax
            worker_id = jax.process_index()
        os.makedirs(directory, exist_ok=True)
        self.worker_id = int(worker_id)
        self.path = os.path.join(directory,
                                 f"worker-{self.worker_id}.jsonl")
        self.flush_every = int(flush_every)
        self.flush_secs = (default_interval() if flush_secs is None
                           else float(flush_secs))
        self.max_buffered = int(max_buffered)
        self.dropped = 0
        self.written = 0
        self._buf: List[str] = []
        self._last_flush = time.monotonic()

    def write(self, record: Dict[str, Any]) -> None:
        self._buf.append(json.dumps(record, default=str))
        if len(self._buf) > self.max_buffered:
            # the stream is wedged (flushes failing) — stay alive, keep
            # the newest records, and account for the loss
            excess = len(self._buf) - self.max_buffered
            del self._buf[:excess]
            self.dropped += excess
        if (len(self._buf) >= self.flush_every
                or time.monotonic() - self._last_flush >= self.flush_secs):
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        payload = ("\n".join(self._buf) + "\n").encode("utf-8")
        n = len(self._buf)
        try:
            fsio.append_bytes(self.path, payload)
        except OSError as e:
            # keep the buffer for the next flush; telemetry is
            # best-effort by contract
            get_logger().warning(
                "observability: flush of %d records to %s failed: %s",
                n, self.path, e)
            self._last_flush = time.monotonic()
            return
        self.written += n
        del self._buf[:n]
        self._last_flush = time.monotonic()

    def close(self) -> None:
        self.flush()


class StderrSummary:
    """Periodic one-line run summary through the package logger.

    Tracks the latest ``step`` record it sees and, every ``interval``
    seconds, logs step/tokens-per-sec/MFU plus any counters — the
    glanceable "is this run healthy" line for a console tail.
    """

    def __init__(self, interval: Optional[float] = None):
        self.interval = (default_interval() if interval is None
                         else float(interval))
        self._registry = None
        self._last = 0.0
        self._last_step: Optional[Dict[str, Any]] = None
        self.emitted = 0

    def bind(self, registry) -> None:
        self._registry = registry

    def write(self, record: Dict[str, Any]) -> None:
        if record.get("kind") == "step":
            self._last_step = record
        now = time.monotonic()
        if now - self._last < self.interval:
            return
        self._last = now
        self._log_line()

    def _log_line(self) -> None:
        parts = []
        s = self._last_step
        if s is not None:
            parts.append(f"step={s.get('step')}")
            if s.get("step_time_ms") is not None:
                parts.append(f"step_ms={s['step_time_ms']:.1f}")
            if s.get("tokens_per_sec") is not None:
                parts.append(f"tok/s={s['tokens_per_sec']:.0f}")
            if s.get("mfu") is not None:
                parts.append(f"mfu={s['mfu']:.3f}")
        if self._registry is not None:
            snap = self._registry.snapshot()
            for name, m in snap.items():
                if m["type"] == "counter" and m["value"]:
                    parts.append(f"{name}={m['value']:g}")
        get_logger().info("metrics: %s", " ".join(parts) or "(no data)")
        self.emitted += 1

    def flush(self) -> None:
        self._log_line()

    def close(self) -> None:
        pass  # nothing buffered; the logger owns stderr


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
# instrument-name label convention: "memory.bytes_in_use[device=tpu:0]"
# → metric paddle_tpu_memory_bytes_in_use{device="tpu:0"}
_PROM_LABELED = re.compile(r"^(?P<base>[^\[\]]+)\[(?P<labels>[^\]]*)\]$")


def _prom_name(name: str) -> str:
    return "paddle_tpu_" + _PROM_BAD.sub("_", name)


def _prom_label_value(value: str) -> str:
    """Escape a label VALUE per the Prometheus text exposition format
    (backslash, double-quote, newline) — values pass through verbatim
    otherwise, unlike metric/label names which get sanitized."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_parse(name: str):
    """Split an instrument name into (prom metric name, label dict).
    Labels ride in a ``[k=v,k2=v2]`` suffix; names stay sanitized,
    values only escaped (a device label like ``tpu:0`` must survive)."""
    m = _PROM_LABELED.match(name)
    if not m:
        return _prom_name(name), {}
    labels = {}
    for part in m.group("labels").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[_PROM_BAD.sub("_", k.strip())] = v.strip()
    return _prom_name(m.group("base")), labels


def _prom_labels(labels: Dict[str, str], extra: Optional[Dict[str, Any]]
                 = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(f'{k}="{_prom_label_value(v)}"'
                    for k, v in sorted(merged.items()))
    return "{" + body + "}"


def render_prometheus(registry) -> str:
    """Every registered instrument in the Prometheus text exposition
    format — shared by :class:`PrometheusTextfile` (written to disk for
    node_exporter) and the live monitor's ``/metrics`` endpoint
    (ISSUE 5), so a scrape and a textfile snapshot are byte-identical."""
    lines = []
    if registry is None:
        return ""
    typed = set()
    for name, m in registry.snapshot().items():
        pname, labels = _prom_parse(name)
        lb = _prom_labels(labels)
        if m["type"] == "counter":
            if pname not in typed:
                lines.append(f"# TYPE {pname} counter")
                typed.add(pname)
            lines.append(f"{pname}{lb} {m['value']:g}")
        elif m["type"] == "gauge":
            if m["value"] is None:
                continue
            if pname not in typed:
                lines.append(f"# TYPE {pname} gauge")
                typed.add(pname)
            lines.append(f"{pname}{lb} {m['value']:g}")
        else:  # histogram → summary (count/sum + quantile gauges)
            if pname not in typed:
                lines.append(f"# TYPE {pname} summary")
                typed.add(pname)
            for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                if m.get(key) is not None:
                    qlb = _prom_labels(labels, {"quantile": str(q)})
                    lines.append(f"{pname}{qlb} {m[key]:g}")
            lines.append(f"{pname}_sum{lb} {m['sum']:g}")
            lines.append(f"{pname}_count{lb} {m['count']:g}")
    return "\n".join(lines) + ("\n" if lines else "")


class PrometheusTextfile:
    """Textfile-collector exporter: rewrites ``path`` atomically with a
    snapshot of every instrument, at most once per ``interval`` seconds
    (plus on ``flush()``/``close()``).  Point node_exporter's
    ``--collector.textfile.directory`` at the parent directory."""

    def __init__(self, path: str, interval: Optional[float] = None):
        self.path = path
        self.interval = (default_interval() if interval is None
                         else float(interval))
        self._registry = None
        self._last = 0.0

    def bind(self, registry) -> None:
        self._registry = registry

    def write(self, record: Dict[str, Any]) -> None:
        if time.monotonic() - self._last < self.interval:
            return
        self.flush()

    def render(self) -> str:
        return render_prometheus(self._registry)

    def flush(self) -> None:
        self._last = time.monotonic()
        text = self.render()
        try:
            fsio.atomic_write_bytes(self.path, text.encode("utf-8"))
        except OSError as e:
            get_logger().warning(
                "observability: prometheus textfile %s failed: %s",
                self.path, e)

    def close(self) -> None:
        self.flush()
