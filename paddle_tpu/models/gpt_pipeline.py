"""GPT × pipeline parallelism: the full mp×pp×dp hybrid composition.

This is the north-star workload's missing piece (BASELINE config #4): the
reference composes it as ``fleet.distributed_model`` → ``PipelineParallel``
wrapping ``PipelineLayer`` stage cuts (pp_layers.py:132) driven by the 1F1B
``train_batch`` loop (pipeline_parallel.py:152), with tied-embedding grad
sync (``allreduce_shared_weight_gradients``, pipeline_parallel.py:147).

TPU-native rendering:
- the decoder trunk's per-layer params are stage-stacked (S, L, ...) and
  placed ``P('pp', None, <TP spec>)`` — pp × mp composed on one mesh;
- embeddings / final LN / head stay OUTSIDE the pipeline (they are shared,
  not staged): the tied ``wte`` is used by both the embed front and the loss
  head, and because the whole schedule is ONE SPMD program its gradient
  contributions simply add — the reference's shared-weight allreduce has no
  analog to write;
- the schedule is ``one_f_one_b_spmd`` (distributed/pipeline.py): forward
  and backward waves interleaved inside one ``lax.scan``, input stash +
  per-tick ``jax.vjp`` recompute, peak activation memory independent of the
  micro-batch count (the 1F1B property);
- dp shards every micro-batch's batch dim; mp shards heads/ffn inside each
  stage via the mp_layers specs the model already carries.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..distributed import pipeline as pp_mod
from ..distributed.mp_layers import _clean_spec, shard_constraint
from ..distributed.mp_ops import parallel_cross_entropy
from ..distributed.topology import get_mesh
from ..framework import random as fw_random
from ..framework.errors import enforce

LAYER_RE = r"gpt\.h\.(\d+)\.(.*)"
_NAME_FMT = "gpt.h.{i}.{suffix}"


class GPTPipeline:
    """Pipeline-parallel training wrapper around ``GPTForCausalLM``.

    State layout: ``{"stacked": {suffix: (S, L, ...)}, "rest": {name: ...}}``
    — convert with :meth:`split_state` / :meth:`merge_state` (the analog of
    the reference's per-stage param partition, ``SegmentLayers`` uniform cut).
    """

    def __init__(self, model, num_stages: int, num_microbatches: int):
        c = model.config
        enforce(num_stages >= 1, "num_stages must be >= 1")
        enforce(c.num_layers % num_stages == 0,
                f"{c.num_layers} layers not divisible by {num_stages} stages")
        enforce(c.moe_num_experts == 0 or c.moe_every == 1,
                "pipeline needs a homogeneous trunk: MoE models must use "
                "moe_every=1 so every layer has the same parameter set")
        self.model = model
        self.config = c
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.layers_per_stage = c.num_layers // num_stages
        self.template = model.gpt.h[0]

    # -- state management --------------------------------------------------
    def split_state(self, params: Dict[str, Any]) -> Dict[str, Any]:
        stacked, rest = pp_mod.stack_stage_params(
            params, LAYER_RE, self.num_stages)
        return {"stacked": stacked, "rest": rest}

    def merge_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        flat = pp_mod.unstack_stage_params(state["stacked"], _NAME_FMT)
        return {**flat, **state["rest"]}

    def state_shardings(self, mesh=None) -> Optional[Dict[str, Any]]:
        """NamedShardings: stacked params P('pp', None, <TP spec>); rest
        params keep their own pspecs (wte stays vocab-parallel, replicated
        over pp — the tied embedding lives outside the stage cut)."""
        mesh = mesh or get_mesh()
        if mesh is None:
            return None
        layer0 = {name: getattr(p, "pspec", None)
                  for name, p in self.template.named_parameters()}
        stacked_specs = pp_mod.stacked_stage_specs(layer0, layer0, mesh=mesh)
        rest_specs = {}
        for name, p in self.model.named_parameters():
            if name.startswith("gpt.h."):
                continue
            rest_specs[name] = NamedSharding(
                mesh, _clean_spec(mesh, tuple(getattr(p, "pspec", None) or ())))
        return {"stacked": stacked_specs, "rest": rest_specs}

    def place_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        shardings = self.state_shardings()
        if shardings is None:
            return state
        return jax.tree_util.tree_map(
            jax.device_put, state, shardings,
            is_leaf=lambda x: not isinstance(x, dict))

    # -- pipeline pieces ---------------------------------------------------
    def _embed_fn(self, rest, ids_mb, mb_idx, key):
        c = self.config
        s = ids_mb.shape[1]
        with fw_random.key_scope(
                jax.random.fold_in(jax.random.fold_in(key, 1), mb_idx)):
            x = self.model.gpt.wte.apply(
                {"weight": rest["gpt.wte.weight"]}, ids_mb)
            x = x + rest["gpt.wpe"][:s]
            if c.dtype != "float32":
                x = x.astype(c.dtype)
            x = self.model.gpt.drop(x)
        return shard_constraint(x, "dp", None, None)

    def _make_stage_fn(self, key):
        template = self.template
        L = self.layers_per_stage
        n_layers = self.config.num_layers
        M = self.num_microbatches
        from ..distributed.moe import collect_aux_losses

        def stage_fn(pslice, x, mb_idx, stage_idx):
            def body(h, inp):
                pl, li = inp
                # key unique per (micro-batch, global layer): deterministic
                # dropout, distinct across layers AND micro-batches —
                # ≙ the per-op Philox seed/offset attrs of
                # fused_attention_op.cc:292-311
                gl = stage_idx * L + li
                k = jax.random.fold_in(
                    jax.random.fold_in(key, 2), mb_idx * n_layers + gl)
                with collect_aux_losses() as aux_items, fw_random.key_scope(k):
                    h = template.apply(pl, h)
                aux = (sum(aux_items) if aux_items
                       else jnp.zeros((), jnp.float32))
                return h, aux
            h, auxes = lax.scan(body, x, (pslice, jnp.arange(L)))
            # per micro-batch MoE aux, scaled 1/M so the scheduler's total
            # is the mean over micro-batches of the per-layer sum
            return h, jnp.sum(auxes) / M

        return stage_fn

    def _post_fn(self, rest, y, labels_mb):
        ln = self.model.gpt.ln_f
        h = ln.apply({"weight": rest["gpt.ln_f.weight"],
                      "bias": rest["gpt.ln_f.bias"]}, y)
        table = rest["gpt.wte.weight"].astype(h.dtype)
        logits = jnp.einsum("bsh,vh->bsv", h, table)
        logits = shard_constraint(logits, "dp", None, "mp")
        loss = parallel_cross_entropy(
            logits.astype(jnp.float32), labels_mb, reduction="mean")
        return loss / self.num_microbatches

    # -- training ----------------------------------------------------------
    def loss_and_grads(self, state, input_ids, labels, key):
        """Mean causal-LM loss over the batch + grads in state layout."""
        M = self.num_microbatches
        from .gpt import shift_labels
        ids_mb = pp_mod.split_microbatches(input_ids, M)
        # causal shift happens BEFORE the microbatch split (batch-axis
        # split: every microbatch keeps full sequences)
        labels_mb = pp_mod.split_microbatches(shift_labels(labels), M)
        rest, stacked = state["rest"], state["stacked"]

        def embed_all(rest_):
            return jax.vmap(
                lambda i, idx: self._embed_fn(rest_, idx, i, key)
            )(jnp.arange(M), ids_mb)

        acts, embed_pull = jax.vjp(embed_all, rest)
        aux_w = float(self.config.moe_aux_weight)
        losses, aux_total, dstacked, dpost, dinp = pp_mod.one_f_one_b_spmd(
            self._make_stage_fn(key), stacked, acts,
            self._post_fn, rest, labels_mb, has_aux=True, aux_weight=aux_w)
        (drest_embed,) = embed_pull(dinp.astype(acts.dtype))
        # tied wte: head (post) and embedding contributions sum here — the
        # whole of pipeline_parallel.py:147's shared-weight grad allreduce
        grads_rest = jax.tree_util.tree_map(
            lambda a, b: a + b.astype(a.dtype), dpost, drest_embed)
        loss = jnp.sum(losses) + aux_w * aux_total
        return loss, {"stacked": dstacked, "rest": grads_rest}

    def train_batch(self, state, opt, opt_state, input_ids, labels, key):
        """One 1F1B train step (≙ PipelineParallel.train_batch,
        pipeline_parallel.py:152). Jit-compatible; compose under jax.jit
        with donated state for the perf path."""
        loss, grads = self.loss_and_grads(state, input_ids, labels, key)
        new_state, new_opt_state = opt.apply_gradients(
            grads, state, opt_state)
        return loss, new_state, new_opt_state
