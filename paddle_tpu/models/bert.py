"""BERT encoder family (BASELINE config #3: BERT-base pretraining).

Semantic reference: the fused transformer family the reference builds for
exactly this block — fused_attention_op.cc:221-357 with pre_layer_norm=False
(BERT is post-LN: self-attention → bias+dropout+residual+LN via
FusedDropoutLayerNormHelper, fused_dropout_helper.h:207) and
fused_feedforward_op.cc for the intermediate/output FFN.  The model
class/API shape follows the reference's nn.TransformerEncoder doctrine
(python/paddle/nn/layer/transformer.py) since the BERT model itself lives
in PaddleNLP, outside this snapshot.

TPU-first: the same Megatron TP layout as GPT (qkv column-split over heads,
out/ffn row-split), flash-attention routing for the non-causal path, bf16
activations, vocab-parallel MLM loss.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..distributed.mp_layers import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding, shard_constraint)
from ..distributed.mp_ops import parallel_cross_entropy
from ..framework import random as fw_random
from ..framework.errors import enforce
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.initializer import ParamAttr
from ..nn.layer import Layer, Parameter
from ..nn.layers import Dropout, Embedding, LayerNorm, Linear

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "bert_tiny", "bert_base",
           "bert_large"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30528          # padded to a multiple of 64 for the MXU
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None  # default 4*hidden
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02
    use_pallas_attention: bool = False
    dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        enforce(self.hidden_size % self.num_heads == 0,
                "num_heads must evenly divide hidden_size")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def _normal(std):
    return I.Normal(mean=0.0, std=std)


class BertSelfAttention(Layer):
    """Bidirectional self-attention, TP over heads; ≙ fused_attention_op's
    FMHA path with SrcMask (the additive padding mask, cc:237)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.config = c
        std = c.initializer_range
        self.qkv_proj = ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, gather_output=False,
            weight_attr=ParamAttr(initializer=_normal(std)))
        self.out_proj = RowParallelLinear(
            c.hidden_size, c.hidden_size, input_is_parallel=True,
            weight_attr=ParamAttr(initializer=_normal(std)))
        self.attn_dropout_p = c.attention_dropout

    def forward(self, x, attn_mask=None):
        c = self.config
        b, s, _ = x.shape
        qkv = self.qkv_proj(x)
        # head-major fused dim: mp sharding factors onto heads through the
        # reshape (same layout rationale as GPTAttention)
        qkv = qkv.reshape(b, s, c.num_heads, 3, c.head_dim)
        qkv = shard_constraint(qkv, "dp", None, "mp", None, None)
        q = qkv[:, :, :, 0].transpose(0, 2, 1, 3)
        k = qkv[:, :, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, :, 2].transpose(0, 2, 1, 3)
        if (c.use_pallas_attention and attn_mask is None
                and not (self.attn_dropout_p > 0 and self.training)):
            from ..ops import flash_attention
            out = flash_attention(q, k, v, causal=False, dropout_p=0.0,
                                  training=self.training)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=False,
                dropout_p=self.attn_dropout_p, training=self.training)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, c.hidden_size)
        return self.out_proj(out)


class BertLayer(Layer):
    """Post-LN encoder block: attn → dropout+residual+LN → FFN →
    dropout+residual+LN (fused_attention_op pre_layer_norm=False +
    fused_feedforward_op semantics)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        std = c.initializer_range
        self.attn = BertSelfAttention(c)
        self.attn_dropout = Dropout(c.hidden_dropout)
        self.attn_ln = LayerNorm(c.hidden_size, epsilon=c.layer_norm_epsilon)
        self.fc_in = ColumnParallelLinear(
            c.hidden_size, c.intermediate_size, gather_output=False,
            weight_attr=ParamAttr(initializer=_normal(std)))
        self.fc_out = RowParallelLinear(
            c.intermediate_size, c.hidden_size, input_is_parallel=True,
            weight_attr=ParamAttr(initializer=_normal(std)))
        self.ffn_dropout = Dropout(c.hidden_dropout)
        self.ffn_ln = LayerNorm(c.hidden_size, epsilon=c.layer_norm_epsilon)

    def forward(self, x, attn_mask=None):
        h = self.attn(x, attn_mask=attn_mask)
        x = self.attn_ln(x + self.attn_dropout(h))
        h = self.fc_out(F.gelu(self.fc_in(x)))
        return self.ffn_ln(x + self.ffn_dropout(h))


class BertEmbeddings(Layer):
    """word + position + token-type embeddings → LN → dropout."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        std = c.initializer_range
        self.word_embeddings = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size,
            weight_attr=ParamAttr(initializer=_normal(std)))
        self.position_embeddings = Embedding(
            c.max_position_embeddings, c.hidden_size,
            weight_attr=ParamAttr(initializer=_normal(std)))
        self.token_type_embeddings = Embedding(
            c.type_vocab_size, c.hidden_size,
            weight_attr=ParamAttr(initializer=_normal(std)))
        self.layer_norm = LayerNorm(c.hidden_size,
                                    epsilon=c.layer_norm_epsilon)
        self.dropout = Dropout(c.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = jnp.arange(s)
        x = self.word_embeddings(input_ids)
        x = x + self.position_embeddings(pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertModel(Layer):
    """Encoder backbone (+ tanh pooler over [CLS], the reference BertPooler
    shape)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.config = c
        self.embeddings = BertEmbeddings(c)
        from ..nn.layer import LayerList
        self.encoder = LayerList([BertLayer(c) for _ in range(c.num_layers)])
        self.pooler = Linear(c.hidden_size, c.hidden_size,
                             weight_attr=ParamAttr(
                                 initializer=_normal(c.initializer_range)))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        c = self.config
        x = self.embeddings(input_ids, token_type_ids)
        if c.dtype != "float32":
            x = x.astype(c.dtype)
        x = shard_constraint(x, "dp", None, None)
        mask = None
        if attention_mask is not None:
            # (b, s) {0,1} → additive (b, 1, 1, s), the SrcMask layout
            mask = (1.0 - attention_mask[:, None, None, :].astype(x.dtype))
            mask = mask * jnp.asarray(-1e9, x.dtype)
        for layer in self.encoder:
            x = layer(x, attn_mask=mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(Layer):
    """MLM + NSP pretraining head; MLM logits tied to the word embedding,
    loss vocab-parallel (c_softmax_with_cross_entropy semantics)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.config = c
        self.bert = BertModel(c)
        std = c.initializer_range
        self.transform = Linear(c.hidden_size, c.hidden_size,
                                weight_attr=ParamAttr(
                                    initializer=_normal(std)))
        self.transform_ln = LayerNorm(c.hidden_size,
                                      epsilon=c.layer_norm_epsilon)
        self.mlm_bias = Parameter(jnp.zeros((c.vocab_size,), jnp.float32),
                                  is_bias=True)
        self.mlm_bias.pspec = P("mp")
        self.nsp = Linear(c.hidden_size, 2,
                          weight_attr=ParamAttr(initializer=_normal(std)))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                mlm_labels=None, nsp_labels=None):
        c = self.config
        hidden, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_ln(F.gelu(self.transform(hidden)))
        table = self.bert.embeddings.word_embeddings.weight.value
        logits = jnp.einsum("bsh,vh->bsv", h, table.astype(h.dtype))
        logits = logits + self.mlm_bias.value.astype(h.dtype)
        logits = shard_constraint(logits, "dp", None, "mp")
        nsp_logits = self.nsp(pooled)
        if mlm_labels is None:
            return logits, nsp_logits
        # MLM: only positions with label != -100 count (standard masking)
        valid = (mlm_labels != -100)
        safe_labels = jnp.where(valid, mlm_labels, 0)
        per_tok = parallel_cross_entropy(
            logits.astype(jnp.float32), safe_labels, reduction="none")
        denom = jnp.maximum(jnp.sum(valid), 1)
        loss = jnp.sum(per_tok * valid) / denom
        if nsp_labels is not None:
            nsp_loss = jnp.mean(F.cross_entropy(
                nsp_logits.astype(jnp.float32), nsp_labels))
            loss = loss + nsp_loss
        return loss, logits


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        loss = jnp.mean(F.cross_entropy(logits.astype(jnp.float32), labels))
        return loss, logits


def _cfg(defaults: Dict[str, Any], kw: Dict[str, Any]) -> BertConfig:
    return BertConfig(**{**defaults, **kw})


def bert_tiny(**kw) -> BertConfig:
    return _cfg(dict(hidden_size=128, num_layers=2, num_heads=4,
                     vocab_size=1024, max_position_embeddings=128), kw)


def bert_base(**kw) -> BertConfig:
    return _cfg(dict(hidden_size=768, num_layers=12, num_heads=12), kw)


def bert_large(**kw) -> BertConfig:
    return _cfg(dict(hidden_size=1024, num_layers=24, num_heads=16), kw)
