"""GPT: decoder-only transformer for causal-LM pretraining — the north-star
workload (BASELINE.json config #4: GPT-3 1.3B/6.7B hybrid-parallel).

Semantic reference: the fused transformer family the reference builds for
exactly this model — fused_attention_op.cc:221-357 (pre-LN → QKV GEMM → FMHA
→ out proj → bias+dropout+residual), fused_feedforward_op.cc, and the
Megatron TP layers (fleet/meta_parallel/mp_layers.py:30,97,170) this model
instantiates for the hybrid configs.

TPU-first design:
- every Linear is Column/RowParallel with GSPMD PartitionSpecs — serial when
  no mesh, Megatron-TP when fleet.init gives mp>1; no per-rank weight code.
- attention heads shard over mp (qkv column-split = head split);
- activations carry (dp, None, mp-on-hidden) constraints at layer borders —
  the "sequence of sharded GEMMs" layout from the scaling-book recipe;
- dropout keys are counter-based via framework.random.key_scope, TP-safe via
  the RNGStatesTracker fold-in (distributed/random.py);
- optional per-layer recompute (jax.checkpoint) for the 1.3B+ configs;
- logits tied to the embedding table; loss is the vocab-parallel CE
  (c_softmax_with_cross_entropy semantics, distributed/mp_ops.py).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..distributed.fleet.recompute import recompute
from ..distributed.mp_layers import (ColumnParallelLinear, RowParallelLinear,
                                     VocabParallelEmbedding, shard_constraint)
from ..distributed.mp_ops import parallel_cross_entropy
from ..framework import random as fw_random
from ..framework.errors import enforce
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.initializer import ParamAttr
from ..nn.layer import Layer, Parameter
from ..nn.layers import Dropout, LayerNorm


def shift_labels(labels, ignore_index: int = -100):
    """Causal-LM label shift: position t is scored against token t+1.

    ``labels`` is the same (B, S) id tensor as the input (the standard
    causal-LM calling convention); the roll keeps the (B, S) shape so
    sp/pp shardings are untouched, and the final position is masked with
    ``ignore_index`` (consumed by parallel_cross_entropy)."""
    shifted = jnp.roll(labels, -1, axis=1)
    return shifted.at[:, -1].set(ignore_index)


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # padded to a multiple of 128 for the MXU
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: Optional[int] = None   # default 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_recompute: bool = False
    recompute_policy: Optional[str] = None
    use_pallas_attention: bool = False   # flash-attention kernel (ops/)
    # block-level fused execution (ISSUE 7, ops/fused_block.py): routes the
    # whole pre-LN block — LN→QKV→attention→out-proj epilogue and
    # LN→GEMM→gelu→GEMM→residual — through the fused kernel surfaces (Pallas
    # on TPU, the jnp composition elsewhere; PTPU_FUSED_BLOCK forces a
    # route).  Train, fixed-shape decode, and paged serving paths all honor
    # it; MoE layers and sp/cp configs stay on the unfused path.
    use_fused_block: bool = False
    dtype: str = "float32"               # activation dtype ("bfloat16" on TPU)
    # long-sequence parallelism over the 'sp' mesh axis (additive TPU-native
    # capability; the reference has none — SURVEY §5):
    #   sequence_parallel: Ulysses-style — activations seq-sharded, heads
    #     resharded over mp×sp inside attention (GSPMD emits the all-to-alls)
    #   context_parallel: ring attention — no device ever holds the full
    #     sequence; KV chunks rotate via ppermute (distributed/
    #     sequence_parallel.py)
    sequence_parallel: bool = False
    context_parallel: bool = False
    # MoE (BASELINE config #5, ERNIE-MoE style): 0 experts = dense FFN.
    # moe_every=2 alternates dense/MoE like GShard; 1 = every layer (needed
    # for the homogeneous-trunk pipeline path).
    moe_num_experts: int = 0
    moe_gate: str = "gshard"
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    moe_every: int = 2
    # memory-efficient LM loss (ops/fused.py linear_softmax_cross_entropy):
    # never materializes the [B, S, V] logits/softmax — measured on v5e this
    # is the top HLO temp of the naive path (benchmarks/batch_scan_125m.json)
    fused_lm_loss: bool = True

    def is_moe_layer(self, index: int) -> bool:
        return (self.moe_num_experts > 0
                and index % self.moe_every == self.moe_every - 1)

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size
        enforce(self.hidden_size % self.num_heads == 0,
                "num_heads must evenly divide hidden_size")
        enforce(not (self.context_parallel and self.attention_dropout > 0),
                "context_parallel (ring attention) does not implement "
                "attention-probability dropout; set attention_dropout=0 "
                "(hidden_dropout is unaffected)")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def _normal(std):
    return I.Normal(mean=0.0, std=std)


class GPTAttention(Layer):
    """Causal self-attention, TP over heads (qkv column-split = head split,
    reference mp_layers.py usage in the fleet GPT; fused semantics ≙
    fused_attention_op.cc FMHA path)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.config = c
        std = c.initializer_range
        # fused qkv: one (h, 3h) GEMM keeps the MXU busy (reference
        # attn_gemm.h AttnMatMul computes qkv as a single GEMM likewise)
        self.qkv_proj = ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, gather_output=False,
            weight_attr=ParamAttr(initializer=_normal(std)))
        # GPT-2 style scaled init on residual-out projections
        self.out_proj = RowParallelLinear(
            c.hidden_size, c.hidden_size, input_is_parallel=True,
            weight_attr=ParamAttr(
                initializer=_normal(std / math.sqrt(2.0 * c.num_layers))))
        self.attn_dropout_p = c.attention_dropout
        self.resid_dropout = Dropout(c.hidden_dropout)

    def forward(self, x, cache=None):
        from ..distributed.topology import get_mesh
        c = self.config
        b, s, _ = x.shape
        qkv = self.qkv_proj(x)                      # (b, s, 3h) mp-sharded
        # head-major column order (head0: q|k|v, head1: q|k|v, ...): the mp
        # sharding of the fused dim then factors onto `heads`, the outer
        # reshape factor, so GSPMD propagates it through the reshape instead
        # of involuntarily rematerializing (a (3, heads, ...) factorization
        # would need mp | 3)
        qkv = qkv.reshape(b, s, c.num_heads, 3, c.head_dim)
        seq_ax = "sp" if c.sequence_parallel or c.context_parallel else None
        qkv = shard_constraint(qkv, "dp", seq_ax, "mp", None, None)
        q = qkv[:, :, :, 0].transpose(0, 2, 1, 3)   # (b, heads, s, d)
        k = qkv[:, :, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, :, 2].transpose(0, 2, 1, 3)
        if cache is not None:
            from ..inference.kv_cache import PagedLayerCache
            if isinstance(cache, PagedLayerCache):
                # serving path (ISSUE 6): KV lands in shared fixed-size
                # blocks addressed by per-sequence tables; ragged decode
                # batches ride the paged-attention kernel.  Single-host
                # only (pallas_call / the page scatter are opaque to
                # GSPMD) — the serving engine enforces that.
                out, new_cache = self._paged_cache_forward(q, k, v, cache,
                                                          b, s)
                return self.resid_dropout(self.out_proj(out)), new_cache
        if cache is not None:
            # fixed-shape cache (k_buf, v_buf, used): write the new chunk at
            # `used` and attend with an explicit causal+validity mask — no
            # shape growth, so the jitted decode step never retraces
            k_buf, v_buf, used = cache
            k_buf = lax.dynamic_update_slice(
                k_buf, k.astype(k_buf.dtype), (0, 0, used, 0))
            v_buf = lax.dynamic_update_slice(
                v_buf, v.astype(v_buf.dtype), (0, 0, used, 0))
            L = k_buf.shape[2]
            if c.use_pallas_attention and s == 1 and L % 8 == 0 \
                    and c.head_dim % 8 == 0 and get_mesh() is None:
                # single-token decode rides the streaming cache kernel:
                # only blocks holding real entries are read (dynamic trip
                # count on the traced length — reference CacheKV path).
                # Mesh-gated like functional.py's routing: pallas_call is
                # opaque to GSPMD, so sharded decode stays on the
                # partitionable SDPA branch
                from ..ops import flash_attention_kvcache
                out = flash_attention_kvcache(q, k_buf, v_buf, used + 1)
            else:
                rows = used + jnp.arange(s)             # query positions
                cols = jnp.arange(L)
                bias = jnp.where(cols[None, :] <= rows[:, None], 0.0, -1e9)
                out = F.scaled_dot_product_attention(
                    q, k_buf, v_buf,
                    attn_mask=bias[None, None].astype(q.dtype),
                    is_causal=False, dropout_p=0.0, training=False)
            out = out.transpose(0, 2, 1, 3).reshape(b, s, c.hidden_size)
            out = self.resid_dropout(self.out_proj(out))
            return out, (k_buf, v_buf, used + s)
        if c.context_parallel and cache is None:
            # ring attention: seq stays sharded, KV chunks rotate the ring
            from ..distributed.sequence_parallel import (
                ring_attention_sharded)
            mesh = get_mesh()
            if mesh is not None and "sp" in mesh.axis_names:
                out = ring_attention_sharded(q, k, v, causal=True)
            else:  # serial fallback (tests / meshes without an sp axis)
                out = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True,
                    dropout_p=self.attn_dropout_p, training=self.training)
        else:
            if c.sequence_parallel:
                # Ulysses layout change: full seq per shard, heads over
                # mp×sp — the pair of constraints IS the all-to-all pair
                q = shard_constraint(q, "dp", ("mp", "sp"), None, None)
                k = shard_constraint(k, "dp", ("mp", "sp"), None, None)
                v = shard_constraint(v, "dp", ("mp", "sp"), None, None)
            if c.use_pallas_attention and cache is None:
                from ..ops import flash_attention
                out = flash_attention(
                    q, k, v, causal=True, dropout_p=self.attn_dropout_p,
                    training=self.training)
            else:
                out = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, dropout_p=self.attn_dropout_p,
                    training=self.training)
            if c.sequence_parallel:
                out = shard_constraint(out, "dp", ("mp", "sp"), None, None)
        out = out.transpose(0, 2, 1, 3)             # (b, s, heads, d)
        out = shard_constraint(out, "dp", seq_ax, "mp", None)
        out = out.reshape(b, s, c.hidden_size)
        return self.resid_dropout(self.out_proj(out))

    def _paged_cache_forward(self, q, k, v, cache, b, s):
        """Paged-KV attention (ISSUE 6 serving path).

        Writes this call's k/v into the shared page arrays at
        ``cache.slot_mapping`` (padding slots are out of bounds and
        dropped), then attends:

        - ``s == 1`` (batched decode): ragged paged attention over the
          block tables up to ``seq_lens`` — each row sees its own
          context length (inference/paged_attention.py);
        - ``s > 1`` (prefill chunk): the context IS the chunk (recompute
          prefill after preemption included — the table was freed), so a
          causal in-chunk mask with ``cols < seq_lens`` masking the pad
          columns is exact.
        """
        from ..inference.paged_attention import paged_attention
        c = self.config
        new_k = k.transpose(0, 2, 1, 3).reshape(b * s, c.num_heads,
                                                c.head_dim)
        new_v = v.transpose(0, 2, 1, 3).reshape(b * s, c.num_heads,
                                                c.head_dim)
        slots = cache.slot_mapping.reshape(-1)
        k_pages = cache.k_pages.at[slots].set(
            new_k.astype(cache.k_pages.dtype), mode="drop")
        v_pages = cache.v_pages.at[slots].set(
            new_v.astype(cache.v_pages.dtype), mode="drop")
        if s == 1:
            o = paged_attention(q[:, :, 0, :], k_pages, v_pages,
                                cache.block_tables, cache.seq_lens,
                                block_size=cache.block_size)
            out = o.astype(q.dtype).reshape(b, 1, c.hidden_size)
        else:
            rows = jnp.arange(s)
            cols = jnp.arange(s)
            causal = cols[None, :] <= rows[:, None]              # (s, s)
            valid = cols[None, None, :] < cache.seq_lens[:, None, None]
            bias = jnp.where(causal[None, :, :] & valid, 0.0, -1e9)
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=bias[:, None].astype(q.dtype),
                is_causal=False, dropout_p=0.0, training=False)
            out = out.transpose(0, 2, 1, 3).reshape(b, s, c.hidden_size)
        return out, cache.replace(k_pages=k_pages, v_pages=v_pages)

    def fused_paged_forward(self, x, ln, cache):
        """Fused-epilogue serving step (ISSUE 7): LN→QKV as one fused
        kernel pass, the PR 6 paged attention in the middle, out-proj +
        residual as the fused epilogue.  Returns the residual-added block
        output (the caller skips its own ``x + attn(ln(x))``)."""
        from ..ops.fused_block import fused_linear_residual, fused_ln_linear
        c = self.config
        b, s, _ = x.shape
        qkv = fused_ln_linear(x, self.qkv_proj.weight, self.qkv_proj.bias,
                              ln.weight, ln.bias, epsilon=ln.epsilon)
        qkv = qkv.reshape(b, s, c.num_heads, 3, c.head_dim)
        q = qkv[:, :, :, 0].transpose(0, 2, 1, 3)
        k = qkv[:, :, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, :, 2].transpose(0, 2, 1, 3)
        out, new_cache = self._paged_cache_forward(q, k, v, cache, b, s)
        y = fused_linear_residual(out, self.out_proj.weight,
                                  self.out_proj.bias, x,
                                  dropout_p=0.0, training=False)
        return y, new_cache


class GPTMLP(Layer):
    """h → 4h → h, gelu; TP column/row split (reference
    fused_feedforward_op.cc semantics)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.fc_in = ColumnParallelLinear(
            c.hidden_size, c.ffn_hidden_size, gather_output=False,
            weight_attr=ParamAttr(initializer=_normal(c.initializer_range)))
        self.fc_out = RowParallelLinear(
            c.ffn_hidden_size, c.hidden_size, input_is_parallel=True,
            weight_attr=ParamAttr(initializer=_normal(
                c.initializer_range / math.sqrt(2.0 * c.num_layers))))
        self.dropout = Dropout(c.hidden_dropout)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x))))


class GPTDecoderLayer(Layer):
    """Pre-LN block (reference fused_attention_op pre_layer_norm=True path +
    fused_feedforward).  With ``config.is_moe_layer(index)`` the FFN is a
    capacity-bucketed MoELayer over the ``ep`` mesh axis (ERNIE-MoE)."""

    def __init__(self, config: GPTConfig, index: int = 0):
        super().__init__()
        c = config
        self.config = c
        self.ln_1 = LayerNorm(c.hidden_size, epsilon=c.layer_norm_epsilon)
        self.attn = GPTAttention(c)
        self.ln_2 = LayerNorm(c.hidden_size, epsilon=c.layer_norm_epsilon)
        self._is_moe = c.is_moe_layer(index)
        if self._is_moe:
            from ..distributed.moe import MoELayer
            self.mlp = MoELayer(
                c.hidden_size, c.ffn_hidden_size, c.moe_num_experts,
                gate=c.moe_gate, capacity_factor=c.moe_capacity_factor,
                dropout_p=c.hidden_dropout,
                weight_attr=ParamAttr(
                    initializer=_normal(c.initializer_range)),
                out_weight_attr=ParamAttr(initializer=_normal(
                    c.initializer_range / math.sqrt(2.0 * c.num_layers))))
        else:
            self.mlp = GPTMLP(c)
        self._use_recompute = c.use_recompute
        self._recompute_policy = c.recompute_policy

    def _fused_block_ok(self) -> bool:
        """use_fused_block eligibility: the fused ops are single-program
        (a pallas_call is opaque to GSPMD — same gating as the flash
        decode kernel) and cover the dense pre-LN block only."""
        c = self.config
        if not c.use_fused_block or self._is_moe:
            return False
        if c.sequence_parallel or c.context_parallel:
            return False
        from ..distributed.topology import get_mesh
        return get_mesh() is None

    def _block_fused(self, x):
        """ISSUE 7 hot path: the two halves of the block as fused ops
        (ops/fused_block.py) — Pallas kernels on TPU, the jnp composition
        as the CPU default and interpret oracle."""
        from ..ops.fused_block import fused_attention_block, fused_ffn_block
        c = self.config
        a = self.attn
        x = fused_attention_block(
            x, a.qkv_proj.weight, a.qkv_proj.bias, a.out_proj.weight,
            a.out_proj.bias, self.ln_1.weight, self.ln_1.bias,
            num_heads=c.num_heads, causal=True,
            epsilon=c.layer_norm_epsilon, attn_dropout=c.attention_dropout,
            hidden_dropout=c.hidden_dropout, training=self.training)
        m = self.mlp
        x = fused_ffn_block(
            x, m.fc_in.weight, m.fc_in.bias, m.fc_out.weight, m.fc_out.bias,
            self.ln_2.weight, self.ln_2.bias, activation="gelu",
            dropout2=c.hidden_dropout, epsilon=c.layer_norm_epsilon,
            training=self.training)
        return x, jnp.zeros((), jnp.float32)

    def _fused_cache_forward(self, x, cache):
        """Fused decode step (ISSUE 7): covers both the fixed-shape
        (k_buf, v_buf, used) cache and the PR 6 paged cache."""
        from ..inference.kv_cache import PagedLayerCache
        from ..ops.fused_block import (fused_attention_block_kvcache,
                                       fused_ffn_block)
        c = self.config
        if isinstance(cache, PagedLayerCache):
            x, new_cache = self.attn.fused_paged_forward(x, self.ln_1,
                                                         cache)
        else:
            k_buf, v_buf, used = cache
            a = self.attn
            x, k_buf, v_buf = fused_attention_block_kvcache(
                x, a.qkv_proj.weight, a.qkv_proj.bias, a.out_proj.weight,
                a.out_proj.bias, self.ln_1.weight, self.ln_1.bias,
                k_buf, v_buf, used, num_heads=c.num_heads,
                epsilon=c.layer_norm_epsilon)
            new_cache = (k_buf, v_buf, used + x.shape[1])
        m = self.mlp
        x = fused_ffn_block(
            x, m.fc_in.weight, m.fc_in.bias, m.fc_out.weight, m.fc_out.bias,
            self.ln_2.weight, self.ln_2.bias, activation="gelu",
            dropout2=c.hidden_dropout, epsilon=c.layer_norm_epsilon,
            training=self.training)
        return x, new_cache

    def _block(self, x):
        """Returns (x, aux): MoE aux losses are collected INSIDE so they
        cross the jax.checkpoint boundary as a real remat output instead of
        leaking a tracer through the thread-local side channel."""
        if self._fused_block_ok():
            return self._block_fused(x)
        from ..distributed.moe import collect_aux_losses
        with collect_aux_losses() as aux_items:
            x = x + self.attn(self.ln_1(x))
            x = x + self.mlp(self.ln_2(x))
        aux = sum(aux_items) if aux_items else jnp.zeros((), jnp.float32)
        return x, aux

    def forward(self, x, cache=None):
        from ..distributed.moe import _record_aux
        if cache is not None:
            if self._fused_block_ok():
                return self._fused_cache_forward(x, cache)
            h, new_cache = self.attn(self.ln_1(x), cache=cache)
            x = x + h
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        if self._use_recompute:
            x, aux = recompute(self._block, x, policy=self._recompute_policy)
        else:
            x, aux = self._block(x)
        if self._is_moe:
            _record_aux(aux)
        return x


class GPTModel(Layer):
    """Embeddings + decoder stack + final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.config = c
        self.wte = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size,
            weight_attr=ParamAttr(initializer=_normal(c.initializer_range)))
        self.wpe = Parameter(_normal(c.initializer_range)(
            fw_random.next_key(),
            (c.max_position_embeddings, c.hidden_size), jnp.float32))
        self.wpe.pspec = P(None, None)
        self.drop = Dropout(c.hidden_dropout)
        from ..nn.layer import LayerList
        self.h = LayerList([GPTDecoderLayer(c, i)
                            for i in range(c.num_layers)])
        self.ln_f = LayerNorm(c.hidden_size, epsilon=c.layer_norm_epsilon)

    def forward(self, input_ids, position_offset: int = 0, caches=None):
        c = self.config
        b, s = input_ids.shape
        # traced-offset form: position_offset may be a traced scalar in the
        # jitted decode step (jnp.arange(traced, ...) would fail); the
        # serving engine passes a (b,) vector — every ragged-batch row
        # decodes at its own position
        off = jnp.asarray(position_offset)
        pos = (off[:, None] + jnp.arange(s) if off.ndim
               else off + jnp.arange(s))
        x = self.wte(input_ids) + self.wpe.value[pos]
        if c.dtype != "float32":
            x = x.astype(c.dtype)
        x = self.drop(x)
        seq_ax = ("sp" if c.sequence_parallel or c.context_parallel
                  else None)
        x = shard_constraint(x, "dp", seq_ax, None)
        new_caches = []
        for i, layer in enumerate(self.h):
            if caches is not None:
                x, kv = layer(x, cache=caches[i])
                new_caches.append(kv)
            else:
                x = layer(x)
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x


class GPTForCausalLM(Layer):
    """LM head tied to the embedding; loss = vocab-parallel softmax CE
    (c_softmax_with_cross_entropy semantics)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)

    def forward(self, input_ids, labels=None):
        from ..distributed.moe import collect_aux_losses
        with collect_aux_losses() as aux_losses:
            hidden = self.gpt(input_ids)        # (b, s, h)
        # tied head: logits = h @ wte.T → vocab-sharded over mp
        c = self.config
        table = self.gpt.wte.weight.value.astype(hidden.dtype)
        seq_ax = ("sp" if c.sequence_parallel or c.context_parallel
                  else None)

        def full_logits():
            lg = jnp.einsum("bsh,vh->bsv", hidden, table)
            return shard_constraint(lg, "dp", seq_ax, "mp")

        if labels is None:
            return full_logits()
        shifted = shift_labels(labels)
        from ..distributed.mp_ops import _in_axis
        from ..ops.fused import _lce_chunk, linear_softmax_cross_entropy
        if (c.fused_lm_loss and not _in_axis("mp")
                and _lce_chunk(hidden.shape[1]) is not None):
            # memory-efficient path: loss from (hidden, table) directly —
            # the full [B, S, V] logits are never built (the 16GB-chip
            # budget that makes the full-vocab 1.3B trainable at all, see
            # BASELINE.md), so the logits slot of the return is None; set
            # fused_lm_loss=False to get (loss, logits)
            loss = linear_softmax_cross_entropy(
                hidden, table, shifted,
                logits_spec=("dp", seq_ax, "mp"), reduction="mean")
            logits = None
        else:
            # shard_map vocab-parallel contexts and irregular sequence
            # lengths keep the c_softmax_with_cross_entropy path
            logits = full_logits()
            loss = parallel_cross_entropy(
                logits.astype(jnp.float32), shifted, reduction="mean")
        if aux_losses:
            loss = loss + self.config.moe_aux_weight * sum(aux_losses)
        return loss, logits

    def build_pipeline(self, num_stages: int, num_microbatches: int):
        """Pipeline-parallel wrapper (used by fleet.distributed_model when
        pp_degree > 1; ≙ fleet_base.py:1027 selecting PipelineParallel)."""
        from .gpt_pipeline import GPTPipeline
        return GPTPipeline(self, num_stages, num_microbatches)

    def generate_step(self, input_ids, caches, position_offset: int):
        """Single decode step with KV caches (reference CacheKV path,
        fused_attention_op.cc:235)."""
        hidden, new_caches = self.gpt(
            input_ids, position_offset=position_offset, caches=caches)
        table = self.gpt.wte.weight.value.astype(hidden.dtype)
        logits = jnp.einsum("bsh,vh->bsv", hidden[:, -1:], table)
        return logits, new_caches

    def serving_step(self, input_ids, caches, position_offset, last_index):
        """One serving-engine step over paged caches (ISSUE 6): runs the
        stack, gathers the hidden state at ``last_index`` per row (the
        last *real* token of a padded prefill chunk; 0 for single-token
        decode), and returns its tied-head logits.

        Unlike :meth:`generate_step` this works for ragged padded
        chunks — ``hidden[:, -1]`` of a padded prefill is a pad
        position.  Returns ``(logits (b, vocab), new_caches)``.
        """
        hidden, new_caches = self.gpt(
            input_ids, position_offset=position_offset, caches=caches)
        b = hidden.shape[0]
        idx = jnp.broadcast_to(jnp.asarray(last_index, jnp.int32), (b,))
        h_last = hidden[jnp.arange(b), idx]              # (b, h)
        table = self.gpt.wte.weight.value.astype(h_last.dtype)
        logits = jnp.einsum("bh,vh->bv", h_last, table)
        return logits, new_caches

    def make_caches(self, batch_size: int, max_length: int):
        """Fixed-shape KV caches (one (k_buf, v_buf, used) triple per
        layer) for jitted decoding — preallocated so every decode step has
        identical shapes (no retracing), written via dynamic_update_slice:
        the static-shape rendering of the reference's growing CacheKV."""
        c = self.config
        dt = jnp.dtype(c.dtype) if c.dtype != "float32" else jnp.float32
        shape = (batch_size, c.num_heads, max_length, c.head_dim)
        return [(jnp.zeros(shape, dt), jnp.zeros(shape, dt),
                 jnp.asarray(0, jnp.int32)) for _ in range(c.num_layers)]

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 key=None, eos_token_id: Optional[int] = None):
        """Autoregressive decoding: ONE jitted step (prefill reuses it with
        the prompt chunk) over fixed-shape caches; temperature 0 = greedy,
        else sampling (optionally top-k truncated)."""
        c = self.config
        self.eval()
        params = self.state_dict()
        ids = jnp.asarray(input_ids, jnp.int32)
        b, prompt_len = ids.shape
        if max_new_tokens <= 0:
            return ids
        total = prompt_len + max_new_tokens
        enforce(total <= c.max_position_embeddings,
                f"{total} positions exceed max_position_embeddings "
                f"({c.max_position_embeddings})")
        if key is None:
            key = fw_random.next_key()
        step = self._gen_step(float(temperature), int(top_k))

        caches = self.make_caches(b, total)
        out = [ids]
        key, sub = jax.random.split(key)
        nxt, caches = step(params, ids, caches,
                           jnp.asarray(0, jnp.int32), sub)
        out.append(nxt[:, None])
        finished = np.asarray(nxt == eos_token_id) \
            if eos_token_id is not None else None
        for i in range(1, max_new_tokens):
            key, sub = jax.random.split(key)
            # traced position: a python int would retrace every step
            nxt, caches = step(params, nxt[:, None], caches,
                               jnp.asarray(prompt_len + i - 1, jnp.int32),
                               sub)
            if eos_token_id is not None:
                # finished rows stay pinned to EOS (reference generate pads
                # completed sequences instead of sampling garbage)
                nxt = jnp.where(jnp.asarray(finished), eos_token_id, nxt)
                finished = finished | np.asarray(nxt == eos_token_id)
            out.append(nxt[:, None])
            if eos_token_id is not None and bool(np.all(finished)):
                break
        return jnp.concatenate(out, axis=1)

    def _gen_step(self, temperature: float, top_k: int):
        """One jitted decode step, cached per (temperature, top_k) on the
        instance so repeated generate() calls never recompile for the same
        shapes."""
        cache = getattr(self, "_gen_step_cache", None)
        if cache is None:
            cache = self._gen_step_cache = {}
        fn = cache.get((temperature, top_k))
        if fn is not None:
            return fn

        def step_fn(p, chunk, caches, pos, k):
            logits, new_caches = self.apply(p, chunk, caches, pos,
                                            method="generate_step")
            logits = logits[:, -1].astype(jnp.float32)     # (b, vocab)
            if temperature <= 0.0:
                nxt = jnp.argmax(logits, axis=-1)
            else:
                scaled = logits / temperature
                if top_k > 0:
                    kth = lax.top_k(scaled, top_k)[0][:, -1][:, None]
                    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
                nxt = jax.random.categorical(k, scaled, axis=-1)
            return nxt.astype(jnp.int32), new_caches

        fn = jax.jit(step_fn)
        cache[(temperature, top_k)] = fn
        return fn


# -- standard configs (GPT-3 table; BASELINE.json configs) ------------------
# kwargs override the size defaults (e.g. gpt_tiny(num_layers=4))
def _cfg(defaults: Dict[str, Any], kw: Dict[str, Any]) -> GPTConfig:
    return GPTConfig(**{**defaults, **kw})


def gpt_tiny(**kw) -> GPTConfig:
    return _cfg(dict(hidden_size=128, num_layers=2, num_heads=4,
                     max_position_embeddings=256, vocab_size=1024), kw)


def gpt_125m(**kw) -> GPTConfig:
    return _cfg(dict(hidden_size=768, num_layers=12, num_heads=12), kw)


def gpt_350m(**kw) -> GPTConfig:
    return _cfg(dict(hidden_size=1024, num_layers=24, num_heads=16), kw)


def gpt_1p3b(**kw) -> GPTConfig:
    return _cfg(dict(hidden_size=2048, num_layers=24, num_heads=16,
                     max_position_embeddings=2048), kw)


def gpt_6p7b(**kw) -> GPTConfig:
    return _cfg(dict(hidden_size=4096, num_layers=32, num_heads=32,
                     max_position_embeddings=2048), kw)
