"""Model zoo (reference: python/paddle/vision/models + the GPT/BERT configs
of BASELINE.json; vision models live in paddle_tpu.vision.models)."""
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM,  # noqa: F401
                  gpt_tiny, gpt_125m, gpt_350m, gpt_1p3b, gpt_6p7b)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_tiny",
           "gpt_125m", "gpt_350m", "gpt_1p3b", "gpt_6p7b"]
