"""Model zoo (reference: python/paddle/vision/models + the GPT/BERT configs
of BASELINE.json; vision models live in paddle_tpu.vision.models)."""
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM,  # noqa: F401
                  gpt_tiny, gpt_125m, gpt_350m, gpt_1p3b, gpt_6p7b)
from .gpt_pipeline import GPTPipeline  # noqa: F401

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPipeline", "gpt_tiny",
           "gpt_125m", "gpt_350m", "gpt_1p3b", "gpt_6p7b"]
