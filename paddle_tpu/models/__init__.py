"""Model zoo (reference: python/paddle/vision/models + the GPT/BERT configs
of BASELINE.json; vision models live in paddle_tpu.vision.models)."""
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM,  # noqa: F401
                  gpt_tiny, gpt_125m, gpt_350m, gpt_1p3b, gpt_6p7b)
from .gpt_pipeline import GPTPipeline  # noqa: F401
from .bert import (BertConfig, BertModel, BertForPretraining,  # noqa: F401
                   BertForSequenceClassification, bert_tiny,
                   bert_base, bert_large)

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "bert_tiny", "bert_base",
           "bert_large",
           "GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPipeline", "gpt_tiny",
           "gpt_125m", "gpt_350m", "gpt_1p3b", "gpt_6p7b"]
