"""paddle_tpu.vision (reference: python/paddle/vision)."""
from . import datasets, transforms  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
