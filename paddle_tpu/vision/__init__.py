"""paddle_tpu.vision (reference: python/paddle/vision)."""
from . import datasets, transforms  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend: str):
    """Reference vision.set_image_backend: 'pil' | 'cv2' | 'tensor'."""
    from ..framework.errors import enforce
    enforce(backend in ("pil", "cv2", "tensor"),
            f"unknown image backend {backend!r}")
    global _image_backend
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path: str, backend=None):
    """Load an image per the active backend (reference vision.image_load);
    'tensor'/'cv2' return HWC numpy, 'pil' a PIL Image."""
    b = backend or _image_backend
    from PIL import Image
    img = Image.open(path)
    if b == "pil":
        return img
    import numpy as np
    arr = np.asarray(img)
    if b == "cv2" and arr.ndim == 3 and arr.shape[-1] == 3:
        arr = arr[..., ::-1]      # the cv2 backend convention is BGR
    return arr


__all__ = ["set_image_backend", "get_image_backend", "image_load",
           "transforms", "datasets", "models", "ops"]
