"""Vision transforms (reference: python/paddle/vision/transforms/) — numpy
host-side implementations for the data pipeline."""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "RandomCrop",
           "RandomHorizontalFlip", "CenterCrop", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32)
        if self.data_format == "CHW":
            shape = (-1,) + (1,) * (x.ndim - 1)
        else:
            shape = (1,) * (x.ndim - 1) + (-1,)
        return (x - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __call__(self, x):
        x = np.asarray(x)
        if x.dtype == np.uint8:
            x = x.astype(np.float32) / 255.0
        if x.ndim == 2:
            x = x[None]
        elif x.ndim == 3:
            x = x.transpose(2, 0, 1)
        return np.ascontiguousarray(x, np.float32)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, x):
        return np.asarray(x).transpose(self.order)


class Resize:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        x = np.asarray(x)
        hwc = x.ndim == 3
        h, w = (x.shape[0], x.shape[1])
        th, tw = self.size
        ys = (np.arange(th) * (h / th)).astype(np.int64)
        xs = (np.arange(tw) * (w / tw)).astype(np.int64)
        return x[ys][:, xs] if hwc or x.ndim == 2 else x


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        x = np.asarray(x)
        h, w = x.shape[0], x.shape[1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return x[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, x):
        x = np.asarray(x)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            pad += [(0, 0)] * (x.ndim - 2)
            x = np.pad(x, pad)
        h, w = x.shape[0], x.shape[1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return x[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            return np.asarray(x)[:, ::-1].copy()
        return np.asarray(x)
