"""Vision transforms (reference: python/paddle/vision/transforms/) — numpy
host-side implementations for the data pipeline."""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "RandomCrop",
           "RandomHorizontalFlip", "CenterCrop", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32)
        if self.data_format == "CHW":
            shape = (-1,) + (1,) * (x.ndim - 1)
        else:
            shape = (1,) * (x.ndim - 1) + (-1,)
        return (x - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __call__(self, x):
        x = np.asarray(x)
        if x.dtype == np.uint8:
            x = x.astype(np.float32) / 255.0
        if x.ndim == 2:
            x = x[None]
        elif x.ndim == 3:
            x = x.transpose(2, 0, 1)
        return np.ascontiguousarray(x, np.float32)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, x):
        return np.asarray(x).transpose(self.order)


class Resize:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        x = np.asarray(x)
        hwc = x.ndim == 3
        h, w = (x.shape[0], x.shape[1])
        th, tw = self.size
        ys = (np.arange(th) * (h / th)).astype(np.int64)
        xs = (np.arange(tw) * (w / tw)).astype(np.int64)
        return x[ys][:, xs] if hwc or x.ndim == 2 else x


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        x = np.asarray(x)
        h, w = x.shape[0], x.shape[1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return x[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, x):
        x = np.asarray(x)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            pad += [(0, 0)] * (x.ndim - 2)
            x = np.pad(x, pad)
        h, w = x.shape[0], x.shape[1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return x[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            return np.asarray(x)[:, ::-1].copy()
        return np.asarray(x)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            return np.asarray(x)[::-1].copy()
        return np.asarray(x)


class Pad:
    """Pad HW(C) images (reference transforms Pad; constant mode)."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding, padding, padding, padding)  # l, t, r, b
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, x):
        x = np.asarray(x)
        l, t, r, b = self.padding
        pad = [(t, b), (l, r)] + [(0, 0)] * (x.ndim - 2)
        if self.padding_mode == "constant":
            return np.pad(x, pad, constant_values=self.fill)
        return np.pad(x, pad, mode=self.padding_mode)


class Grayscale:
    """RGB HWC -> grayscale with `num_output_channels` copies."""

    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def __call__(self, x):
        orig_dtype = np.asarray(x).dtype
        x = np.asarray(x, np.float32)
        g = np.clip(_rgb_to_gray(x), 0, 255)
        out = np.stack([g] * self.num_output_channels, axis=-1)
        return out.astype(np.uint8) if orig_dtype == np.uint8 else out


def _jitter_out(y, orig_dtype):
    """uint8 inputs clip back to uint8 [0,255]; float inputs stay float
    clipped to their natural [0,1] range."""
    if orig_dtype == np.uint8:
        return np.clip(y, 0, 255).astype(np.uint8)
    return np.clip(y, 0.0, 1.0).astype(orig_dtype)


def _rgb_to_gray(x):
    """ITU-R BT.601 luma, trailing-channel RGB."""
    return 0.299 * x[..., 0] + 0.587 * x[..., 1] + 0.114 * x[..., 2]


def _factor_range(value):
    """Paddle jitter-value semantics: scalar v → [max(0, 1-v), 1+v];
    (lo, hi) pair passes through.  Returns None when inactive."""
    if isinstance(value, (tuple, list)):
        lo, hi = float(value[0]), float(value[1])
    else:
        if value == 0:
            return None
        lo, hi = max(0.0, 1.0 - value), 1.0 + value
    if lo == hi == 1.0:
        return None
    return lo, hi


class BrightnessTransform:
    def __init__(self, value):
        self.range = _factor_range(value)

    def __call__(self, x):
        if self.range is None:
            return np.asarray(x)
        orig = np.asarray(x).dtype
        alpha = np.random.uniform(*self.range)
        return _jitter_out(np.asarray(x, np.float32) * alpha, orig)


class ContrastTransform:
    def __init__(self, value):
        self.range = _factor_range(value)

    def __call__(self, x):
        if self.range is None:
            return np.asarray(x)
        orig = np.asarray(x).dtype
        alpha = np.random.uniform(*self.range)
        x = np.asarray(x, np.float32)
        mean = x.mean()
        return _jitter_out(mean + alpha * (x - mean), orig)


class SaturationTransform:
    def __init__(self, value):
        self.range = _factor_range(value)

    def __call__(self, x):
        if self.range is None:
            return np.asarray(x)
        orig = np.asarray(x).dtype
        alpha = np.random.uniform(*self.range)
        x = np.asarray(x, np.float32)
        gray = _rgb_to_gray(x)[..., None]
        return _jitter_out(gray + alpha * (x - gray), orig)


class HueTransform:
    """Approximate hue jitter by rotating RGB channels toward the rolled
    image (cheap host-side analog; reference uses HSV rotation)."""

    def __init__(self, value):
        if isinstance(value, (tuple, list)):
            self.range = (float(value[0]), float(value[1]))
        elif value == 0:
            self.range = None
        else:
            self.range = (-float(value), float(value))

    def __call__(self, x):
        if self.range is None:
            return np.asarray(x)
        orig = np.asarray(x).dtype
        # blend weight = |sampled hue shift|: this channel-roll analog has
        # no direction, so the shift's MAGNITUDE drives the blend for both
        # scalar and (lo, hi) forms (a (-0.5, -0.1) range jitters like
        # (0.1, 0.5))
        alpha = np.clip(np.abs(np.random.uniform(*self.range)), 0.0, 1.0)
        x = np.asarray(x, np.float32)
        rolled = np.roll(x, 1, axis=-1)
        return _jitter_out((1 - alpha) * x + alpha * rolled, orig)


class ColorJitter:
    """Compose brightness/contrast/saturation/hue jitters in random order
    (reference transforms ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, x):
        order = np.random.permutation(len(self.ts))
        for i in order:
            x = self.ts[i](x)
        return x


class RandomResizedCrop:
    """Random scale/aspect crop then resize (reference
    RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, x):
        x = np.asarray(x)
        h, w = x.shape[0], x.shape[1]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = x[i:i + ch, j:j + cw]
                return Resize(self.size)(crop)
        return Resize(self.size)(CenterCrop(min(h, w))(x))


class RandomRotation:
    """Rotate by a random multiple-of-90-free angle via coordinate
    mapping (nearest-neighbor, constant fill)."""

    def __init__(self, degrees):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)

    def __call__(self, x):
        x = np.asarray(x)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        h, w = x.shape[0], x.shape[1]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.mgrid[0:h, 0:w]
        ys = cy + (yy - cy) * np.cos(angle) + (xx - cx) * np.sin(angle)
        xs = cx - (yy - cy) * np.sin(angle) + (xx - cx) * np.cos(angle)
        yn = np.clip(np.round(ys), 0, h - 1).astype(np.int64)
        xn = np.clip(np.round(xs), 0, w - 1).astype(np.int64)
        valid = (ys >= 0) & (ys <= h - 1) & (xs >= 0) & (xs <= w - 1)
        out = x[yn, xn]
        return np.where(valid[(...,) + (None,) * (x.ndim - 2)], out, 0)


__all__ += ["RandomVerticalFlip", "Pad", "Grayscale", "BrightnessTransform",
            "ContrastTransform", "SaturationTransform", "HueTransform",
            "ColorJitter", "RandomResizedCrop", "RandomRotation"]


# ---------------------------------------------------------------------------
# Functional forms (reference vision/transforms/functional.py) + the
# BaseTransform class-transform base.  Host-side numpy like the classes.
# ---------------------------------------------------------------------------
class BaseTransform:
    """Reference transforms.BaseTransform: keys-aware transform base.
    Subclasses implement _apply_image (and optionally _apply_boxes /
    _apply_mask); __call__ routes inputs per ``keys``."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, image):
        raise NotImplementedError

    def _apply_boxes(self, boxes):
        return boxes

    def _apply_mask(self, mask):
        return mask

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return self._apply_image(inputs)
        outs = []
        for key, data in zip(self.keys, inputs):
            fn = getattr(self, f"_apply_{key}", None)
            outs.append(fn(data) if fn is not None else data)
        return tuple(outs)


def to_tensor(pic, data_format: str = "CHW"):
    out = ToTensor()(pic)
    return out if data_format == "CHW" else out.transpose(1, 2, 0)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


def resize(img, size, interpolation: str = "bilinear"):
    return Resize(size)(img)


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    return Pad(padding, fill, padding_mode)(img)


def crop(img, top: int, left: int, height: int, width: int):
    return np.asarray(img)[top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def rotate(img, angle: float, interpolation: str = "nearest",
           expand: bool = False, center=None, fill=0):
    """Rotate an HWC image by ``angle`` degrees (nearest-neighbor inverse
    mapping, host-side)."""
    x = np.asarray(img)
    h, w = x.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    yy, xx = np.mgrid[0:h, 0:w]
    # inverse rotation: output pixel ← source position
    sx = cos * (xx - cx) + sin * (yy - cy) + cx
    sy = -sin * (xx - cx) + cos * (yy - cy) + cy
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    inside = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    out = np.full_like(x, fill)
    out[inside] = x[syi[inside], sxi[inside]]
    return out


def to_grayscale(img, num_output_channels: int = 1):
    return Grayscale(num_output_channels)(img)


def adjust_brightness(img, brightness_factor: float):
    orig = np.asarray(img).dtype
    return _jitter_out(np.asarray(img, np.float32) * brightness_factor,
                       orig)


def adjust_contrast(img, contrast_factor: float):
    orig = np.asarray(img).dtype
    x = np.asarray(img, np.float32)
    mean = x.mean()
    return _jitter_out(mean + contrast_factor * (x - mean), orig)


def adjust_hue(img, hue_factor: float):
    orig = np.asarray(img).dtype
    x = np.asarray(img, np.float32)
    alpha = float(np.clip(abs(hue_factor), 0.0, 1.0))
    return _jitter_out((1 - alpha) * x + alpha * np.roll(x, 1, axis=-1),
                       orig)


def normalize(img, mean, std, data_format: str = "CHW",
              to_rgb: bool = False):
    return Normalize(mean, std, data_format)(img)


__all__ += ["BaseTransform", "to_tensor", "hflip", "vflip", "resize",
            "pad", "crop", "center_crop", "rotate", "to_grayscale",
            "adjust_brightness", "adjust_contrast", "adjust_hue",
            "normalize"]
