"""paddle.vision.ops (reference: python/paddle/vision/ops.py — yolo_box:253,
deform_conv2d:430/DeformConv2D:633, psroi_pool:918, roi_pool:1033,
roi_align:1160 (+ Layer wrappers), nms:1376, read_file:826,
decode_jpeg:871, ConvNormActivation:1322).

TPU-first shapes of the detection ops:
- roi_align / roi_pool / psroi_pool: per-box bilinear sampling is expressed
  as static gathers + interpolation weights under ``vmap`` — fixed output
  shapes (num_boxes, C, ph, pw), no dynamic control flow;
- deform_conv2d: offset-shifted kernel taps become one bilinear-sample
  gather per tap followed by a single big (N*H*W, K*C)×(K*C, O) matmul —
  the MXU does the contraction;
- nms: the O(N²) IoU matrix + a ``lax.while_loop`` greedy sweep — static
  shapes; the kept mask converts to indices on the host (eager API, like
  the reference's dynamic-shape op);
- yolo_box: pure elementwise decode.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.errors import enforce
from ..nn.layer import Layer
from .models.utils import ConvNormActivation  # noqa: F401  (reference :1322)

__all__ = ["yolo_box", "roi_align", "roi_pool", "psroi_pool", "RoIAlign",
           "RoIPool", "PSRoIPool", "nms", "deform_conv2d", "DeformConv2D",
           "read_file", "decode_jpeg", "ConvNormActivation"]


# ---------------------------------------------------------------------------
# bilinear sampling shared core
# ---------------------------------------------------------------------------
def _bilinear_sample(feat, y, x):
    """Sample feat (C, H, W) at fractional (y, x) grids (...,) → (C, ...).

    Out-of-range samples contribute 0 (roi_align border semantics)."""
    C, H, W = feat.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0
    out = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            valid = ((yy >= 0) & (yy < H) & (xx >= 0) & (xx < W))
            yc = jnp.clip(yy, 0, H - 1)
            xc = jnp.clip(xx, 0, W - 1)
            v = feat[:, yc, xc]                      # (C, ...)
            out = out + v * (wy * wx * valid)[None]
    return out


def _box_batch_index(boxes_num, total):
    """(num_boxes,) image index per box from per-image counts."""
    boxes_num = np.asarray(boxes_num)
    enforce(int(boxes_num.sum()) == int(total),
            f"sum(boxes_num)={int(boxes_num.sum())} must equal the number "
            f"of boxes {int(total)}")
    return jnp.asarray(np.repeat(np.arange(len(boxes_num)), boxes_num),
                       jnp.int32)


_ROI_ALIGN_WARNED = False


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True):
    """Mask R-CNN RoIAlign (reference ops.py:1160).

    ``sampling_ratio=-1`` differs from the reference: the reference
    picks an *adaptive* grid of ``ceil(roi_size / pooled_size)``
    samples per bin per box, which is a data-dependent shape — so this
    TPU-first version fixes the grid at **2×2 samples per bin** (the
    value detection configs overwhelmingly use, and exact whenever the
    RoI is no larger than ~2× the pooled output).  RoIs much larger
    than ``2 * output_size`` feature pixels are under-sampled relative
    to the reference — bins average 4 taps where the reference would
    take more — which slightly blurs very large proposals.  Pass an
    explicit ``sampling_ratio`` to match the reference exactly for a
    known box-size regime; a one-time ``RuntimeWarning`` fires when
    concrete boxes exceed the 2× regime."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    img_idx = _box_batch_index(boxes_num, boxes.shape[0])
    sr = sampling_ratio if sampling_ratio > 0 else 2
    off = 0.5 if aligned else 0.0
    global _ROI_ALIGN_WARNED
    if (sampling_ratio <= 0 and not _ROI_ALIGN_WARNED
            and not isinstance(boxes, jax.core.Tracer)):
        b = np.asarray(boxes)
        if b.size and (np.any((b[:, 2] - b[:, 0]) * spatial_scale
                              > 2.0 * pw)
                       or np.any((b[:, 3] - b[:, 1]) * spatial_scale
                                 > 2.0 * ph)):
            _ROI_ALIGN_WARNED = True
            import warnings
            warnings.warn(
                "roi_align(sampling_ratio=-1) uses a fixed 2x2 "
                "sample grid per bin (static shapes for TPU); at "
                "least one RoI exceeds 2x the pooled output size and "
                "will be under-sampled vs the reference's adaptive "
                "grid — pass an explicit sampling_ratio to match",
                RuntimeWarning, stacklevel=2)

    def one_box(feat, box):
        x1, y1, x2, y2 = (box * spatial_scale) - off
        rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        iy = jnp.arange(ph)[:, None, None, None]     # (ph,1,sr,1)
        ix = jnp.arange(pw)[None, :, None, None]     # (1,pw,1,1)
        sy = jnp.arange(sr)[None, None, :, None]
        sx = jnp.arange(sr)[None, None, None, :]
        ys = y1 + (iy + (sy + 0.5) / sr) * bin_h     # (ph,pw,sr,sr)
        xs = x1 + (ix + (sx + 0.5) / sr) * bin_w
        vals = _bilinear_sample(feat, ys, xs)        # (C,ph,pw,sr,sr)
        return jnp.mean(vals, axis=(-2, -1))         # (C,ph,pw)

    return jax.vmap(one_box)(x[img_idx], boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0):
    """Fast R-CNN RoIPool: max over quantized bins (reference ops.py:1033)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    H, W = x.shape[2], x.shape[3]
    boxes = jnp.asarray(boxes, jnp.float32)
    img_idx = _box_batch_index(boxes_num, boxes.shape[0])
    # Exact quantized max-pool, reference partitioning: bin bounds come
    # from the UNclipped rounded RoI; each bin's pixel range is then
    # clipped to the image (empty bins → 0).  Computed as a separable
    # masked row-max then col-max over the full H (resp. W) axis, so it
    # is exact for any box with no per-bin span bound.

    def one_box(feat, box):
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        iy = jnp.arange(ph)[:, None]
        ix = jnp.arange(pw)[:, None]
        hs = jnp.clip(y1 + jnp.floor(iy * bin_h), 0, H)       # (ph,1)
        he = jnp.clip(y1 + jnp.ceil((iy + 1) * bin_h), 0, H)
        ws = jnp.clip(x1 + jnp.floor(ix * bin_w), 0, W)       # (pw,1)
        we = jnp.clip(x1 + jnp.ceil((ix + 1) * bin_w), 0, W)
        rows = jnp.arange(H)[None, :]
        cols = jnp.arange(W)[None, :]
        mask_h = (rows >= hs) & (rows < he)                   # (ph,H)
        mask_w = (cols >= ws) & (cols < we)                   # (pw,W)
        # rowmax[c,i,w] = max over bin i's rows; (C,1,H,W) masked → (C,ph,W)
        rowmax = jnp.max(jnp.where(mask_h[None, :, :, None],
                                   feat[:, None, :, :], -jnp.inf), axis=2)
        # (C,ph,1,W) masked by (pw,W) → (C,ph,pw)
        out = jnp.max(jnp.where(mask_w[None, None, :, :],
                                rowmax[:, :, None, :], -jnp.inf), axis=3)
        empty = (~jnp.any(mask_h, 1))[:, None] | (~jnp.any(mask_w, 1))[None]
        return jnp.where(empty[None], 0.0, out)

    # lax.map (not vmap): the masked row-max intermediate is (C,ph,H,W)
    # per box — batching it over hundreds of boxes would blow HBM, and
    # each step already has plenty of inner parallelism for the VPU
    return lax.map(lambda fb: one_box(*fb), (x[img_idx], boxes))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0):
    """Position-sensitive RoI pooling (reference ops.py:918): input has
    C = out_channels * ph * pw; bin (i, j) pools its OWN channel group."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    C = x.shape[1]
    enforce(C % (ph * pw) == 0,
            f"psroi_pool needs channels {C} divisible by {ph * pw}")
    out_c = C // (ph * pw)
    boxes = jnp.asarray(boxes, jnp.float32)
    img_idx = _box_batch_index(boxes_num, boxes.shape[0])
    H, W = x.shape[2], x.shape[3]
    sr = 4

    def one_box(feat, box):
        x1, y1, x2, y2 = box * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h, bin_w = rh / ph, rw / pw
        iy = jnp.arange(ph)[:, None, None, None]
        ix = jnp.arange(pw)[None, :, None, None]
        sy = jnp.arange(sr)[None, None, :, None]
        sx = jnp.arange(sr)[None, None, None, :]
        ys = jnp.floor(y1 + iy * bin_h + (sy + 0.5) / sr * bin_h)
        xs = jnp.floor(x1 + ix * bin_w + (sx + 0.5) / sr * bin_w)
        yc = jnp.clip(ys, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xs, 0, W - 1).astype(jnp.int32)
        # (C,ph,pw,sr,sr) → average per bin
        vals = jnp.mean(feat[:, yc, xc], axis=(-2, -1))   # (C,ph,pw)
        # select the channel group of each bin:
        # group layout: channel c of bin (i,j) lives at c*ph*pw + i*pw + j
        vals = vals.reshape(out_c, ph, pw, ph, pw)
        iy2 = jnp.arange(ph)[:, None]
        ix2 = jnp.arange(pw)[None, :]
        return vals[:, iy2, ix2, iy2, ix2]           # (out_c, ph, pw)

    return jax.vmap(one_box)(x[img_idx], boxes)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale: float = 1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale: float = 1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale: float = 1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------
def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms_mask(boxes, scores=None, iou_threshold: float = 0.3):
    """Static-shape greedy NMS core: (N,) bool keep mask, jittable.

    Boxes are visited in descending score order; a box is kept iff it does
    not overlap (> threshold) any higher-scored kept box."""
    boxes = jnp.asarray(boxes, jnp.float32)
    n = boxes.shape[0]
    order = jnp.argsort(-jnp.asarray(scores, jnp.float32)) \
        if scores is not None else jnp.arange(n)
    iou = _iou_matrix(boxes[order])

    def body(i, keep):
        overlaps = (iou[i] > iou_threshold) & keep
        overlaps = overlaps & (jnp.arange(n) < i)   # only higher-ranked
        return keep.at[i].set(~jnp.any(overlaps))

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None):
    """Greedy NMS returning kept indices, score-descending (reference
    ops.py:1376).  Eager API (dynamic output length, like the reference
    op); use ``nms_mask`` inside jitted programs."""
    boxes = jnp.asarray(boxes, jnp.float32)
    n = boxes.shape[0]
    if category_idxs is not None:
        # multiclass: offset boxes per category so classes never suppress
        # each other (the standard batched-NMS trick)
        enforce(categories is not None,
                "categories must accompany category_idxs")
        span = jnp.max(boxes) + 1.0
        offsets = jnp.asarray(category_idxs, jnp.float32)[:, None] * span
        shifted = boxes + offsets
    else:
        shifted = boxes
    keep = np.asarray(nms_mask(shifted, scores, iou_threshold))
    idx = np.nonzero(keep)[0]
    if scores is not None:
        s = np.asarray(scores)[idx]
        idx = idx[np.argsort(-s)]
    if top_k is not None:
        idx = idx[:top_k]
    return jnp.asarray(idx)   # canonical index dtype (int32 w/o x64)


# ---------------------------------------------------------------------------
# YOLO decode
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num: int, conf_thresh: float,
             downsample_ratio: int, clip_bbox: bool = True,
             scale_x_y: float = 1.0, iou_aware: bool = False,
             iou_aware_factor: float = 0.5):
    """Decode YOLOv3 head output to boxes + scores (reference ops.py:253).

    x: (N, A*(5+cls), H, W) — or (N, A*(6+cls), H, W) with iou_aware,
    where the leading A channels are per-anchor IoU logits
    (yolo_box_util.h GetIoUIndex layout).  Returns (boxes (N, A*H*W, 4)
    in xyxy, scores (N, A*H*W, cls)).  Confidence below conf_thresh
    zeroes the box+score (the reference's semantics)."""
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    a = len(anchors) // 2
    anchors_arr = jnp.asarray(anchors, jnp.float32).reshape(a, 2)
    img_size = jnp.asarray(img_size, jnp.float32)      # (N, 2) h, w

    if iou_aware:
        enforce(c == a * (6 + class_num),
                f"iou_aware yolo_box expects {a * (6 + class_num)} "
                f"channels, got {c}")
        iou = jax.nn.sigmoid(x[:, :a])                 # (n, a, h, w)
        x = x[:, a:]
    else:
        enforce(c == a * (5 + class_num),
                f"yolo_box expects {a * (5 + class_num)} channels, got {c}")
    feats = x.reshape(n, a, 5 + class_num, h, w)
    tx, ty = feats[:, :, 0], feats[:, :, 1]
    tw, th = feats[:, :, 2], feats[:, :, 3]
    obj = jax.nn.sigmoid(feats[:, :, 4])
    if iou_aware:   # conf = obj^(1-f) * iou^f (yolo_box_kernel.cc:80)
        obj = (obj ** (1.0 - iou_aware_factor)) * (iou ** iou_aware_factor)
    cls_prob = jax.nn.sigmoid(feats[:, :, 5:])

    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    bias = 0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(tx) * scale_x_y - bias + gx) / w
    cy = (jax.nn.sigmoid(ty) * scale_x_y - bias + gy) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(tw) * anchors_arr[None, :, None, None, 0] / input_w
    bh = jnp.exp(th) * anchors_arr[None, :, None, None, 1] / input_h

    im_h = img_size[:, 0][:, None, None, None]
    im_w = img_size[:, 1][:, None, None, None]
    x1 = (cx - bw / 2) * im_w
    y1 = (cy - bh / 2) * im_h
    x2 = (cx + bw / 2) * im_w
    y2 = (cy + bh / 2) * im_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, im_w - 1)
        y1 = jnp.clip(y1, 0, im_h - 1)
        x2 = jnp.clip(x2, 0, im_w - 1)
        y2 = jnp.clip(y2, 0, im_h - 1)

    conf = obj[..., None] * jnp.moveaxis(cls_prob, 2, -1)  # (n,a,h,w,cls)
    mask = (obj > conf_thresh)[..., None]
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * mask
    scores = conf * mask
    return (boxes.reshape(n, a * h * w, 4),
            scores.reshape(n, a * h * w, class_num))


# ---------------------------------------------------------------------------
# Deformable convolution
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups: int = 1, groups: int = 1,
                  mask=None):
    """Deformable conv v1/v2 (reference ops.py:430; v2 when mask given).

    x: (N, Cin, H, W); offset: (N, 2*dg*kh*kw, Hout, Wout);
    mask: (N, dg*kh*kw, Hout, Wout); weight: (Cout, Cin/g, kh, kw).
    Implementation: per-tap bilinear sampling (gathers) then one
    (N*Ho*Wo, kh*kw*Cin)×(kh*kw*Cin, Cout) MXU matmul."""
    x = jnp.asarray(x)
    offset = jnp.asarray(offset)
    weight = jnp.asarray(weight)
    enforce(groups == 1 and deformable_groups == 1,
            "deform_conv2d: groups/deformable_groups > 1 not supported "
            "in this build")
    n, cin, H, W = x.shape
    cout, _, kh, kw = weight.shape
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    ho = (H + 2 * p[0] - (d[0] * (kh - 1) + 1)) // s[0] + 1
    wo = (W + 2 * p[1] - (d[1] * (kw - 1) + 1)) // s[1] + 1
    enforce(offset.shape[1] == 2 * kh * kw,
            f"offset channels {offset.shape[1]} != 2*kh*kw {2 * kh * kw}")

    # base sampling positions per output pixel and tap
    oy = jnp.arange(ho) * s[0] - p[0]
    ox = jnp.arange(wo) * s[1] - p[1]
    ky = jnp.arange(kh) * d[0]
    kx = jnp.arange(kw) * d[1]
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # ho,1,kh,1
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # 1,wo,1,kw

    off = offset.reshape(n, kh, kw, 2, ho, wo)
    dy = jnp.transpose(off[:, :, :, 0], (0, 3, 4, 1, 2))  # n,ho,wo,kh,kw
    dx = jnp.transpose(off[:, :, :, 1], (0, 3, 4, 1, 2))
    ys = base_y[None, :, :, :, :] + dy
    xs = base_x[None, :, :, :, :] + dx

    def per_image(feat, y, x_):
        return _bilinear_sample(feat, y, x_)         # (C,ho,wo,kh,kw)

    sampled = jax.vmap(per_image)(x, ys, xs)         # (n,C,ho,wo,kh,kw)
    if mask is not None:
        m = jnp.asarray(mask).reshape(n, kh, kw, ho, wo)
        m = jnp.transpose(m, (0, 3, 4, 1, 2))        # n,ho,wo,kh,kw
        sampled = sampled * m[:, None]
    # contract (C, kh, kw) against the kernel on the MXU
    cols = jnp.transpose(sampled, (0, 2, 3, 1, 4, 5)).reshape(
        n * ho * wo, cin * kh * kw)
    wmat = weight.reshape(cout, cin * kh * kw).T
    out = (cols @ wmat).reshape(n, ho, wo, cout)
    out = jnp.transpose(out, (0, 3, 1, 2))
    if bias is not None:
        out = out + jnp.asarray(bias)[None, :, None, None]
    return out


class DeformConv2D(Layer):
    """Reference ops.py:633 — learnable weight/bias; offset (and mask)
    come in at call time from a companion conv."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, deformable_groups=1,
                 groups=1, weight_attr=None, bias_attr=None):
        super().__init__()
        import math
        from ..nn import initializer as I
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._dg, self._groups = deformable_groups, groups
        fan_in = in_channels * k[0] * k[1] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k[0], k[1]),
            default_initializer=I.Uniform(-bound, bound), attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), is_bias=True,
            default_initializer=I.Uniform(-bound, bound), attr=bias_attr)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._dg, self._groups, mask)


# ---------------------------------------------------------------------------
# image IO (host-side)
# ---------------------------------------------------------------------------
def read_file(filename: str):
    """Raw file bytes as a uint8 tensor (reference ops.py:826)."""
    with open(filename, "rb") as f:
        return jnp.asarray(np.frombuffer(f.read(), np.uint8))


def decode_jpeg(x, mode: str = "unchanged"):
    """Decode a JPEG byte tensor to (C, H, W) uint8 (reference ops.py:871);
    PIL-backed host op."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(np.asarray(x).tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = np.transpose(arr, (2, 0, 1))
    return jnp.asarray(arr)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num: int,
              ignore_thresh: float, downsample_ratio: int, gt_score=None,
              use_label_smooth: bool = True, name=None,
              scale_x_y: float = 1.0):
    """YOLOv3 training loss (reference ops.py yolo_loss /
    yolov3_loss_op.cc): per-sample sum of location (BCE x/y + L1 w/h,
    box-scale weighted), objectness (BCE; negatives whose best IoU with
    any gt exceeds ignore_thresh are ignored), and class BCE terms.

    x: (N, A*(5+C), H, W) head output; gt_box: (N, B, 4) normalized
    center-xywh; gt_label: (N, B) int (negative/zero-area boxes are
    padding); anchors: flat pixel pairs for ALL anchors; anchor_mask:
    indices of this head's anchors.  Returns (N,) loss.
    """
    x = jnp.asarray(x)
    gt_box = jnp.asarray(gt_box, jnp.float32)
    gt_label = jnp.asarray(gt_label, jnp.int32)
    n, c, h, w = x.shape
    a = len(anchor_mask)
    enforce(c == a * (5 + class_num),
            f"yolo_loss expects {a * (5 + class_num)} channels, got {c}")
    all_anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    mask_anchors = all_anchors[jnp.asarray(anchor_mask)]
    input_h = float(downsample_ratio * h)
    input_w = float(downsample_ratio * w)
    b = gt_box.shape[1]
    if gt_score is None:
        gt_score = jnp.ones((n, b), jnp.float32)
    else:
        gt_score = jnp.asarray(gt_score, jnp.float32)

    feats = x.reshape(n, a, 5 + class_num, h, w)
    px, py = feats[:, :, 0], feats[:, :, 1]          # raw logits
    pw, ph = feats[:, :, 2], feats[:, :, 3]
    pobj = feats[:, :, 4]
    pcls = feats[:, :, 5:]                           # (n, a, C, h, w)

    valid = (gt_box[:, :, 2] > 0) & (gt_box[:, :, 3] > 0)   # (n, b)

    # --- responsible anchor per gt: best wh-IoU over ALL anchors --------
    gw = gt_box[:, :, 2] * input_w                   # pixels
    gh = gt_box[:, :, 3] * input_h
    inter = (jnp.minimum(gw[:, :, None], all_anchors[None, None, :, 0])
             * jnp.minimum(gh[:, :, None], all_anchors[None, None, :, 1]))
    union = (gw * gh)[:, :, None] \
        + (all_anchors[:, 0] * all_anchors[:, 1])[None, None, :] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=2)  # (n, b)
    # position in THIS head's mask (or -1)
    mask_arr = jnp.asarray(anchor_mask)
    in_head = best[:, :, None] == mask_arr[None, None, :]        # (n,b,a)
    head_slot = jnp.where(jnp.any(in_head, 2),
                          jnp.argmax(in_head, 2), -1)            # (n, b)
    responsible = valid & (head_slot >= 0)

    gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)

    # --- scatter targets over the (n, a, h, w) grid ---------------------
    slot = jnp.where(responsible, head_slot, 0)
    ni = jnp.arange(n)[:, None] * jnp.ones((1, b), jnp.int32)
    sel = (ni, slot, gj, gi)
    on = responsible.astype(jnp.float32)

    def scat(values):
        z = jnp.zeros((n, a, h, w), jnp.float32)
        return z.at[sel].add(values * on)

    obj_t = scat(gt_score)
    obj_pos = scat(jnp.ones_like(gt_score))
    tx = scat(gt_box[:, :, 0] * w - gi.astype(jnp.float32))
    ty = scat(gt_box[:, :, 1] * h - gj.astype(jnp.float32))
    aw = mask_anchors[slot, 0]
    ah = mask_anchors[slot, 1]
    tw = scat(jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-9), 1e-9)))
    th = scat(jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-9), 1e-9)))
    # box-scale weight 2 - w*h de-emphasizes large boxes (darknet trick)
    bweight = scat(2.0 - gt_box[:, :, 2] * gt_box[:, :, 3])

    delta = 1.0 / class_num if use_label_smooth and class_num > 1 else 0.0
    cls_t = jnp.zeros((n, a, class_num, h, w), jnp.float32)
    lbl = jnp.clip(gt_label, 0, class_num - 1)
    cls_t = cls_t.at[ni, slot, lbl, gj, gi].add(on)
    cls_t = jnp.clip(cls_t, 0.0, 1.0)
    if delta:
        cls_t = cls_t * (1.0 - delta) + delta / class_num

    def bce(logit, target):
        return (jnp.maximum(logit, 0) - logit * target
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    pos = obj_pos
    loss_xy = pos * bweight * (bce(px, tx) + bce(py, ty))
    loss_wh = pos * bweight * 0.5 * (jnp.abs(pw - tw) + jnp.abs(ph - th))

    # --- ignore mask: negatives overlapping a gt box beyond thresh ------
    gx_grid = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy_grid = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    bias = 0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(px) * scale_x_y - bias + gx_grid) / w
    cy = (jax.nn.sigmoid(py) * scale_x_y - bias + gy_grid) / h
    bw = jnp.exp(jnp.clip(pw, -10, 10)) * mask_anchors[None, :, 0,
                                                       None, None] / input_w
    bh = jnp.exp(jnp.clip(ph, -10, 10)) * mask_anchors[None, :, 1,
                                                       None, None] / input_h
    p1 = jnp.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2],
                   axis=-1)                          # (n, a, h, w, 4)
    g1 = jnp.stack([gt_box[:, :, 0] - gt_box[:, :, 2] / 2,
                    gt_box[:, :, 1] - gt_box[:, :, 3] / 2,
                    gt_box[:, :, 0] + gt_box[:, :, 2] / 2,
                    gt_box[:, :, 1] + gt_box[:, :, 3] / 2], axis=-1)
    px1 = p1[:, :, :, :, None, :]
    gb1 = g1[:, None, None, None, :, :]
    iw = jnp.maximum(jnp.minimum(px1[..., 2], gb1[..., 2])
                     - jnp.maximum(px1[..., 0], gb1[..., 0]), 0)
    ih = jnp.maximum(jnp.minimum(px1[..., 3], gb1[..., 3])
                     - jnp.maximum(px1[..., 1], gb1[..., 1]), 0)
    inter2 = iw * ih
    area_p = ((px1[..., 2] - px1[..., 0])
              * (px1[..., 3] - px1[..., 1]))
    area_g = ((gb1[..., 2] - gb1[..., 0])
              * (gb1[..., 3] - gb1[..., 1]))
    iou = inter2 / jnp.maximum(area_p + area_g - inter2, 1e-9)
    iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
    best_iou = jnp.max(iou, axis=-1)                 # (n, a, h, w)
    noobj_mask = ((best_iou <= ignore_thresh)
                  & (pos == 0)).astype(jnp.float32)

    loss_obj = pos * obj_t * bce(pobj, jnp.ones_like(pobj)) \
        + noobj_mask * bce(pobj, jnp.zeros_like(pobj))
    loss_cls = pos[:, :, None] * bce(pcls, cls_t)

    total = (jnp.sum(loss_xy, axis=(1, 2, 3))
             + jnp.sum(loss_wh, axis=(1, 2, 3))
             + jnp.sum(loss_obj, axis=(1, 2, 3))
             + jnp.sum(loss_cls, axis=(1, 2, 3, 4)))
    return total


__all__.append("yolo_loss")
