"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST mnist.py,
Cifar, ImageFolder).  Zero-egress environment: datasets load from local files
when present (paddle-compatible idx/gz formats) and fall back to a
deterministic synthetic set so examples/tests run anywhere."""
from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "ImageFolder", "DatasetFolder"]


def _load_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _load_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.astype(np.int64)


def _synthetic_classes(n: int, seed: int, shape, proto_seed: int,
                       noise: float = 0.3, num_classes: int = 10):
    """Deterministic learnable class data: each class is a distinct
    pattern plus per-sample noise.  The class prototypes come from a FIXED
    seed shared by every split — train and test must agree on what the
    classes look like; only the sampling noise differs by ``seed``."""
    protos = np.random.RandomState(proto_seed).rand(
        num_classes, *shape).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    imgs = np.clip(protos[labels]
                   + noise * rng.randn(n, *shape).astype(np.float32), 0, 1)
    return (imgs * 255).astype(np.uint8), labels


def _synthetic_digits(n: int, seed: int, image_hw=(28, 28)):
    return _synthetic_classes(n, seed, image_hw, proto_seed=1234)


class MNIST(Dataset):
    """paddle.vision.datasets.MNIST analog (reference
    python/paddle/vision/datasets/mnist.py)."""

    NUM_CLASSES = 10

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2", synthetic_size: Optional[int] = None):
        self.transform = transform
        self.mode = mode
        if image_path and os.path.exists(image_path):
            self.images = _load_idx_images(image_path)
            self.labels = _load_idx_labels(label_path)
        else:
            n = synthetic_size or (4096 if mode == "train" else 512)
            self.images, self.labels = _synthetic_digits(
                n, seed=7 if mode == "train" else 11)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 synthetic_size: Optional[int] = None):
        self.transform = transform
        n = synthetic_size or (2048 if mode == "train" else 256)
        self.images, self.labels = _synthetic_classes(
            n, seed=13 if mode == "train" else 17, shape=(32, 32, 3),
            proto_seed=4321, noise=0.25)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 synthetic_size: Optional[int] = None):
        self.transform = transform
        n = synthetic_size or (2048 if mode == "train" else 256)
        self.images, self.labels = _synthetic_classes(
            n, seed=19 if mode == "train" else 23, shape=(32, 32, 3),
            proto_seed=8765, noise=0.25, num_classes=100)


class Flowers(Dataset):
    """paddle.vision.datasets.Flowers analog (reference
    python/paddle/vision/datasets/flowers.py:43): 102-category flower
    classification with train/valid/test splits.  Zero-egress default:
    deterministic learnable synthetic classes (shared prototypes across
    splits, split-specific noise)."""

    NUM_CLASSES = 102

    def __init__(self, data_file: Optional[str] = None,
                 label_file: Optional[str] = None,
                 setid_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "cv2",
                 synthetic_size: Optional[int] = None):
        assert mode in ("train", "valid", "test"), mode
        self.transform = transform
        self.mode = mode
        n = synthetic_size or {"train": 1024, "valid": 128,
                               "test": 256}[mode]
        seed = {"train": 29, "valid": 31, "test": 37}[mode]
        self.images, self.labels = _synthetic_classes(
            n, seed=seed, shape=(64, 64, 3), proto_seed=10246,
            noise=0.25, num_classes=self.NUM_CLASSES)
        self.labels = self.labels + 1   # reference labels are 1-based

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """paddle.vision.datasets.VOC2012 analog (reference
    python/paddle/vision/datasets/voc2012.py:40): segmentation pairs
    (image, per-pixel label mask over 21 classes).  Zero-egress default:
    each sample places a class-colored rectangle on a noise background
    with the exactly-matching mask — learnable by a small conv net."""

    NUM_CLASSES = 21

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "cv2",
                 synthetic_size: Optional[int] = None, image_hw=(64, 64)):
        assert mode in ("train", "valid", "test"), mode
        self.transform = transform
        self.mode = mode
        n = synthetic_size or {"train": 512, "valid": 64, "test": 128}[mode]
        rng = np.random.RandomState({"train": 41, "valid": 43,
                                     "test": 47}[mode])
        colors = np.random.RandomState(20127).rand(
            self.NUM_CLASSES, 3).astype(np.float32)
        H, W = image_hw
        imgs = rng.rand(n, H, W, 3).astype(np.float32) * 0.3
        masks = np.zeros((n, H, W), np.int64)
        for i in range(n):
            cls = rng.randint(1, self.NUM_CLASSES)
            h0, w0 = rng.randint(0, H // 2), rng.randint(0, W // 2)
            h1 = h0 + rng.randint(H // 4, H // 2)
            w1 = w0 + rng.randint(W // 4, W // 2)
            imgs[i, h0:h1, w0:w1] = (
                colors[cls] + 0.1 * rng.randn(h1 - h0, w1 - w0, 3)
            ).clip(0, 1)
            masks[i, h0:h1, w0:w1] = cls
        self.images = (imgs * 255).astype(np.uint8)
        self.masks = masks

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


# reference folder.py IMG_EXTENSIONS — stray non-image files (README,
# .DS_Store, csv sidecars) must not enter the sample list
IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp")


def _default_loader(path):
    return np.asarray(__import__("PIL.Image", fromlist=["open"]).open(path))


def _has_valid_ext(fname: str, extensions) -> bool:
    if isinstance(extensions, str):   # a bare ".npy" must not explode into
        extensions = (extensions,)    # per-character suffixes via tuple()
    return fname.lower().endswith(tuple(extensions))


class DatasetFolder(Dataset):
    """Reference: vision/datasets/folder.py — class-per-subdir image tree.
    Only files matching ``extensions`` (IMG_EXTENSIONS by default) are
    indexed; an empty result raises like the reference."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if _has_valid_ext(fname, extensions):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of {root}; supported "
                f"extensions: {','.join(extensions)}")

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Reference: vision/datasets/folder.py ImageFolder — a flat recursive
    scan of image files under ``root``; unlike DatasetFolder items carry
    NO label (the reference yields ``[sample]``)."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        self.samples = []
        for dirpath, _dirnames, filenames in sorted(os.walk(root)):
            for fname in sorted(filenames):
                if _has_valid_ext(fname, extensions):
                    self.samples.append(os.path.join(dirpath, fname))
        if not self.samples:
            raise RuntimeError(
                f"Found 0 files in {root}; supported extensions: "
                f"{','.join(extensions)}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
