"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST mnist.py,
Cifar, ImageFolder).  Zero-egress environment: datasets load from local files
when present (paddle-compatible idx/gz formats) and fall back to a
deterministic synthetic set so examples/tests run anywhere."""
from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "ImageFolder", "DatasetFolder"]


def _load_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _load_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.astype(np.int64)


def _synthetic_classes(n: int, seed: int, shape, proto_seed: int,
                       noise: float = 0.3, num_classes: int = 10):
    """Deterministic learnable class data: each class is a distinct
    pattern plus per-sample noise.  The class prototypes come from a FIXED
    seed shared by every split — train and test must agree on what the
    classes look like; only the sampling noise differs by ``seed``."""
    protos = np.random.RandomState(proto_seed).rand(
        num_classes, *shape).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    imgs = np.clip(protos[labels]
                   + noise * rng.randn(n, *shape).astype(np.float32), 0, 1)
    return (imgs * 255).astype(np.uint8), labels


def _synthetic_digits(n: int, seed: int, image_hw=(28, 28)):
    return _synthetic_classes(n, seed, image_hw, proto_seed=1234)


class MNIST(Dataset):
    """paddle.vision.datasets.MNIST analog (reference
    python/paddle/vision/datasets/mnist.py)."""

    NUM_CLASSES = 10

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2", synthetic_size: Optional[int] = None):
        self.transform = transform
        self.mode = mode
        if image_path and os.path.exists(image_path):
            self.images = _load_idx_images(image_path)
            self.labels = _load_idx_labels(label_path)
        else:
            n = synthetic_size or (4096 if mode == "train" else 512)
            self.images, self.labels = _synthetic_digits(
                n, seed=7 if mode == "train" else 11)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 synthetic_size: Optional[int] = None):
        self.transform = transform
        n = synthetic_size or (2048 if mode == "train" else 256)
        self.images, self.labels = _synthetic_classes(
            n, seed=13 if mode == "train" else 17, shape=(32, 32, 3),
            proto_seed=4321, noise=0.25)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class DatasetFolder(Dataset):
    """Reference: vision/datasets/folder.py — class-per-subdir image tree."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 loader: Optional[Callable] = None):
        self.root = root
        self.transform = transform
        self.loader = loader or (lambda p: np.asarray(
            __import__("PIL.Image", fromlist=["open"]).open(p)))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                self.samples.append((os.path.join(cdir, fname),
                                     self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder
