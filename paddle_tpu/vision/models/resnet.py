"""ResNet family (BASELINE config #2: ResNet-50 ImageNet).

API parity target: python/paddle/vision/models/resnet.py:1 (class ResNet,
constructors resnet18/34/50/101/152, wide_resnet50_2/101_2) — the canonical
He et al. architecture, written here against this framework's layer system.

TPU notes: convs run through XLA's conv emitter (MXU-tiled); the public API
keeps the reference's NCHW layout — XLA's layout assignment re-tiles
internally, so no NHWC fork of the model is needed.  Channel counts are all
multiples of 64/128, which is what MXU tiling wants.
"""
from __future__ import annotations

from typing import List, Optional, Type, Union

from ...nn import functional as F
from ...nn.layer import Layer
from ...nn.layers import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Linear,
                          MaxPool2D)

__all__ = ["ResNet", "BasicBlock", "BottleneckBlock", "resnet18", "resnet34",
           "resnet50", "resnet101", "resnet152", "wide_resnet50_2",
           "wide_resnet101_2"]


def _conv_bn(in_ch: int, out_ch: int, kernel: int, stride: int = 1,
             groups: int = 1):
    pad = (kernel - 1) // 2
    return (Conv2D(in_ch, out_ch, kernel, stride=stride, padding=pad,
                   groups=groups, bias_attr=False),
            BatchNorm2D(out_ch))


class BasicBlock(Layer):
    """3x3 + 3x3 residual block (resnet18/34)."""

    expansion = 1

    def __init__(self, inplanes: int, planes: int, stride: int = 1,
                 downsample: Optional[Layer] = None, groups: int = 1,
                 base_width: int = 64):
        super().__init__()
        self.conv1, self.bn1 = _conv_bn(inplanes, planes, 3, stride)
        self.conv2, self.bn2 = _conv_bn(planes, planes, 3)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = self.downsample(x) if self.downsample is not None else x
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.relu(out + identity)


class BottleneckBlock(Layer):
    """1x1 → 3x3 → 1x1 bottleneck (resnet50/101/152)."""

    expansion = 4

    def __init__(self, inplanes: int, planes: int, stride: int = 1,
                 downsample: Optional[Layer] = None, groups: int = 1,
                 base_width: int = 64):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1, self.bn1 = _conv_bn(inplanes, width, 1)
        self.conv2, self.bn2 = _conv_bn(width, width, 3, stride, groups)
        self.conv3, self.bn3 = _conv_bn(width, planes * self.expansion, 1)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = self.downsample(x) if self.downsample is not None else x
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


class _Downsample(Layer):
    def __init__(self, in_ch: int, out_ch: int, stride: int):
        super().__init__()
        self.conv, self.bn = _conv_bn(in_ch, out_ch, 1, stride)

    def forward(self, x):
        return self.bn(self.conv(x))


class ResNet(Layer):
    """ResNet backbone + classifier head (reference resnet.py class ResNet:
    depth select via block type + layer counts; with_pool/num_classes knobs
    kept for API parity)."""

    def __init__(self, block: Type[Union[BasicBlock, BottleneckBlock]],
                 depth_or_layers, num_classes: int = 1000,
                 with_pool: bool = True, groups: int = 1,
                 width_per_group: int = 64):
        super().__init__()
        if isinstance(depth_or_layers, int):
            layers = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                      101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth_or_layers]
        else:
            layers = list(depth_or_layers)
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.groups = groups
        self.base_width = width_per_group
        self.inplanes = 64

        self.conv1, self.bn1 = _conv_bn(3, 64, 7, stride=2)
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes: int, count: int, stride: int = 1):
        from ...nn.layer import Sequential
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = _Downsample(self.inplanes,
                                     planes * block.expansion, stride)
        blocks: List[Layer] = [block(self.inplanes, planes, stride,
                                     downsample, self.groups,
                                     self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, count):
            blocks.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width))
        return Sequential(*blocks)

    def forward(self, x):
        x = F.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x


def resnet18(**kw) -> ResNet:
    return ResNet(BasicBlock, 18, **kw)


def resnet34(**kw) -> ResNet:
    return ResNet(BasicBlock, 34, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(BottleneckBlock, 50, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(BottleneckBlock, 101, **kw)


def resnet152(**kw) -> ResNet:
    return ResNet(BottleneckBlock, 152, **kw)


def wide_resnet50_2(**kw) -> ResNet:
    return ResNet(BottleneckBlock, 50, width_per_group=128, **kw)


def wide_resnet101_2(**kw) -> ResNet:
    return ResNet(BottleneckBlock, 101, width_per_group=128, **kw)
