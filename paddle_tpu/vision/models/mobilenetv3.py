"""MobileNetV3 (reference API: python/paddle/vision/models/mobilenetv3.py:1
— MobileNetV3Small/MobileNetV3Large, mobilenet_v3_small/large).

V2's inverted residual plus squeeze-excite and hardswish; the SE block's
two 1x1 convs run on pooled 1x1 maps, so they're tiny GEMMs.
"""
from __future__ import annotations

from typing import List, Tuple

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import AdaptiveAvgPool2D, Conv2D, Dropout, Linear
from .mobilenetv2 import _make_divisible
from .utils import ConvNormActivation as ConvBNAct

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class SqueezeExcite(Layer):
    def __init__(self, ch: int, reduction: int = 4):
        super().__init__()
        squeezed = _make_divisible(ch // reduction)
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.reduce = Conv2D(ch, squeezed, 1)
        self.expand = Conv2D(squeezed, ch, 1)

    def forward(self, x):
        s = F.relu(self.reduce(self.pool(x)))
        return x * F.hardsigmoid(self.expand(s))


class InvertedResidualV3(Layer):
    def __init__(self, in_ch: int, hidden: int, out_ch: int, kernel: int,
                 stride: int, use_se: bool, act: str):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers: List[Layer] = []
        if hidden != in_ch:
            layers.append(ConvBNAct(in_ch, hidden, 1, act=act))
        layers.append(ConvBNAct(hidden, hidden, kernel, stride,
                                groups=hidden, act=act))
        if use_se:
            layers.append(SqueezeExcite(hidden))
        layers.append(ConvBNAct(hidden, out_ch, 1, act="none"))
        self.body = Sequential(*layers)

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_res else out


# (kernel, expanded, out, use_se, act, stride)
_LARGE: List[Tuple] = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2), (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2), (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL: List[Tuple] = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2), (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2), (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1), (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1), (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1), (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(Layer):
    def __init__(self, settings: List[Tuple], last_exp: int, last_ch: int,
                 scale: float, num_classes: int, with_pool: bool):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        in_ch = _make_divisible(16 * scale)
        layers = [ConvBNAct(3, in_ch, 3, stride=2, act="hardswish")]
        for k, exp, out, se, act, s in settings:
            layers.append(InvertedResidualV3(
                in_ch, _make_divisible(exp * scale),
                _make_divisible(out * scale), k, s, se, act))
            in_ch = _make_divisible(out * scale)
        exp_ch = _make_divisible(last_exp * scale)
        layers.append(ConvBNAct(in_ch, exp_ch, 1, act="hardswish"))
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.head_fc = Linear(exp_ch, last_ch)
            self.dropout = Dropout(0.2)
            self.fc = Linear(last_ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = F.hardswish(self.head_fc(F.flatten(x, 1)))
            x = self.fc(self.dropout(x))
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__(_LARGE, 960, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__(_SMALL, 576, 1024, scale, num_classes, with_pool)


def mobilenet_v3_small(scale: float = 1.0, **kw) -> MobileNetV3Small:
    return MobileNetV3Small(scale=scale, **kw)


def mobilenet_v3_large(scale: float = 1.0, **kw) -> MobileNetV3Large:
    return MobileNetV3Large(scale=scale, **kw)
