"""Model zoo (reference: python/paddle/vision/models/)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (ResNet, BasicBlock, BottleneckBlock,  # noqa: F401
                     resnet18, resnet34, resnet50, resnet101, resnet152,
                     wide_resnet50_2, wide_resnet101_2)
