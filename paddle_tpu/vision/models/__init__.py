"""Model zoo (reference: python/paddle/vision/models/)."""
from .lenet import LeNet  # noqa: F401

try:  # resnet lands with the conv milestone
    from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                         resnet152)
except ImportError:  # pragma: no cover
    pass
