"""AlexNet (reference API: python/paddle/vision/models/alexnet.py:1).

Written against this framework's layer system; conv stack follows the
canonical Krizhevsky et al. single-tower formulation the reference ships.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import (AdaptiveAvgPool2D, Conv2D, Dropout, Linear,
                          MaxPool2D, ReLU)

__all__ = ["AlexNet", "alexnet"]


class AlexNet(Layer):
    def __init__(self, num_classes: int = 1000, dropout: float = 0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, stride=2),
        )
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(dropout), Linear(256 * 6 * 6, 4096), ReLU(),
                Dropout(dropout), Linear(4096, 4096), ReLU(),
                Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(F.flatten(x, 1))
        return x


def alexnet(**kw) -> AlexNet:
    return AlexNet(**kw)
