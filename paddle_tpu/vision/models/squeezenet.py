"""SqueezeNet (reference API: python/paddle/vision/models/squeezenet.py:1
— class SqueezeNet with version "1.0"/"1.1", squeezenet1_0/1_1).

Fire module = squeeze 1x1 → parallel expand 1x1 / expand 3x3 → channel
concat; final classifier is a 1x1 conv + global average pool.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import (AdaptiveAvgPool2D, Conv2D, Dropout, MaxPool2D,
                          ReLU)

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class Fire(Layer):
    def __init__(self, in_ch: int, squeeze: int, expand1x1: int,
                 expand3x3: int):
        super().__init__()
        self.squeeze = Conv2D(in_ch, squeeze, 1)
        self.expand1x1 = Conv2D(squeeze, expand1x1, 1)
        self.expand3x3 = Conv2D(squeeze, expand3x3, 3, padding=1)

    def forward(self, x):
        x = F.relu(self.squeeze(x))
        return jnp.concatenate(
            [F.relu(self.expand1x1(x)), F.relu(self.expand3x3(x))], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError(f"unsupported SqueezeNet version {version!r}")
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(),
                MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128),
                MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                MaxPool2D(3, stride=2),
                Fire(512, 64, 256, 256),
            )
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(),
                MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256),
            )
        if num_classes > 0:
            self.classifier_drop = Dropout(0.5)
            self.classifier_conv = Conv2D(512, num_classes, 1)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = F.relu(self.classifier_conv(self.classifier_drop(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = F.flatten(x, 1)
        return x


def squeezenet1_0(**kw) -> SqueezeNet:
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw) -> SqueezeNet:
    return SqueezeNet("1.1", **kw)
