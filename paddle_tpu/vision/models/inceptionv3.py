"""Inception-v3 (reference API:
python/paddle/vision/models/inceptionv3.py:1 — class InceptionV3,
inception_v3; 299x299 input).

Factorized convolutions (nx1/1xn towers), grid-reduction blocks, BN after
every conv.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D,
                          Dropout, Linear, MaxPool2D)

__all__ = ["InceptionV3", "inception_v3"]


class _Conv(Layer):
    def __init__(self, in_ch: int, out_ch: int, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride,
                           padding=padding, bias_attr=False)
        self.bn = BatchNorm2D(out_ch)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


class InceptionA(Layer):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool towers."""

    def __init__(self, in_ch: int, pool_ch: int):
        super().__init__()
        self.b1 = _Conv(in_ch, 64, 1)
        self.b5_1 = _Conv(in_ch, 48, 1)
        self.b5_2 = _Conv(48, 64, 5, padding=2)
        self.b3_1 = _Conv(in_ch, 64, 1)
        self.b3_2 = _Conv(64, 96, 3, padding=1)
        self.b3_3 = _Conv(96, 96, 3, padding=1)
        self.pool = AvgPool2D(3, stride=1, padding=1)
        self.bp = _Conv(in_ch, pool_ch, 1)

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b5_2(self.b5_1(x)),
             self.b3_3(self.b3_2(self.b3_1(x))), self.bp(self.pool(x))],
            axis=1)


class ReductionA(Layer):
    """35→17 grid reduction."""

    def __init__(self, in_ch: int):
        super().__init__()
        self.b3 = _Conv(in_ch, 384, 3, stride=2)
        self.d3_1 = _Conv(in_ch, 64, 1)
        self.d3_2 = _Conv(64, 96, 3, padding=1)
        self.d3_3 = _Conv(96, 96, 3, stride=2)
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return jnp.concatenate(
            [self.b3(x), self.d3_3(self.d3_2(self.d3_1(x))), self.pool(x)],
            axis=1)


class InceptionB(Layer):
    """17x17 block with 1x7/7x1 factorized towers."""

    def __init__(self, in_ch: int, mid: int):
        super().__init__()
        self.b1 = _Conv(in_ch, 192, 1)
        self.b7_1 = _Conv(in_ch, mid, 1)
        self.b7_2 = _Conv(mid, mid, (1, 7), padding=(0, 3))
        self.b7_3 = _Conv(mid, 192, (7, 1), padding=(3, 0))
        self.d7_1 = _Conv(in_ch, mid, 1)
        self.d7_2 = _Conv(mid, mid, (7, 1), padding=(3, 0))
        self.d7_3 = _Conv(mid, mid, (1, 7), padding=(0, 3))
        self.d7_4 = _Conv(mid, mid, (7, 1), padding=(3, 0))
        self.d7_5 = _Conv(mid, 192, (1, 7), padding=(0, 3))
        self.pool = AvgPool2D(3, stride=1, padding=1)
        self.bp = _Conv(in_ch, 192, 1)

    def forward(self, x):
        t7 = self.b7_3(self.b7_2(self.b7_1(x)))
        d7 = self.d7_5(self.d7_4(self.d7_3(self.d7_2(self.d7_1(x)))))
        return jnp.concatenate(
            [self.b1(x), t7, d7, self.bp(self.pool(x))], axis=1)


class ReductionB(Layer):
    """17→8 grid reduction."""

    def __init__(self, in_ch: int):
        super().__init__()
        self.b3_1 = _Conv(in_ch, 192, 1)
        self.b3_2 = _Conv(192, 320, 3, stride=2)
        self.b7_1 = _Conv(in_ch, 192, 1)
        self.b7_2 = _Conv(192, 192, (1, 7), padding=(0, 3))
        self.b7_3 = _Conv(192, 192, (7, 1), padding=(3, 0))
        self.b7_4 = _Conv(192, 192, 3, stride=2)
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return jnp.concatenate(
            [self.b3_2(self.b3_1(x)),
             self.b7_4(self.b7_3(self.b7_2(self.b7_1(x)))), self.pool(x)],
            axis=1)


class InceptionC(Layer):
    """8x8 block with branched 1x3/3x1 towers."""

    def __init__(self, in_ch: int):
        super().__init__()
        self.b1 = _Conv(in_ch, 320, 1)
        self.b3_0 = _Conv(in_ch, 384, 1)
        self.b3_a = _Conv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _Conv(384, 384, (3, 1), padding=(1, 0))
        self.d3_0 = _Conv(in_ch, 448, 1)
        self.d3_1 = _Conv(448, 384, 3, padding=1)
        self.d3_a = _Conv(384, 384, (1, 3), padding=(0, 1))
        self.d3_b = _Conv(384, 384, (3, 1), padding=(1, 0))
        self.pool = AvgPool2D(3, stride=1, padding=1)
        self.bp = _Conv(in_ch, 192, 1)

    def forward(self, x):
        b3 = self.b3_0(x)
        b3 = jnp.concatenate([self.b3_a(b3), self.b3_b(b3)], axis=1)
        d3 = self.d3_1(self.d3_0(x))
        d3 = jnp.concatenate([self.d3_a(d3), self.d3_b(d3)], axis=1)
        return jnp.concatenate(
            [self.b1(x), b3, d3, self.bp(self.pool(x))], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        stem: List[Layer] = [
            _Conv(3, 32, 3, stride=2), _Conv(32, 32, 3),
            _Conv(32, 64, 3, padding=1), MaxPool2D(3, stride=2),
            _Conv(64, 80, 1), _Conv(80, 192, 3), MaxPool2D(3, stride=2),
        ]
        body: List[Layer] = stem + [
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            ReductionA(288),
            InceptionB(768, 128), InceptionB(768, 160),
            InceptionB(768, 160), InceptionB(768, 192),
            ReductionB(768),
            InceptionC(1280), InceptionC(2048),
        ]
        self.features = Sequential(*body)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = Dropout(0.2)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(F.flatten(x, 1)))
        return x


def inception_v3(**kw) -> InceptionV3:
    return InceptionV3(**kw)
