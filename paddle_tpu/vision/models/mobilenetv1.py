"""MobileNetV1 (reference API: python/paddle/vision/models/mobilenetv1.py:1
— class MobileNetV1(scale), mobilenet_v1).

Depthwise-separable stack: 3x3 depthwise (groups=channels) + 1x1 pointwise,
each conv-BN-ReLU.  TPU note: depthwise convs are VPU-bound, the 1x1
pointwise convs carry the MXU FLOPs — widths stay multiples of 32.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import AdaptiveAvgPool2D, Linear
from .utils import ConvNormActivation

__all__ = ["MobileNetV1", "mobilenet_v1"]


class DepthwiseSeparable(Layer):
    def __init__(self, in_ch: int, out_ch: int, stride: int):
        super().__init__()
        self.depthwise = ConvNormActivation(in_ch, in_ch, 3, stride,
                                            groups=in_ch)
        self.pointwise = ConvNormActivation(in_ch, out_ch, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


# (out_channels, stride) per depthwise-separable block at scale=1.0
_BLOCKS = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
           (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
           (1024, 1)]


class MobileNetV1(Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch: int) -> int:
            return max(8, int(ch * scale))

        layers = [ConvNormActivation(3, c(32), 3, stride=2)]
        in_ch = c(32)
        for out, stride in _BLOCKS:
            layers.append(DepthwiseSeparable(in_ch, c(out), stride))
            in_ch = c(out)
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(in_ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(F.flatten(x, 1))
        return x


def mobilenet_v1(scale: float = 1.0, **kw) -> MobileNetV1:
    return MobileNetV1(scale=scale, **kw)
