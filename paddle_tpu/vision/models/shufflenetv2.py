"""ShuffleNetV2 (reference API: python/paddle/vision/models/shufflenetv2.py:1
— class ShuffleNetV2(scale, act), shuffle_net_v2_x0_25 … x2_0 + swish).

Channel split → (identity ‖ dw-separable branch) → concat → channel
shuffle.  The shuffle is a reshape/transpose pair — free for XLA (layout
change only, usually fused away).
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import AdaptiveAvgPool2D, Linear, MaxPool2D
from .utils import ConvNormActivation

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def channel_shuffle(x, groups: int):
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = jnp.swapaxes(x, 1, 2)
    return x.reshape(n, c, h, w)


def _act(x, act: str):
    return F.silu(x) if act == "swish" else F.relu(x)


def ConvBN(in_ch, out_ch, kernel, stride=1, groups=1):
    # bare conv+bn; shufflenet applies its act selectively outside
    return ConvNormActivation(in_ch, out_ch, kernel, stride, groups,
                              act="none")


class ShuffleUnit(Layer):
    """stride=1 unit: split in half, transform one half, concat+shuffle."""

    def __init__(self, ch: int, act: str):
        super().__init__()
        branch = ch // 2
        self.pw1 = ConvBN(branch, branch, 1)
        self.dw = ConvBN(branch, branch, 3, groups=branch)
        self.pw2 = ConvBN(branch, branch, 1)
        self.act = act

    def forward(self, x):
        half = x.shape[1] // 2
        x1, x2 = x[:, :half], x[:, half:]
        x2 = _act(self.pw1(x2), self.act)
        x2 = self.dw(x2)
        x2 = _act(self.pw2(x2), self.act)
        return channel_shuffle(jnp.concatenate([x1, x2], axis=1), 2)


class ShuffleDownUnit(Layer):
    """stride=2 unit: both branches transform and downsample."""

    def __init__(self, in_ch: int, out_ch: int, act: str):
        super().__init__()
        branch = out_ch // 2
        self.left_dw = ConvBN(in_ch, in_ch, 3, stride=2, groups=in_ch)
        self.left_pw = ConvBN(in_ch, branch, 1)
        self.right_pw1 = ConvBN(in_ch, branch, 1)
        self.right_dw = ConvBN(branch, branch, 3, stride=2, groups=branch)
        self.right_pw2 = ConvBN(branch, branch, 1)
        self.act = act

    def forward(self, x):
        left = _act(self.left_pw(self.left_dw(x)), self.act)
        right = _act(self.right_pw1(x), self.act)
        right = self.right_dw(right)
        right = _act(self.right_pw2(right), self.act)
        return channel_shuffle(jnp.concatenate([left, right], axis=1), 2)


_STAGE_REPEATS = [4, 8, 4]
_STAGE_CHANNELS = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(Layer):
    def __init__(self, scale: float = 1.0, act: str = "relu",
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        if scale not in _STAGE_CHANNELS:
            raise ValueError(f"unsupported ShuffleNetV2 scale {scale}")
        chans = _STAGE_CHANNELS[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBN(3, chans[0], 3, stride=2)
        self.act_name = act
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        stages: List[Layer] = []
        in_ch = chans[0]
        for stage_i, repeats in enumerate(_STAGE_REPEATS):
            out_ch = chans[stage_i + 1]
            units: List[Layer] = [ShuffleDownUnit(in_ch, out_ch, act)]
            units += [ShuffleUnit(out_ch, act) for _ in range(repeats - 1)]
            stages.append(Sequential(*units))
            in_ch = out_ch
        self.stages = Sequential(*stages)
        self.conv_last = ConvBN(in_ch, chans[4], 1)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(chans[4], num_classes)

    def forward(self, x):
        x = _act(self.conv1(x), self.act_name)
        x = self.stages(self.maxpool(x))
        x = _act(self.conv_last(x), self.act_name)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(F.flatten(x, 1))
        return x


def shufflenet_v2_x0_25(**kw) -> ShuffleNetV2:
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(**kw) -> ShuffleNetV2:
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x0_5(**kw) -> ShuffleNetV2:
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(**kw) -> ShuffleNetV2:
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(**kw) -> ShuffleNetV2:
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(**kw) -> ShuffleNetV2:
    return ShuffleNetV2(scale=2.0, **kw)


def shufflenet_v2_swish(**kw) -> ShuffleNetV2:
    return ShuffleNetV2(scale=1.0, act="swish", **kw)
