"""Shared building blocks for the model zoo (reference:
python/paddle/vision/models/utils.py)."""
from __future__ import annotations

from ...nn import functional as F
from ...nn.layers import BatchNorm2D, Conv2D
from ...nn.layer import Layer

__all__ = ["ConvNormActivation"]

_ACTS = {"relu": F.relu, "relu6": F.relu6, "hardswish": F.hardswish,
         "swish": F.silu, "none": None}


class ConvNormActivation(Layer):
    """Conv2D (same-padding, no bias) + BatchNorm2D + optional activation —
    the block every mobile/shuffle architecture stamps out."""

    def __init__(self, in_ch: int, out_ch: int, kernel: int = 3,
                 stride: int = 1, groups: int = 1, act: str = "relu"):
        super().__init__()
        if act not in _ACTS:
            raise ValueError(f"unsupported activation {act!r}")
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride,
                           padding=(kernel - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(out_ch)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        fn = _ACTS[self.act]
        return fn(x) if fn is not None else x
