"""DenseNet (reference API: python/paddle/vision/models/densenet.py:1 —
class DenseNet(layers=121|161|169|201|264), densenet121 … densenet264).

Dense block = every layer concats its input with its output; transition
layers halve channels and spatial dims.  BN-ReLU-Conv pre-activation
ordering.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D,
                          Linear, MaxPool2D)

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CONFIGS = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class DenseLayer(Layer):
    """BN-ReLU-1x1 (bottleneck 4k) → BN-ReLU-3x3 (k); output concats."""

    def __init__(self, in_ch: int, growth: int, bn_size: int = 4):
        super().__init__()
        self.bn1 = BatchNorm2D(in_ch)
        self.conv1 = Conv2D(in_ch, bn_size * growth, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth)
        self.conv2 = Conv2D(bn_size * growth, growth, 3, padding=1,
                            bias_attr=False)

    def forward(self, x):
        out = self.conv1(F.relu(self.bn1(x)))
        out = self.conv2(F.relu(self.bn2(out)))
        return jnp.concatenate([x, out], axis=1)


class Transition(Layer):
    def __init__(self, in_ch: int, out_ch: int):
        super().__init__()
        self.bn = BatchNorm2D(in_ch)
        self.conv = Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(F.relu(self.bn(x))))


class DenseNet(Layer):
    def __init__(self, layers: int = 121, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        if layers not in _CONFIGS:
            raise ValueError(f"unsupported DenseNet depth {layers}")
        init_ch, growth, block_repeats = _CONFIGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = Conv2D(3, init_ch, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(init_ch)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)

        blocks: List[Layer] = []
        ch = init_ch
        for i, repeats in enumerate(block_repeats):
            dense: List[Layer] = []
            for _ in range(repeats):
                dense.append(DenseLayer(ch, growth))
                ch += growth
            blocks.append(Sequential(*dense))
            if i != len(block_repeats) - 1:
                blocks.append(Transition(ch, ch // 2))
                ch //= 2
        self.blocks = Sequential(*blocks)
        self.bn_final = BatchNorm2D(ch)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(ch, num_classes)

    def forward(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        x = F.relu(self.bn_final(self.blocks(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(F.flatten(x, 1))
        return x


def densenet121(**kw) -> DenseNet:
    return DenseNet(121, **kw)


def densenet161(**kw) -> DenseNet:
    return DenseNet(161, **kw)


def densenet169(**kw) -> DenseNet:
    return DenseNet(169, **kw)


def densenet201(**kw) -> DenseNet:
    return DenseNet(201, **kw)


def densenet264(**kw) -> DenseNet:
    return DenseNet(264, **kw)
