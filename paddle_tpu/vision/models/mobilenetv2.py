"""MobileNetV2 (reference API: python/paddle/vision/models/mobilenetv2.py:1
— class MobileNetV2(scale), mobilenet_v2).

Inverted residual: 1x1 expand → 3x3 depthwise → 1x1 linear project, with a
residual add when stride==1 and channels match.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout,
                          Linear)
from .utils import ConvNormActivation

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:  # never round down by more than 10%
        new_v += divisor
    return new_v


def _conv_bn_relu6(in_ch, out_ch, kernel=3, stride=1, groups=1):
    return ConvNormActivation(in_ch, out_ch, kernel, stride, groups,
                              act="relu6")


class InvertedResidual(Layer):
    def __init__(self, in_ch: int, out_ch: int, stride: int, expand: int):
        super().__init__()
        hidden = int(round(in_ch * expand))
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if expand != 1:
            layers.append(_conv_bn_relu6(in_ch, hidden, 1))
        layers.append(_conv_bn_relu6(hidden, hidden, 3, stride,
                                     groups=hidden))
        self.body = Sequential(*layers)
        self.project = Conv2D(hidden, out_ch, 1, bias_attr=False)
        self.project_bn = BatchNorm2D(out_ch)

    def forward(self, x):
        out = self.project_bn(self.project(self.body(x)))
        return x + out if self.use_res else out


# (expand_ratio, out_channels, repeats, first_stride) at scale=1.0
_SETTINGS = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
             (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]


class MobileNetV2(Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        in_ch = _make_divisible(32 * scale)
        last_ch = _make_divisible(1280 * max(1.0, scale))
        layers = [_conv_bn_relu6(3, in_ch, 3, stride=2)]
        for t, c, n, s in _SETTINGS:
            out_ch = _make_divisible(c * scale)
            for i in range(n):
                layers.append(InvertedResidual(
                    in_ch, out_ch, s if i == 0 else 1, t))
                in_ch = out_ch
        layers.append(_conv_bn_relu6(in_ch, last_ch, 1))
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(last_ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(F.flatten(x, 1)))
        return x


def mobilenet_v2(scale: float = 1.0, **kw) -> MobileNetV2:
    return MobileNetV2(scale=scale, **kw)
