"""VGG family (reference API: python/paddle/vision/models/vgg.py:1 —
class VGG + vgg11/13/16/19 constructors with a batch_norm knob).

TPU note: all channel widths are multiples of 64, so every conv tiles the
MXU cleanly; BN folds into the conv at inference via XLA fusion.
"""
from __future__ import annotations

from typing import List

from ...nn import functional as F
from ...nn.layer import Layer, Sequential
from ...nn.layers import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout,
                          Linear, MaxPool2D, ReLU)

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19"]

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _make_features(cfg: List, batch_norm: bool) -> Sequential:
    layers: List[Layer] = []
    in_ch = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, stride=2))
        else:
            layers.append(Conv2D(in_ch, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            in_ch = v
    return Sequential(*layers)


class VGG(Layer):
    def __init__(self, features: Layer, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 7 * 7, 4096), ReLU(), Dropout(),
                Linear(4096, 4096), ReLU(), Dropout(),
                Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(F.flatten(x, 1))
        return x


def _vgg(cfg_key: str, batch_norm: bool, **kw) -> VGG:
    return VGG(_make_features(_CFGS[cfg_key], batch_norm), **kw)


def vgg11(batch_norm: bool = False, **kw) -> VGG:
    return _vgg("A", batch_norm, **kw)


def vgg13(batch_norm: bool = False, **kw) -> VGG:
    return _vgg("B", batch_norm, **kw)


def vgg16(batch_norm: bool = False, **kw) -> VGG:
    return _vgg("D", batch_norm, **kw)


def vgg19(batch_norm: bool = False, **kw) -> VGG:
    return _vgg("E", batch_norm, **kw)
