"""GoogLeNet / Inception-v1 (reference API:
python/paddle/vision/models/googlenet.py:1 — class GoogLeNet, googlenet;
forward returns (main, aux1, aux2) like the reference).

Inception module = four parallel towers (1x1 / 1x1→3x3 / 1x1→5x5 /
pool→1x1) concatenated on channels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer
from ...nn.layers import (AdaptiveAvgPool2D, Conv2D, Dropout, Linear,
                          MaxPool2D)

__all__ = ["GoogLeNet", "googlenet"]


class _Conv(Layer):
    def __init__(self, in_ch: int, out_ch: int, kernel: int, stride: int = 1,
                 padding: int = 0):
        super().__init__()
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride,
                           padding=padding)

    def forward(self, x):
        return F.relu(self.conv(x))


class Inception(Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.t1 = _Conv(in_ch, c1, 1)
        self.t2a = _Conv(in_ch, c3r, 1)
        self.t2b = _Conv(c3r, c3, 3, padding=1)
        self.t3a = _Conv(in_ch, c5r, 1)
        self.t3b = _Conv(c5r, c5, 5, padding=2)
        self.pool = MaxPool2D(3, stride=1, padding=1)
        self.t4 = _Conv(in_ch, proj, 1)

    def forward(self, x):
        return jnp.concatenate(
            [self.t1(x), self.t2b(self.t2a(x)), self.t3b(self.t3a(x)),
             self.t4(self.pool(x))], axis=1)


class _AuxHead(Layer):
    def __init__(self, in_ch: int, num_classes: int):
        super().__init__()
        self.pool = AdaptiveAvgPool2D((4, 4))
        self.conv = _Conv(in_ch, 128, 1)
        self.fc1 = Linear(128 * 4 * 4, 1024)
        self.drop = Dropout(0.7)
        self.fc2 = Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = F.relu(self.fc1(F.flatten(x, 1)))
        return self.fc2(self.drop(x))


class GoogLeNet(Layer):
    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = _Conv(3, 64, 7, stride=2, padding=3)
        self.pool1 = MaxPool2D(3, stride=2, padding=1)
        self.conv2 = _Conv(64, 64, 1)
        self.conv3 = _Conv(64, 192, 3, padding=1)
        self.pool2 = MaxPool2D(3, stride=2, padding=1)

        self.ince3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, stride=2, padding=1)
        self.ince4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, stride=2, padding=1)
        self.ince5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = Inception(832, 384, 192, 384, 48, 128, 128)

        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = Dropout(0.4)
            self.fc = Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.pool1(self.conv1(x))
        x = self.pool2(self.conv3(self.conv2(x)))
        x = self.ince3b(self.ince3a(x))
        x = self.ince4a(self.pool3(x))
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.ince4d(self.ince4c(self.ince4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.ince4e(x))
        x = self.ince5b(self.ince5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(F.flatten(x, 1)))
            return x, aux1, aux2
        return x


def googlenet(**kw) -> GoogLeNet:
    return GoogLeNet(**kw)
