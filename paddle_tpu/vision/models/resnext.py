"""ResNeXt (reference API: python/paddle/vision/models/resnext.py:1 —
resnext50/101/152 at 32x4d / 64x4d cardinalities).

Grouped-convolution bottleneck — expressed through the ResNet backbone's
groups/width knobs rather than a parallel class hierarchy.
"""
from __future__ import annotations

from .resnet import BottleneckBlock, ResNet

__all__ = ["ResNeXt", "resnext50_32x4d", "resnext50_64x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
           "resnext152_64x4d"]


class ResNeXt(ResNet):
    def __init__(self, depth: int = 50, cardinality: int = 32,
                 width: int = 4, **kw):
        super().__init__(BottleneckBlock, depth, groups=cardinality,
                         width_per_group=width, **kw)


def resnext50_32x4d(**kw) -> ResNeXt:
    return ResNeXt(50, 32, 4, **kw)


def resnext50_64x4d(**kw) -> ResNeXt:
    return ResNeXt(50, 64, 4, **kw)


def resnext101_32x4d(**kw) -> ResNeXt:
    return ResNeXt(101, 32, 4, **kw)


def resnext101_64x4d(**kw) -> ResNeXt:
    return ResNeXt(101, 64, 4, **kw)


def resnext152_32x4d(**kw) -> ResNeXt:
    return ResNeXt(152, 32, 4, **kw)


def resnext152_64x4d(**kw) -> ResNeXt:
    return ResNeXt(152, 64, 4, **kw)