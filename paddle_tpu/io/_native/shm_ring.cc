// Shared-memory ring queue for DataLoader worker→parent batch transfer.
//
// Role in the design (SURVEY A7): the reference moves sample batches from
// worker subprocesses through shared memory (mmap_allocator.cc backing
// core._array_to_share_memory_tensor) into a C++ blocking queue
// (lod_tensor_blocking_queue.h) consumed by buffered_reader.cc.  This file
// is the TPU build's native equivalent of that pair: a fixed-slot MPSC ring
// living inside one anonymous MAP_SHARED mapping created by the parent
// BEFORE fork (so no shm_open names, no cleanup races), with process-shared
// pthread mutex/condvars for blocking put/get and scatter-gather writes so
// workers copy numpy buffers straight into the ring — no pickling of array
// payloads, no socket/pipe transfer.
//
// Layout: [Header | len[slots] | slot data (slots * slot_bytes)]
// API is C, consumed via ctypes (no pybind11 in the image).

#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <time.h>

#include <cerrno>

extern "C" {

struct Header {
  pthread_mutex_t mutex;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
  uint64_t slots;
  uint64_t slot_bytes;
  uint64_t head;  // next slot to read
  uint64_t tail;  // next slot to write
  uint64_t count;
  uint64_t closed;
};

struct Iovec {
  const void* base;
  uint64_t len;
};

static inline uint64_t* lens(Header* h) {
  return reinterpret_cast<uint64_t*>(reinterpret_cast<char*>(h) +
                                     sizeof(Header));
}

static inline char* slot_ptr(Header* h, uint64_t i) {
  return reinterpret_cast<char*>(h) + sizeof(Header) +
         h->slots * sizeof(uint64_t) + i * h->slot_bytes;
}

// Total mapping size needed for (slots, slot_bytes).
uint64_t srq_size(uint64_t slots, uint64_t slot_bytes) {
  return sizeof(Header) + slots * sizeof(uint64_t) + slots * slot_bytes;
}

// Initialize a ring inside caller-provided shared memory.
int srq_init(void* mem, uint64_t slots, uint64_t slot_bytes) {
  Header* h = reinterpret_cast<Header*>(mem);
  memset(h, 0, sizeof(Header));
  h->slots = slots;
  h->slot_bytes = slot_bytes;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  // robust: a worker terminated mid-put must not wedge the parent's lock
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  if (pthread_mutex_init(&h->mutex, &ma) != 0) return -1;
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  if (pthread_cond_init(&h->not_full, &ca) != 0) return -1;
  if (pthread_cond_init(&h->not_empty, &ca) != 0) return -1;
  pthread_condattr_destroy(&ca);
  return 0;
}

// Lock handling EOWNERDEAD: mark consistent and treat the ring as closed —
// a dead owner may have left a half-written slot, so draining is over.
static int robust_lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mutex);
    h->closed = 1;
    pthread_cond_broadcast(&h->not_empty);
    pthread_cond_broadcast(&h->not_full);
  }
  return 0;
}

static void deadline_after(struct timespec* ts, double seconds) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  time_t sec = static_cast<time_t>(seconds);
  long nsec = static_cast<long>((seconds - sec) * 1e9);
  ts->tv_sec += sec;
  ts->tv_nsec += nsec;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// Gathered write of n iovecs as ONE message. Returns 0 ok, -1 timeout,
// -2 message too large, -3 closed.
int srq_put(void* mem, const Iovec* iov, uint64_t n, double timeout) {
  Header* h = reinterpret_cast<Header*>(mem);
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; ++i) total += iov[i].len;
  if (total > h->slot_bytes) return -2;

  struct timespec ts;
  deadline_after(&ts, timeout);
  robust_lock(h);
  while (h->count == h->slots && !h->closed) {
    if (pthread_cond_timedwait(&h->not_full, &h->mutex, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mutex);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mutex);
    return -3;
  }
  uint64_t i = h->tail;
  h->tail = (h->tail + 1) % h->slots;
  h->count += 1;
  // copy OUTSIDE would be ideal (slot reserved), but simplicity wins: the
  // copy is memcpy-bound and parent-side contention is on whole batches
  char* dst = slot_ptr(h, i);
  uint64_t off = 0;
  for (uint64_t k = 0; k < n; ++k) {
    memcpy(dst + off, iov[k].base, iov[k].len);
    off += iov[k].len;
  }
  lens(h)[i] = total;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mutex);
  return 0;
}

// Blocking read into out (cap bytes). Returns message length, -1 timeout,
// -2 out too small, -3 closed-and-empty.
int64_t srq_get(void* mem, void* out, uint64_t cap, double timeout) {
  Header* h = reinterpret_cast<Header*>(mem);
  struct timespec ts;
  deadline_after(&ts, timeout);
  robust_lock(h);
  while (h->count == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mutex);
      return -3;
    }
    if (pthread_cond_timedwait(&h->not_empty, &h->mutex, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mutex);
      return -1;
    }
  }
  uint64_t i = h->head;
  uint64_t len = lens(h)[i];
  if (len > cap) {
    pthread_mutex_unlock(&h->mutex);
    return -2;
  }
  memcpy(out, slot_ptr(h, i), len);
  h->head = (h->head + 1) % h->slots;
  h->count -= 1;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mutex);
  return static_cast<int64_t>(len);
}

// Wake every waiter; subsequent puts fail, gets drain then return -3.
void srq_close(void* mem) {
  Header* h = reinterpret_cast<Header*>(mem);
  robust_lock(h);
  h->closed = 1;
  pthread_cond_broadcast(&h->not_empty);
  pthread_cond_broadcast(&h->not_full);
  pthread_mutex_unlock(&h->mutex);
}

uint64_t srq_count(void* mem) {
  Header* h = reinterpret_cast<Header*>(mem);
  robust_lock(h);
  uint64_t c = h->count;
  pthread_mutex_unlock(&h->mutex);
  return c;
}

}  // extern "C"
