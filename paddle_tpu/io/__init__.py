"""Data pipeline (reference: python/paddle/io/ + fluid/dataloader/ —
multiprocess workers dataloader_iter.py:338, worker loop worker.py:255,
shared-memory transport via mmap_allocator.cc, C++ double-buffer prefetch
operators/reader/buffered_reader.cc; see SURVEY.md A7).

TPU-native design: python worker processes produce numpy batches over a
multiprocessing queue; a background prefetch thread stages host→device
transfers (jax.device_put) ahead of consumption — the buffered_reader analog.
When the native C++ prefetch core is built (paddle_tpu/lib/), the shared
memory ring buffer replaces the pickle queue transport.
"""
from __future__ import annotations

import atexit
import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import jax
import numpy as np

from ..framework.errors import enforce

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "Subset", "ChainDataset", "random_split", "Sampler", "SequenceSampler", "RandomSampler",
    "BatchSampler", "DistributedBatchSampler", "WeightedRandomSampler",
    "DataLoader", "default_collate_fn", "WorkerInfo", "get_worker_info",
]


# ---------------------------------------------------------------------------
# Datasets (reference: python/paddle/io/dataset.py)
# ---------------------------------------------------------------------------
class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not indexable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrs = [np.asarray(t) for t in tensors]
        enforce(all(a.shape[0] == arrs[0].shape[0] for a in arrs),
                "all tensors must share dim 0")
        self.tensors = arrs

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, tuple) else (item,))
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    enforce(sum(lengths) == len(dataset), "lengths must sum to dataset size")
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


# ---------------------------------------------------------------------------
# Samplers (reference: python/paddle/io/sampler.py, batch_sampler.py)
# ---------------------------------------------------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    """Draw indices with the given per-sample weights (reference
    fluid/dataloader WeightedRandomSampler)."""

    def __init__(self, weights, num_samples: int, replacement: bool = True):
        super().__init__()
        self.weights = np.asarray(weights, np.float64)
        enforce(np.all(self.weights >= 0), "weights must be non-negative")
        enforce(self.weights.sum() > 0, "weights must not all be zero")
        enforce(num_samples > 0, "num_samples must be positive")
        self.num_samples = num_samples
        self.replacement = replacement
        enforce(replacement
                or num_samples <= int(np.count_nonzero(self.weights)),
                "cannot draw more samples than nonzero weights without "
                "replacement")

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle: bool = False,
                 batch_size: int = 1, drop_last: bool = False):
        enforce((dataset is None) != (sampler is None),
                "provide exactly one of dataset/sampler")
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference: python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler — shards sample indices across data-parallel
    ranks (epoch-seeded shuffle so every rank permutes identically)."""

    def __init__(self, dataset, batch_size: int, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle: bool = False,
                 drop_last: bool = False):
        from .. import distributed as dist
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]  # pad to even shards
        indices = indices[self.local_rank: self.total_size: self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# ---------------------------------------------------------------------------
# Collate
# ---------------------------------------------------------------------------
def default_collate_fn(batch: List[Any]):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    return np.asarray(batch)


# ---------------------------------------------------------------------------
# Worker process loop (reference: fluid/dataloader/worker.py:255 _worker_loop)
# ---------------------------------------------------------------------------
class WorkerInfo:
    """Reference fluid/dataloader/worker.py WorkerInfo: available inside
    dataset code running in a DataLoader worker via get_worker_info()."""

    def __init__(self, id: int, num_workers: int, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info: Optional[WorkerInfo] = None


def get_worker_info() -> Optional[WorkerInfo]:
    """None in the main process; the WorkerInfo inside a worker
    (reference paddle.io.get_worker_info)."""
    return _worker_info


def _worker_loop(dataset, index_queue, result_queue, collate_fn, worker_id,
                 worker_init_fn, ring=None, num_workers: int = 1):
    """With ``ring`` (the native shared-memory transport, io/native.py)
    batches cross as raw array buffers gathered into a shm slot — no
    pickling of payloads; otherwise the python mp.Queue carries them."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    np.random.seed((np.random.SeedSequence().entropy + worker_id) % (2**31))
    if ring is not None:
        from .native import encode_batch_parts
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            batch = collate_fn(samples)
            if ring is not None:
                try:
                    while True:
                        try:
                            ring.put_parts(
                                encode_batch_parts(batch_id, batch))
                            break
                        except TimeoutError:
                            # consumer busy (e.g. first-step compile) —
                            # keep waiting; ring close ends the loop
                            continue
                except ValueError:
                    # batch exceeds a shm slot → per-batch queue fallback
                    result_queue.put((batch_id, batch, None))
                except BrokenPipeError:
                    break  # parent closed the ring (shutdown)
            else:
                result_queue.put((batch_id, batch, None))
        except Exception as e:  # propagate across the process boundary
            result_queue.put((batch_id, None, repr(e)))


class DataLoader:
    """Reference: paddle.io.DataLoader (fluid/reader.py).

    num_workers=0: synchronous in-process loading.
    num_workers>0: worker subprocesses (index queue → result queue), batches
    re-ordered by id, `prefetch_factor` batches in flight per worker.
    A device-prefetch thread overlaps jax.device_put with consumption.
    """

    @staticmethod
    def from_generator(feed_list=None, capacity: int = 10,
                       use_double_buffer: bool = True, iterable: bool = True,
                       return_list: bool = True, use_multiprocess: bool = False,
                       drop_last: bool = True):
        """Pre-2.0 generator-fed loader (reference
        DataLoader.from_generator).  The feed-queue knobs (capacity,
        double buffering, places) have no role in the one-codepath
        design and are accepted for signature parity only."""
        return _GeneratorLoader()

    @staticmethod
    def from_dataset(dataset, places=None, drop_last: bool = True):
        """Re-iterable loader over a fleet dataset's in-memory records,
        batched by the dataset's configured batch_size (reference
        DataLoader.from_dataset)."""
        recs = getattr(dataset, "_records", None)
        enforce(recs is not None,
                "from_dataset expects an InMemoryDataset with "
                "load_into_memory() called (docs/MIGRATION.md: "
                "'parameter server')")
        bs = max(int(getattr(dataset, "_batch_size", 1)), 1)

        def gen():
            for i in range(0, len(recs) - (bs - 1 if drop_last else 0), bs):
                yield recs[i:i + bs]

        return _GeneratorLoader().set_batch_generator(gen)

    def __init__(self, dataset, feed_list=None, places=None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, batch_sampler=None,
                 num_workers: int = 0, collate_fn=None, use_shared_memory=True,
                 prefetch_factor: int = 2, worker_init_fn=None,
                 to_device: bool = True, return_list=True):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = max(prefetch_factor, 1)
        self.worker_init_fn = worker_init_fn
        self.to_device = to_device
        self.use_shared_memory = use_shared_memory
        self.native_slot_bytes = 32 << 20
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        if self._iterable_mode:
            gen = self._iter_iterable()
        elif self.num_workers == 0:
            gen = self._iter_single()
        else:
            gen = self._iter_multiprocess()
        if self.to_device:
            gen = _DevicePrefetcher(gen)
        return gen

    def _iter_iterable(self):
        # IterableDataset runs in-process (num_workers is a map-style
        # knob here); present the canonical get_worker_info() sharding
        # pattern with a single-worker view — one shard IS the stream
        global _worker_info
        prev = _worker_info
        _worker_info = WorkerInfo(0, 1, self.dataset)
        try:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        finally:
            _worker_info = prev

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _make_ring(self):
        """Native shm transport when FLAGS_dataloader_use_native (and the
        toolchain) allow it — the mmap_allocator/blocking-queue analog."""
        from ..framework.flags import get_flags
        flag = get_flags(["dataloader_use_native"])["dataloader_use_native"]
        if not self.use_shared_memory or not flag or str(flag) in (
                "0", "False", "false"):
            return None
        from .native import ShmRing, native_available
        if not native_available():
            return None
        return ShmRing(slots=max(4, 2 * self.num_workers),
                       slot_bytes=self.native_slot_bytes)

    def _iter_multiprocess(self):
        ctx = mp.get_context("fork")
        index_queue = ctx.Queue()
        result_queue = ctx.Queue()
        ring = self._make_ring()   # create BEFORE fork: children inherit it
        workers = []
        for wid in range(self.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queue, result_queue, self.collate_fn,
                      wid, self.worker_init_fn, ring, self.num_workers),
                daemon=True)
            w.start()
            workers.append(w)

        def shutdown():
            for _ in workers:
                try:
                    index_queue.put(None)
                except Exception:  # noqa: swallow — best-effort shutdown
                    pass
            for w in workers:
                w.join(timeout=1.0)
                if w.is_alive():
                    w.terminate()
            if ring is not None:
                ring.close()

        def recv():
            if ring is None:
                return result_queue.get()
            from .native import decode_batch
            while True:
                try:  # rare path: errors / oversized batches via the queue
                    return result_queue.get_nowait()
                except queue_mod.Empty:
                    pass
                try:
                    bid, err, batch = decode_batch(ring.get(timeout=0.1))
                    return bid, batch, err
                except TimeoutError:
                    if not any(w.is_alive() for w in workers):
                        raise RuntimeError(
                            "all DataLoader workers died") from None

        try:
            sampler_iter = enumerate(iter(self.batch_sampler))
            in_flight = {}
            reorder = {}
            next_out = 0
            # prime
            for _ in range(self.prefetch_factor * self.num_workers):
                try:
                    bid, indices = next(sampler_iter)
                except StopIteration:
                    break
                index_queue.put((bid, indices))
                in_flight[bid] = True
            while in_flight:
                bid, batch, err = recv()
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                del in_flight[bid]
                reorder[bid] = batch
                try:
                    nbid, indices = next(sampler_iter)
                    index_queue.put((nbid, indices))
                    in_flight[nbid] = True
                except StopIteration:
                    pass
                while next_out in reorder:
                    yield reorder.pop(next_out)
                    next_out += 1
        finally:
            shutdown()


class _DevicePrefetcher:
    """Host→device double buffering (buffered_reader.cc analog): keeps one
    batch already on device while the consumer works on the previous one."""

    def __init__(self, gen: Iterable, depth: int = 2):
        self._gen = iter(gen)
        self._queue: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
        self._done = object()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for batch in self._gen:
                staged = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a) if isinstance(a, np.ndarray) else a,
                    batch)
                self._queue.put(staged)
        except Exception as e:
            self._queue.put(e)
            return
        self._queue.put(self._done)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is self._done:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item


def _collate_slots(rows):
    """[(a0, b0), (a1, b1), ...] → [stack(a), stack(b)] — the reference
    loader's per-slot batch arrays."""
    if not rows:
        return rows
    first = rows[0]
    if not isinstance(first, (tuple, list)):
        return np.stack([np.asarray(r) for r in rows])
    return [np.stack([np.asarray(r[i]) for r in rows])
            for i in range(len(first))]


class _GeneratorLoader:
    """Pre-2.0 DataLoader.from_generator facade: set_batch_generator/
    set_sample_generator feed a python generator; iteration yields its
    batches (the reference's feed-queue machinery collapses into plain
    iteration in the one-codepath design).  Re-iterable: the generator
    function is called afresh per epoch."""

    def __init__(self):
        self._fn = None

    def set_batch_generator(self, fn, places=None):
        self._fn = fn
        return self

    def set_sample_generator(self, fn, batch_size: int = 1, places=None,
                             drop_last: bool = True):
        from ..reader import batch as _batch
        batched = _batch(fn, batch_size, drop_last=drop_last)

        def gen():
            for rows in batched():
                yield _collate_slots(list(rows))   # per-slot arrays

        self._fn = gen
        return self

    def set_sample_list_generator(self, fn, places=None):
        def gen():
            for rows in fn():
                yield _collate_slots(list(rows))

        self._fn = gen
        return self

    def __iter__(self):
        enforce(self._fn is not None,
                "call set_batch_generator/set_sample_generator first")
        return iter(self._fn())


class ChainDataset(IterableDataset):
    """Chain iterable datasets back-to-back (reference ChainDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds
