"""ctypes binding + message codec for the native shared-memory ring
(io/_native/shm_ring.cc — see its header for the design rationale;
the reference analog is mmap_allocator.cc + lod_tensor_blocking_queue.h).

Batches cross the ring as [u32 meta_len][pickle meta][raw array buffers]:
only tiny metadata is pickled; array payloads are gathered straight into
the shared slot (srq_put iovecs) and rebuilt with np.frombuffer on the
parent side.  The .so is compiled on first use with g++ (no pybind11 in
the image; plain C ABI via ctypes) and cached next to the source.
"""
from __future__ import annotations

import ctypes
import mmap
import os
import pickle
import struct
import subprocess
import threading
from typing import Any, List, Optional, Tuple

import numpy as np

from ..framework.log import get_logger

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_SRC = os.path.join(_DIR, "shm_ring.cc")
_SO = os.path.join(_DIR, "libshm_ring.so")

_lib = None
_lib_lock = threading.Lock()


class _Iovec(ctypes.Structure):
    _fields_ = [("base", ctypes.c_void_p), ("len", ctypes.c_uint64)]


def _build() -> Optional[str]:
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", _SO, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except Exception as e:  # toolchain missing → python fallback
        get_logger().warning("native dataloader core build failed: %s", e)
        return None


def load_library():
    """The ctypes handle, building the .so on first use; None if the
    toolchain is unavailable (callers fall back to the python queue)."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        lib.srq_size.restype = ctypes.c_uint64
        lib.srq_size.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.srq_init.restype = ctypes.c_int
        lib.srq_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                 ctypes.c_uint64]
        lib.srq_put.restype = ctypes.c_int
        lib.srq_put.argtypes = [ctypes.c_void_p, ctypes.POINTER(_Iovec),
                                ctypes.c_uint64, ctypes.c_double]
        lib.srq_get.restype = ctypes.c_int64
        lib.srq_get.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_uint64, ctypes.c_double]
        lib.srq_close.restype = None
        lib.srq_close.argtypes = [ctypes.c_void_p]
        lib.srq_count.restype = ctypes.c_uint64
        lib.srq_count.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_library() is not None


class ShmRing:
    """Fixed-slot MPSC ring in an anonymous shared mapping.

    Create in the PARENT before forking workers — children inherit the
    mapping, so there is nothing to name, unlink, or clean up."""

    def __init__(self, slots: int = 8, slot_bytes: int = 32 << 20):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native dataloader core unavailable")
        self._lib = lib
        self.slots = slots
        self.slot_bytes = slot_bytes
        size = int(lib.srq_size(slots, slot_bytes))
        self._mm = mmap.mmap(-1, size)  # MAP_SHARED|MAP_ANONYMOUS
        self._addr = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
        rc = lib.srq_init(self._addr, slots, slot_bytes)
        if rc != 0:
            raise RuntimeError(f"srq_init failed rc={rc}")
        self._scratch = bytearray(slot_bytes)

    # -- raw message API ---------------------------------------------------
    def put_parts(self, parts: List[Any], timeout: float = 60.0) -> None:
        """Gathered write of buffer-protocol objects as one message."""
        n = len(parts)
        iov = (_Iovec * n)()
        keep = []  # hold buffer references until the call returns
        for i, p in enumerate(parts):
            mv = memoryview(p).cast("B") if not isinstance(p, np.ndarray) \
                else memoryview(np.ascontiguousarray(p)).cast("B")
            if not mv.c_contiguous:
                mv = memoryview(bytes(mv))
            if mv.readonly:
                ro = bytes(mv)
                keep.append(ro)
                iov[i].base = ctypes.cast(ctypes.c_char_p(ro),
                                          ctypes.c_void_p)
                iov[i].len = len(ro)
            else:
                buf = (ctypes.c_char * mv.nbytes).from_buffer(mv)
                keep.append((mv, buf))
                iov[i].base = ctypes.addressof(buf)
                iov[i].len = mv.nbytes
        rc = self._lib.srq_put(self._addr, iov, n, float(timeout))
        if rc == -1:
            raise TimeoutError("ShmRing.put timeout")
        if rc == -2:
            total = sum(memoryview(p).nbytes for p in parts)
            raise ValueError(
                f"message {total}B exceeds slot {self.slot_bytes}B — raise "
                f"DataLoader(native_slot_bytes=...)")
        if rc == -3:
            raise BrokenPipeError("ShmRing closed")

    def get(self, timeout: float = 60.0) -> Optional[bytearray]:
        """One message (writable bytearray); None when closed and drained."""
        buf = self._scratch
        caddr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        rc = self._lib.srq_get(self._addr, caddr, len(buf), float(timeout))
        if rc == -1:
            raise TimeoutError("ShmRing.get timeout")
        if rc == -2:
            raise ValueError("message larger than slot?")
        if rc == -3:
            return None
        # bytearray: decode_batch's np.frombuffer views must be writable,
        # matching the arrays the python-queue transport yields
        return bytearray(buf[: int(rc)])

    def close(self) -> None:
        self._lib.srq_close(self._addr)

    def count(self) -> int:
        return int(self._lib.srq_count(self._addr))


# -- batch codec -------------------------------------------------------------
def encode_batch_parts(bid: int, batch, err: Optional[str] = None
                       ) -> List[Any]:
    """[u32 meta_len][meta pickle][array payloads...] as iovec parts."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(batch)
    arrays = [np.ascontiguousarray(a) for a in leaves]
    meta = pickle.dumps(
        (bid, err, treedef, [(str(a.dtype), a.shape) for a in arrays]))
    parts: List[Any] = [struct.pack("<I", len(meta)), meta]
    parts.extend(arrays)
    return parts


def decode_batch(msg: bytes) -> Tuple[int, Optional[str], Any]:
    import jax
    (meta_len,) = struct.unpack_from("<I", msg, 0)
    bid, err, treedef, specs = pickle.loads(msg[4: 4 + meta_len])
    off = 4 + meta_len
    leaves = []
    for dtype, shape in specs:
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        leaves.append(np.frombuffer(msg, dtype=dtype, count=int(
            np.prod(shape)), offset=off).reshape(shape))
        off += n
    return bid, err, jax.tree_util.tree_unflatten(treedef, leaves)
