"""paddle.jit parity: save/load a trained model for inference (E1/E5).

Reference surfaces being matched:
- ``paddle.jit.to_static`` / ``ProgramTranslator`` (dy2static AST rewrite,
  program_translator.py:236) — on TPU ``jax.jit`` traces python directly,
  so ``to_static`` is a thin alias that exists for ported code;
- ``paddle.jit.save`` → inference model (fluid/io.py save_inference_model):
  here the forward is exported as serialized StableHLO via ``jax.export``
  (compiler-level, versioned, loadable without the model class) together
  with the parameters;
- loading for serving (AnalysisPredictor's load half, E1) =
  :func:`paddle_tpu.jit.load` → a callable ``TranslatedLayer`` analog.

The saved artifact is a directory:
  ``model.stablehlo``  — jax.export serialization of apply(params, *inputs)
  ``params/``          — sharded checkpoint (distributed.checkpoint format)
  ``meta.json``        — input specs / structure
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import export as jax_export

from .distributed.checkpoint import load_sharded, save_sharded
from .framework.errors import enforce
from .utils import fsio

__all__ = ["to_static", "save", "load", "InputSpec", "TranslatedLayer"]


class InputSpec:
    """≙ paddle.static.InputSpec(shape, dtype, name)."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def sds(self, scope=None, prefix: str = "d") -> jax.ShapeDtypeStruct:
        """ShapeDtypeStruct; None/-1 dims become jax.export symbolic dims
        (the paddle contract: None = dynamic, typically the batch axis)."""
        dims = []
        for i, d in enumerate(self.shape):
            if d is None or (isinstance(d, int) and d < 0):
                (sym,) = jax_export.symbolic_shape(f"{prefix}{i}",
                                                   scope=scope)
                dims.append(sym)
            else:
                dims.append(int(d))
        return jax.ShapeDtypeStruct(tuple(dims), jnp.dtype(self.dtype))

    def to_json(self):
        return {"shape": list(self.shape), "dtype": str(self.dtype),
                "name": self.name}

    @staticmethod
    def from_json(d):
        return InputSpec(d["shape"], d["dtype"], d.get("name"))


def to_static(function=None, input_spec=None, **kw):
    """≙ @paddle.jit.to_static — jax traces python directly, so this is
    jax.jit with the decorator calling conventions preserved.
    ``ProgramTranslator.enable(False)`` routes calls to the raw python
    function (the reference's debug-eagerly workflow)."""
    def deco(fn):
        from .observability.compilation import track_jit
        from .observability.compilecache import (
            maybe_enable_persistent_cache)
        # opt-in disk cache (PTPU_COMPILE_CACHE_DIR) so a warm process
        # re-loads instead of re-compiling these programs (ROADMAP 5a)
        maybe_enable_persistent_cache()
        # every to_static callsite reports compiles/retraces to the run
        # doctor under its own name (ISSUE 4)
        jitted = track_jit(jax.jit(fn),
                           name=f"to_static.{getattr(fn, '__name__', fn)}")
        import functools

        @functools.wraps(fn)
        def dispatch(*args, **kwargs):
            if not _translator_state["enabled"] or getattr(
                    fn, "__not_to_static__", False):
                return fn(*args, **kwargs)
            return jitted(*args, **kwargs)
        dispatch.__wrapped_jit__ = jitted
        return dispatch
    if function is None:
        return deco
    return deco(function)


def save(layer, path: str, input_spec: List[InputSpec]) -> None:
    """Export ``layer`` (a Layer with .apply / .eval) for inference.

    The forward is traced at the given specs in eval mode and serialized as
    StableHLO — the artifact needs no python model code to run (the property
    that makes AnalysisPredictor deployments work).
    """
    os.makedirs(path, exist_ok=True)
    layer.eval()
    # plain dict: load_sharded's templateless restore builds plain dicts,
    # and OrderedDict vs dict are different pytree node types to jax.export
    params = dict(layer.state_dict())

    def fwd(p, *inputs):
        return layer.apply(p, *inputs)

    scope = jax_export.SymbolicScope()
    sds = [s.sds(scope=scope, prefix=f"s{i}_")
           for i, s in enumerate(input_spec)]
    exported = jax_export.export(jax.jit(fwd))(params, *sds)
    fsio.write_bytes(os.path.join(path, "model.stablehlo"),
                     bytes(exported.serialize()))
    save_sharded(params, os.path.join(path, "params"))
    fsio.write_bytes(
        os.path.join(path, "meta.json"),
        json.dumps({"input_spec": [s.to_json() for s in input_spec]}
                   ).encode("utf-8"))


class TranslatedLayer:
    """Loaded inference callable (≙ paddle.jit.TranslatedLayer /
    the predictor's run surface)."""

    def __init__(self, path: str):
        with open(os.path.join(path, "model.stablehlo"), "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._params = load_sharded(os.path.join(path, "params"))
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self.input_spec = [InputSpec.from_json(d)
                           for d in meta["input_spec"]]
        self._call = jax.jit(self._exported.call)

    def __call__(self, *inputs):
        args = [jnp.asarray(np.asarray(x)) for x in inputs]
        return self._call(self._params, *args)


def load(path: str) -> TranslatedLayer:
    enforce(os.path.isdir(path), f"no exported model at {path!r}")
    return TranslatedLayer(path)


def not_to_static(fn=None):
    """Mark a function to be excluded from to_static conversion (reference
    jit.not_to_static).  One-codepath runtime: tracing is jax's and the
    marker is metadata — the function runs as plain python either way."""
    if fn is None:
        return not_to_static
    fn.__not_to_static__ = True
    return fn


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """Reference jit.set_code_level: controls dy2static transformed-code
    logging.  There is no source transform here (jax traces python
    directly), so this records the setting for API parity."""
    _translator_state["code_level"] = level


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    _translator_state["verbosity"] = level


_translator_state = {"enabled": True, "code_level": 0, "verbosity": 0}


class ProgramTranslator:
    """Reference dy2static ProgramTranslator singleton: enable() toggles
    whether @to_static functions are traced (False = run eagerly)."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool = True):
        _translator_state["enabled"] = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return _translator_state["enabled"]


class TracedLayer:
    """Reference jit.TracedLayer (dygraph trace → static program).  The
    jax analog: trace(layer, inputs) jit-compiles the layer's forward and
    records example inputs; ``save_inference_model`` delegates to jit.save
    via the captured InputSpec."""

    def __init__(self, layer, inputs):
        import jax
        self._layer = layer
        self._inputs = inputs
        sd = layer.state_dict()
        self._fn = jax.jit(lambda params, *a: layer.apply(params, *a))
        self._params = sd

    def __call__(self, *inputs):
        return self._fn(self._params, *inputs)

    @staticmethod
    def trace(layer, inputs):
        tl = TracedLayer(layer, inputs)
        return tl(*inputs), tl

    def save_inference_model(self, path: str, feed=None, fetch=None):
        specs = [InputSpec(tuple(jnp.asarray(i).shape),
                           str(jnp.asarray(i).dtype)) for i in self._inputs]
        save(self._layer, path, specs)


__all__ += ["TracedLayer", "ProgramTranslator", "set_code_level",
            "set_verbosity", "not_to_static"]
