"""paddle.inference parity (E1): the deployment-facing predictor facade.

Reference: AnalysisPredictor (inference/api/analysis_predictor.h:90) — load
a saved program + params, run an optimization pass pipeline, execute with
zero-copy IO; python surface ``paddle.inference.Config`` /
``create_predictor`` / ``predictor.run``.

TPU-native: the saved artifact is jit-exported StableHLO
(paddle_tpu.jit.save); "the pass pipeline" is XLA compiling that module for
the attached device — there is no separate inference executor to build.
This facade keeps the reference's call shapes so serving code ports
directly."""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

import jax

from .. import jit as pt_jit
from ..framework.errors import enforce

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """≙ paddle.inference.Config(model_dir)."""

    def __init__(self, model_dir: Optional[str] = None):
        self._model_dir = model_dir
        self._device = "tpu"

    def set_model(self, model_dir: str) -> None:
        self._model_dir = model_dir

    def model_dir(self) -> str:
        return self._model_dir

    def disable_gpu(self) -> None:  # source-compat no-op
        self._device = "cpu"

    def enable_memory_optim(self) -> None:  # XLA owns buffer reuse
        pass

    def switch_ir_optim(self, _=True) -> None:  # XLA owns the pass pipeline
        pass


class Predictor:
    """≙ AnalysisPredictor's python surface: named input handles, run(),
    named output fetch."""

    def __init__(self, config: Config):
        enforce(config.model_dir(), "Config.set_model(path) first")
        self._layer = pt_jit.load(config.model_dir())
        n_in = len(self._layer.input_spec)
        self._input_names = [
            s.name or f"input_{i}"
            for i, s in enumerate(self._layer.input_spec)]
        self._inputs: Dict[str, Any] = {}
        self._outputs: List[Any] = []
        assert len(self._input_names) == n_in

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> "_Handle":
        return _Handle(self._inputs, name)

    def run(self) -> None:
        args = [self._inputs[n] for n in self._input_names]
        out = self._layer(*args)
        self._outputs = list(out) if isinstance(out, (tuple, list)) else [out]

    def get_output_names(self) -> List[str]:
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name: str) -> "_OutHandle":
        idx = int(name.split("_")[-1])
        return _OutHandle(self._outputs, idx)


class _Handle:
    def __init__(self, store: Dict[str, Any], name: str):
        self._store, self._name = store, name

    def copy_from_cpu(self, arr) -> None:
        self._store[self._name] = np.asarray(arr)

    def reshape(self, shape) -> None:  # source-compat no-op (static shapes)
        pass


class _OutHandle:
    def __init__(self, outputs: List[Any], idx: int):
        self._outputs, self._idx = outputs, idx

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._outputs[self._idx])


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
