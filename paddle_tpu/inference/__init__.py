"""paddle.inference parity (E1): the deployment-facing predictor facade.

Reference: AnalysisPredictor (inference/api/analysis_predictor.h:90) — load
a saved program + params, run an optimization pass pipeline, execute with
zero-copy IO; python surface ``paddle.inference.Config`` /
``create_predictor`` / ``predictor.run``.

TPU-native: the saved artifact is jit-exported StableHLO
(paddle_tpu.jit.save); "the pass pipeline" is XLA compiling that module for
the attached device — there is no separate inference executor to build.
This facade keeps the reference's call shapes so serving code ports
directly."""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

import jax

from .. import jit as pt_jit
from ..framework.errors import enforce

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """≙ paddle.inference.Config(model_dir)."""

    def __init__(self, model_dir: Optional[str] = None):
        self._model_dir = model_dir
        self._device = "tpu"

    def set_model(self, model_dir: str) -> None:
        self._model_dir = model_dir

    def model_dir(self) -> str:
        return self._model_dir

    def disable_gpu(self) -> None:  # source-compat no-op
        self._device = "cpu"

    def enable_memory_optim(self) -> None:  # XLA owns buffer reuse
        pass

    def switch_ir_optim(self, _=True) -> None:  # XLA owns the pass pipeline
        pass


class Predictor:
    """≙ AnalysisPredictor's python surface: named input handles, run(),
    named output fetch."""

    def __init__(self, config: Config):
        enforce(config.model_dir(), "Config.set_model(path) first")
        self._layer = pt_jit.load(config.model_dir())
        n_in = len(self._layer.input_spec)
        self._input_names = [
            s.name or f"input_{i}"
            for i, s in enumerate(self._layer.input_spec)]
        self._inputs: Dict[str, Any] = {}
        self._outputs: List[Any] = []
        assert len(self._input_names) == n_in

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> "_Handle":
        return _Handle(self._inputs, name)

    def run(self) -> None:
        args = [self._inputs[n] for n in self._input_names]
        out = self._layer(*args)
        self._outputs = list(out) if isinstance(out, (tuple, list)) else [out]

    def get_output_names(self) -> List[str]:
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name: str) -> "_OutHandle":
        idx = int(name.split("_")[-1])
        return _OutHandle(self._outputs, idx)


class _Handle:
    def __init__(self, store: Dict[str, Any], name: str):
        self._store, self._name = store, name

    def copy_from_cpu(self, arr) -> None:
        self._store[self._name] = np.asarray(arr)

    def reshape(self, shape) -> None:  # source-compat no-op (static shapes)
        pass


class _OutHandle:
    def __init__(self, outputs: List[Any], idx: int):
        self._outputs, self._idx = outputs, idx

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._outputs[self._idx])


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# reference paddle.inference __all__ parity: type enums + utility surface
import enum as _enum

import numpy as _np


class DataType(_enum.Enum):
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    INT8 = "int8"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType(_enum.Enum):
    CPU = "cpu"
    GPU = "gpu"        # maps to the accelerator (TPU) on this stack
    XPU = "xpu"
    UNK = "unk"


class PrecisionType(_enum.Enum):
    Float32 = "float32"
    Half = "float16"
    Int8 = "int8"


Tensor = _Handle      # the predictor's tensor handle role


def get_version() -> str:
    from .. import __version__
    return __version__


def get_trt_compile_version():
    return (0, 0, 0)       # TensorRT is N/A on TPU (XLA is the engine)


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype) -> int:
    name = dtype.value if isinstance(dtype, DataType) else str(dtype)
    return _np.dtype(name).itemsize


class PredictorPool:
    """Reference PredictorPool(config, size): N independent predictors —
    here they share the compiled XLA executable (compilation is cached),
    so the pool is a list of Predictor facades."""

    def __init__(self, config: Config, size: int = 1):
        self._predictors = [create_predictor(config) for _ in range(size)]

    def retrive(self, idx: int) -> Predictor:   # reference spelling
        return self._predictors[idx]

    retrieve = retrive


__all__ += ["DataType", "PlaceType", "PrecisionType", "Tensor",
            "get_version", "get_trt_compile_version",
            "get_trt_runtime_version", "get_num_bytes_of_data_type",
            "PredictorPool"]
