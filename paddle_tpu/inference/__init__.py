"""paddle.inference parity (E1): the deployment-facing predictor facade.

Reference: AnalysisPredictor (inference/api/analysis_predictor.h:90) — load
a saved program + params, run an optimization pass pipeline, execute with
zero-copy IO; python surface ``paddle.inference.Config`` /
``create_predictor`` / ``predictor.run``.

TPU-native: the saved artifact is jit-exported StableHLO
(paddle_tpu.jit.save); "the pass pipeline" is XLA compiling that module for
the attached device — there is no separate inference executor to build.
This facade keeps the reference's call shapes so serving code ports
directly.

ISSUE 6 grows this package into a real serving subsystem for decoder
models: :mod:`.engine` (ServingEngine: continuous batching over a paged
KV cache), :mod:`.kv_cache` (block allocator + page arrays),
:mod:`.paged_attention` (ragged decode kernel + lax fallback),
:mod:`.scheduler` (admission/preemption policy).  The legacy Config
routes onto it via ``enable_continuous_batching`` +
``set_decoder_model`` — see docs/ARCHITECTURE.md "Serving"."""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

import jax

from .. import jit as pt_jit
from ..framework.errors import enforce

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """≙ paddle.inference.Config(model_dir)."""

    def __init__(self, model_dir: Optional[str] = None):
        self._model_dir = model_dir
        self._device = "tpu"
        self._cb_enabled = False
        self._cb_max_seqs: Optional[int] = None
        self._cb_kv_block_size: Optional[int] = None
        self._decoder_model = None
        self._max_new_tokens = 32
        self._eos_token_id: Optional[int] = None
        self._pad_token_id: Optional[int] = None

    def set_model(self, model_dir: str) -> None:
        self._model_dir = model_dir

    def model_dir(self) -> str:
        return self._model_dir

    def disable_gpu(self) -> None:  # source-compat no-op
        self._device = "cpu"

    def enable_memory_optim(self) -> None:  # XLA owns buffer reuse
        pass

    def switch_ir_optim(self, _=True) -> None:  # XLA owns the pass pipeline
        pass

    # -- serving-engine routing (ISSUE 6) ---------------------------------
    def enable_continuous_batching(self, max_seqs: Optional[int] = None,
                                   kv_block_size: Optional[int] = None
                                   ) -> None:
        """Route this config's predictor onto the paged-KV
        :class:`~paddle_tpu.inference.engine.ServingEngine` (decoder
        models only — attach one with :meth:`set_decoder_model`).  The
        reference predictor call shapes (input handles / ``run()`` /
        output handles) keep working; under the hood each batch row
        becomes a ragged engine request."""
        self._cb_enabled = True
        self._cb_max_seqs = max_seqs
        self._cb_kv_block_size = kv_block_size

    def continuous_batching_enabled(self) -> bool:
        return self._cb_enabled

    def set_decoder_model(self, model, max_new_tokens: int = 32,
                          eos_token_id: Optional[int] = None,
                          pad_token_id: Optional[int] = None) -> None:
        """Attach a decoder model object (``GPTForCausalLM``-like) for
        the continuous-batching path.  A jit-exported StableHLO module
        (``set_model``) cannot decode incrementally — the engine needs
        the live layer to thread paged caches through."""
        self._decoder_model = model
        self._max_new_tokens = int(max_new_tokens)
        self._eos_token_id = eos_token_id
        self._pad_token_id = pad_token_id


class Predictor:
    """≙ AnalysisPredictor's python surface: named input handles, run(),
    named output fetch."""

    def __init__(self, config: Config):
        enforce(config.model_dir(), "Config.set_model(path) first")
        self._layer = pt_jit.load(config.model_dir())
        n_in = len(self._layer.input_spec)
        self._input_names = [
            s.name or f"input_{i}"
            for i, s in enumerate(self._layer.input_spec)]
        self._inputs: Dict[str, Any] = {}
        self._outputs: List[Any] = []
        assert len(self._input_names) == n_in

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> "_Handle":
        return _Handle(self._inputs, name)

    def run(self) -> None:
        args = [self._inputs[n] for n in self._input_names]
        out = self._layer(*args)
        self._outputs = list(out) if isinstance(out, (tuple, list)) else [out]

    def get_output_names(self) -> List[str]:
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name: str) -> "_OutHandle":
        idx = int(name.split("_")[-1])
        return _OutHandle(self._outputs, idx)


class _Handle:
    def __init__(self, store: Dict[str, Any], name: str):
        self._store, self._name = store, name

    def copy_from_cpu(self, arr) -> None:
        self._store[self._name] = np.asarray(arr)

    def reshape(self, shape) -> None:  # source-compat no-op (static shapes)
        pass


class _OutHandle:
    def __init__(self, outputs: List[Any], idx: int):
        self._outputs, self._idx = outputs, idx

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._outputs[self._idx])


class EnginePredictor:
    """Reference predictor call shapes over the serving engine: a batch
    ``run()`` submits every row as a ragged request (trailing pad
    stripped), drives the engine to completion, and pads the generated
    continuations back into one ``(batch, max_len)`` output tensor."""

    def __init__(self, config: Config):
        enforce(config._decoder_model is not None,
                "enable_continuous_batching needs set_decoder_model(model)"
                " — an exported StableHLO module cannot decode "
                "incrementally")
        from .engine import ServingEngine
        self._config = config
        self.engine = ServingEngine(config._decoder_model,
                                    max_seqs=config._cb_max_seqs,
                                    kv_block_size=config._cb_kv_block_size)
        self._input_names = ["input_ids"]
        self._inputs: Dict[str, Any] = {}
        self._outputs: List[Any] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> "_Handle":
        return _Handle(self._inputs, name)

    def run(self) -> None:
        cfg = self._config
        ids = np.asarray(self._inputs["input_ids"])
        enforce(ids.ndim == 2, f"input_ids must be (batch, len), "
                f"got {ids.shape}")
        prompts = []
        for row in ids:
            toks = [int(t) for t in row]
            if cfg._pad_token_id is not None:
                while toks and toks[-1] == cfg._pad_token_id:
                    toks.pop()
            prompts.append(toks)
        outs = self.engine.generate(prompts,
                                    max_new_tokens=cfg._max_new_tokens,
                                    eos_token_id=cfg._eos_token_id)
        full = [p + o for p, o in zip(prompts, outs)]
        width = max(len(f) for f in full)
        pad = cfg._pad_token_id if cfg._pad_token_id is not None else 0
        out = np.full((len(full), width), pad, np.int64)
        for i, f in enumerate(full):
            out[i, :len(f)] = f
        self._outputs = [out]

    def get_output_names(self) -> List[str]:
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name: str) -> "_OutHandle":
        return _OutHandle(self._outputs, int(name.split("_")[-1]))


def create_predictor(config: Config):
    if config.continuous_batching_enabled():
        return EnginePredictor(config)
    return Predictor(config)


# reference paddle.inference __all__ parity: type enums + utility surface
import enum as _enum

import numpy as _np


class DataType(_enum.Enum):
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    INT8 = "int8"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType(_enum.Enum):
    CPU = "cpu"
    GPU = "gpu"        # maps to the accelerator (TPU) on this stack
    XPU = "xpu"
    UNK = "unk"


class PrecisionType(_enum.Enum):
    Float32 = "float32"
    Half = "float16"
    Int8 = "int8"


Tensor = _Handle      # the predictor's tensor handle role


def get_version() -> str:
    from .. import __version__
    return __version__


def get_trt_compile_version():
    return (0, 0, 0)       # TensorRT is N/A on TPU (XLA is the engine)


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype) -> int:
    name = dtype.value if isinstance(dtype, DataType) else str(dtype)
    return _np.dtype(name).itemsize


class PredictorPool:
    """Reference PredictorPool(config, size): N independent predictors —
    here they share the compiled XLA executable (compilation is cached),
    so the pool is a list of Predictor facades."""

    def __init__(self, config: Config, size: int = 1):
        self._predictors = [create_predictor(config) for _ in range(size)]

    def retrive(self, idx: int) -> Predictor:   # reference spelling
        return self._predictors[idx]

    retrieve = retrive


__all__ += ["DataType", "PlaceType", "PrecisionType", "Tensor",
            "get_version", "get_trt_compile_version",
            "get_trt_runtime_version", "get_num_bytes_of_data_type",
            "PredictorPool"]


# -- the serving subsystem (ISSUE 6) ----------------------------------------
from .engine import CollectTimeout, ServingEngine  # noqa: E402
from .kv_cache import BlockAllocator, PagedKVCache  # noqa: E402
from .paged_attention import paged_attention  # noqa: E402
from .scheduler import ContinuousBatchingScheduler  # noqa: E402

__all__ += ["ServingEngine", "CollectTimeout", "PagedKVCache",
            "BlockAllocator", "ContinuousBatchingScheduler",
            "paged_attention", "EnginePredictor"]

# -- the serving fleet (ISSUE 16) -------------------------------------------
from . import fleet  # noqa: E402

__all__ += ["fleet"]
