"""SLO-driven fleet autoscaler (ISSUE 17; ROADMAP item 1's deferred
scaling loop).

The PR 9 elastic coordinator resizes the *training* world through a
quiesce → fence → resize arc; :class:`FleetAutoscaler` recasts that arc
for serving: observe each replica's serving stats against a declared
:class:`ServingSLO`, and when the SLO *burns* for enough of a sliding
window, actuate the :class:`..replica.ReplicaManager`:

    scale up   = manager.spawn()                    (new slot, or a
                 retired slot respawned — replica ids stay stable)
    scale down = router.drain_replica(victim)       (quiesce: migrate
                 live streams to the survivors)
                 manager.retire(victim)             (fence: the slot is
                 marked retired in place, never renumbered)

Control loop, one :meth:`step` per tick:

1. **Sample** — every active replica's ``serving_stats()``: queue
   depth + waiting + running (pressure) and the engine-local
   ``slo.ttft_ms.p99`` / ``slo.tpot_ms.p99`` tails.  A sample is
   *burning* when any declared SLO is violated, *idle* when the fleet
   holds no work at all.
2. **Window** — samples older than ``window_secs`` age out.  Burn
   fraction ≥ ``burn_threshold`` over a *full* window ⇒ scale-up
   pressure; an entirely idle full window ⇒ scale-down pressure.
   Burn-rate-over-window (not instantaneous breach) is what keeps one
   slow request from flapping the fleet size — the autoscaler's own
   hysteresis, mirroring the circuit breaker's.
3. **Actuate** — bounded by ``PTPU_FLEET_MIN`` / ``PTPU_FLEET_MAX``
   and rate-limited by ``PTPU_FLEET_SCALE_COOLDOWN_SECS`` between
   actions.  Every decision — including ``blocked_at_max``, the one
   operators page on — is a ``fleet.autoscale`` timeline record.

Injectable ``clock`` so drills drive the window on fake time.
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ...framework.errors import enforce
from ...framework.log import vlog

__all__ = ["MIN_ENV", "MAX_ENV", "SCALE_WINDOW_SECS_ENV",
           "SCALE_COOLDOWN_SECS_ENV", "SLO_SOURCE_ENV",
           "default_fleet_min", "default_fleet_max",
           "default_scale_window_secs", "default_scale_cooldown_secs",
           "default_slo_source", "ServingSLO", "FleetAutoscaler"]

MIN_ENV = "PTPU_FLEET_MIN"
MAX_ENV = "PTPU_FLEET_MAX"
SCALE_WINDOW_SECS_ENV = "PTPU_FLEET_SCALE_WINDOW_SECS"
SCALE_COOLDOWN_SECS_ENV = "PTPU_FLEET_SCALE_COOLDOWN_SECS"
SLO_SOURCE_ENV = "PTPU_FLEET_SLO_SOURCE"


def default_fleet_min() -> int:
    return int(os.environ.get(MIN_ENV, "1"))


def default_fleet_max() -> int:
    return int(os.environ.get(MAX_ENV, "4"))


def default_scale_window_secs() -> float:
    return float(os.environ.get(SCALE_WINDOW_SECS_ENV, "10"))


def default_scale_cooldown_secs() -> float:
    return float(os.environ.get(SCALE_COOLDOWN_SECS_ENV, "30"))


def default_slo_source() -> str:
    """Whose latency tails the autoscaler burns on: ``engine`` =
    per-replica engine-local p99s (the PR 17 behavior), ``router`` =
    the router's client-observed ``fleet.ttft_ms``/``fleet.tpot_ms``
    tails, which include queueing, retries and failover recompute
    (ISSUE 18)."""
    src = os.environ.get(SLO_SOURCE_ENV, "engine").strip().lower()
    enforce(src in ("engine", "router"),
            f"{SLO_SOURCE_ENV}={src!r}: expected 'engine' or 'router'")
    return src


class ServingSLO:
    """Declared serving objectives; ``None`` disables a dimension.

    ``queue_depth`` is per-replica queued+waiting+running pressure;
    the latency targets are checked against each replica's
    engine-local p99 tails (``stats()["slo"]``)."""

    def __init__(self, queue_depth: Optional[float] = 16.0,
                 ttft_p99_ms: Optional[float] = None,
                 tpot_p99_ms: Optional[float] = None):
        self.queue_depth = queue_depth
        self.ttft_p99_ms = ttft_p99_ms
        self.tpot_p99_ms = tpot_p99_ms

    def violations(self, stats: Dict[str, Any]) -> List[str]:
        """SLO dimensions this one replica's stats snapshot violates."""
        out: List[str] = []
        pressure = (float(stats.get("queue_depth", 0))
                    + float(stats.get("waiting", 0))
                    + float(stats.get("running", 0)))
        if self.queue_depth is not None and pressure > self.queue_depth:
            out.append(f"queue_depth {pressure:.0f} > "
                       f"{self.queue_depth:.0f}")
        slo = stats.get("slo") or {}
        ttft = (slo.get("ttft_ms") or {}).get("p99")
        if (self.ttft_p99_ms is not None and ttft is not None
                and ttft > self.ttft_p99_ms):
            out.append(f"ttft_p99 {ttft:.1f}ms > {self.ttft_p99_ms}ms")
        tpot = (slo.get("tpot_ms") or {}).get("p99")
        if (self.tpot_p99_ms is not None and tpot is not None
                and tpot > self.tpot_p99_ms):
            out.append(f"tpot_p99 {tpot:.1f}ms > {self.tpot_p99_ms}ms")
        return out

    def describe(self) -> Dict[str, Any]:
        return {"queue_depth": self.queue_depth,
                "ttft_p99_ms": self.ttft_p99_ms,
                "tpot_p99_ms": self.tpot_p99_ms}


_ACTIVE_STATES = ("starting", "healthy", "flapping")


class FleetAutoscaler:
    """Burn-rate control loop over a replica manager (+ router).

    ``manager`` must speak the actuator protocol (``spawn`` /
    ``retire`` / ``poll_states`` / ``replicas``) — both
    :class:`..replica.ReplicaManager` and
    :class:`..replica.LocalReplicaManager` do.  ``router`` (optional)
    lets scale-down quiesce first via ``drain_replica``; without one,
    the victim replica is retired cold (its engine's own drain/spill
    discipline still applies)."""

    def __init__(self, manager, *, router=None,
                 slo: Optional[ServingSLO] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 window_secs: Optional[float] = None,
                 burn_threshold: float = 0.5,
                 cooldown_secs: Optional[float] = None,
                 slo_source: Optional[str] = None,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic):
        self.manager = manager
        self.router = router
        self.slo = slo if slo is not None else ServingSLO()
        self.slo_source = (slo_source if slo_source is not None
                           else default_slo_source())
        enforce(self.slo_source in ("engine", "router"),
                f"bad slo_source {self.slo_source!r}")
        enforce(self.slo_source != "router" or router is not None,
                "slo_source='router' needs a router")
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else default_fleet_min())
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else default_fleet_max())
        enforce(1 <= self.min_replicas <= self.max_replicas,
                f"bad autoscaler bounds [{self.min_replicas}, "
                f"{self.max_replicas}]")
        self.window_secs = float(window_secs if window_secs is not None
                                 else default_scale_window_secs())
        self.burn_threshold = float(burn_threshold)
        self.cooldown_secs = float(
            cooldown_secs if cooldown_secs is not None
            else default_scale_cooldown_secs())
        self._registry = registry
        self.clock = clock
        # (ts, burning, idle) samples — guarded_by: single control
        # thread (the loop owner); never shared
        self._window: Deque[Tuple[float, bool, bool]] = deque()
        self._last_action_at: Optional[float] = None
        self.actions = {"up": 0, "down": 0, "blocked_at_max": 0}

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ...observability.registry import get_registry
        return get_registry()

    # -- observe -----------------------------------------------------------
    def active_ids(self) -> List[int]:
        states = self.manager.poll_states()
        return [i for i, s in states.items() if s in _ACTIVE_STATES]

    def sample(self) -> Dict[str, Any]:
        """One observation: per-replica SLO verdicts folded into a
        (burning, idle) window sample."""
        now = float(self.clock())
        violations: Dict[Any, List[str]] = {}
        pressure = 0.0
        for idx in self.active_ids():
            replica = self.manager.replicas[idx]
            try:
                stats = replica.serving_stats()
            except ConnectionError:
                continue              # census handles dead/flapping
            if self.slo_source == "engine":
                v = self.slo.violations(stats)
                if v:
                    violations[idx] = v
            pressure += (float(stats.get("queue_depth", 0))
                         + float(stats.get("waiting", 0))
                         + float(stats.get("running", 0)))
        if self.slo_source == "router":
            # burn on the client-observed tails: the router's numbers
            # include queueing, retries and failover recompute — the
            # components engine-local p99s cannot see (ISSUE 18)
            v = self.slo.violations(self.router.slo_stats())
            if v:
                violations["router"] = v
        burning = bool(violations)
        idle = pressure == 0.0
        self._window.append((now, burning, idle))
        while self._window and now - self._window[0][0] > self.window_secs:
            self._window.popleft()
        return {"burning": burning, "idle": idle, "pressure": pressure,
                "violations": violations}

    def _window_full(self, now: float) -> bool:
        return bool(self._window
                    and now - self._window[0][0] >= self.window_secs * 0.9)

    def burn_fraction(self) -> float:
        if not self._window:
            return 0.0
        return sum(1 for _, b, _i in self._window if b) / len(self._window)

    def idle_fraction(self) -> float:
        if not self._window:
            return 0.0
        return sum(1 for _, _b, i in self._window if i) / len(self._window)

    # -- actuate -----------------------------------------------------------
    def _emit(self, action: str, active: int, target: int,
              why: str) -> None:
        reg = self._reg()
        self.actions[action] = self.actions.get(action, 0) + 1
        reg.counter("fleet.autoscale").inc()
        reg.emit("fleet.autoscale", action=action, replicas=active,
                 target=target, burn=round(self.burn_fraction(), 3),
                 idle=round(self.idle_fraction(), 3), why=why,
                 slo=self.slo.describe())
        vlog(0, "fleet: autoscale %s %d -> %d (%s)", action, active,
             target, why)

    def _in_cooldown(self, now: float) -> bool:
        return (self._last_action_at is not None
                and now - self._last_action_at < self.cooldown_secs)

    def _pick_victim(self, active: List[int]) -> int:
        """Scale-down victim: the least-loaded active replica (ties →
        highest id, so retired-slot reuse stays compact)."""
        def load(idx: int) -> float:
            try:
                s = self.manager.replicas[idx].serving_stats()
            except ConnectionError:
                return -1.0           # unreachable — cheapest to lose
            return (float(s.get("queue_depth", 0))
                    + float(s.get("waiting", 0))
                    + float(s.get("running", 0)))
        return sorted(active, key=lambda i: (load(i), -i))[0]

    def step(self) -> Optional[str]:
        """Sample + decide + (maybe) actuate.  Returns the action taken
        ("up" / "down" / "blocked_at_max") or None."""
        obs = self.sample()
        now = float(self.clock())
        active = self.active_ids()
        n = len(active)
        if not self._window_full(now) or self._in_cooldown(now):
            return None
        burn = self.burn_fraction()
        if burn >= self.burn_threshold:
            why = "; ".join(
                (f"{i}: {', '.join(v)}" if isinstance(i, str)
                 else f"replica {i}: {', '.join(v)}")
                for i, v in sorted(obs["violations"].items(),
                                   key=str)
                ) or f"burn {burn:.2f} over window"
            if n >= self.max_replicas:
                self._last_action_at = now
                self._emit("blocked_at_max", n, n, why)
                return "blocked_at_max"
            self.manager.spawn()
            self._last_action_at = now
            self._emit("up", n, n + 1, why)
            return "up"
        if self.idle_fraction() >= 1.0 and n > self.min_replicas:
            victim = self._pick_victim(active)
            if self.router is not None:
                self.router.drain_replica(victim)
            self.manager.retire(victim)
            self._last_action_at = now
            self._emit("down", n, n - 1,
                       f"idle through window; retired replica {victim}")
            return "down"
        return None

    def run(self, duration_secs: float,
            interval_secs: float = 1.0, sleep=time.sleep) -> None:
        """Drive the loop for a bounded wall-clock span (drills; a
        real deployment owns its own ticker)."""
        deadline = float(self.clock()) + float(duration_secs)
        while float(self.clock()) < deadline:
            self.step()
            sleep(interval_secs)

    def stats(self) -> Dict[str, Any]:
        return {"bounds": [self.min_replicas, self.max_replicas],
                "window_secs": self.window_secs,
                "burn_threshold": self.burn_threshold,
                "cooldown_secs": self.cooldown_secs,
                "burn": round(self.burn_fraction(), 3),
                "idle": round(self.idle_fraction(), 3),
                "samples": len(self._window),
                "actions": dict(self.actions),
                "slo": self.slo.describe(),
                "slo_source": self.slo_source}
