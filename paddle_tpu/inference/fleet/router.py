"""Fleet router: queue-aware dispatch with token-exact failover.

The router is the layer the ROADMAP's "millions of users" tier needs
above the single-host ServingEngine: it owns a set of replicas (any
mix of :class:`..replica.LocalReplica` / ``HttpReplica``) and gives
clients one durable stream per request, surviving replica death,
clean drains, rolling upgrades — and, since ISSUE 17, its *own* death.

Mechanics:

- **Dispatch** — least-loaded by each replica's ``/statusz`` serving
  section (``queue_depth + waiting + running``); a stream with a
  ``session`` key is affine to the replica already serving that
  session (KV/prefix locality), unless that replica left the healthy
  set.  Dispatch failures retry with bounded exponential backoff
  (``PTPU_FLEET_RETRY_MAX`` × ``PTPU_FLEET_RETRY_BACKOFF_MS``) across
  the healthy set; exhaustion raises :class:`DispatchExhausted`
  naming every replica tried.
- **Admission** — fleet-level generalization of the PR 6 load-shed:
  when total queued work across healthy replicas exceeds
  ``PTPU_FLEET_SHED_QUEUE_DEPTH``, new submissions raise
  :class:`FleetOverloaded` (the caller's 429).
- **Token-exact failover** — the router journals every stream's
  prompt and accepted tokens.  ``pump()`` polls new tokens into the
  journal; when a replica dies mid-stream (SIGKILL — no spill file),
  the survivors' journal entries are re-submitted to a healthy
  replica as spill-format records (``output`` = accepted tokens), so
  the engine's recompute-prefill path rebuilds the KV and greedy
  decoding continues **token-exact** — the same seam ``resume()``
  uses.  A replica that drains cleanly hands its ``spilled_records``
  to the router, which migrates them identically.
- **Crash-safe journal** (ISSUE 17) — with a ``run_dir``, every
  journal mutation is written ahead to ``<run_dir>/fleet/journal/``
  through the fsync'd :class:`.journal.JournalStore`.
  ``Router(recover=run_dir)`` rebuilds every stream from the
  directory alone: streams a live replica still owns are
  *re-attached* (polling resumes at the journaled offset); orphans
  are *re-dispatched* through ``admit_record`` — either way the
  client's tokens stay exact across a router SIGKILL with zero
  replica restarts.
- **Flap resistance** (ISSUE 17) — a per-replica
  :class:`.health.CircuitBreaker` turns intermittent transport
  failures into a ``flapping`` census state (excluded from dispatch,
  probed after backoff) instead of failover churn, and every retry /
  failover re-dispatch spends the process-wide
  :class:`.health.RetryBudget`; a dry bucket degrades new work to
  load-shed and defers failovers to the next pump — no retry storms.
- **Rolling upgrade** — :meth:`rolling_upgrade` drains one replica at
  a time (migrating its spill), lets the manager respawn it, waits
  healthy, and moves on; in-flight streams never drop.

Counters: ``fleet.dispatch``, ``fleet.retries``, ``fleet.failovers``,
``fleet.migrations``, ``fleet.shed``, ``fleet.deferred``,
``fleet.recovered``; gauges ``fleet.streams`` and the manager's
``fleet.replicas[state=...]`` census (now including ``flapping``).
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence

from ...framework.errors import enforce
from ...framework.log import vlog
from ...observability import requesttrace
from .health import CircuitBreaker, get_retry_budget
from .journal import JournalStore

__all__ = ["RETRY_MAX_ENV", "RETRY_BACKOFF_MS_ENV",
           "SHED_QUEUE_DEPTH_ENV", "default_retry_max",
           "default_retry_backoff_ms", "default_shed_queue_depth",
           "FleetOverloaded", "DispatchExhausted", "StreamJournal",
           "Router"]

RETRY_MAX_ENV = "PTPU_FLEET_RETRY_MAX"
RETRY_BACKOFF_MS_ENV = "PTPU_FLEET_RETRY_BACKOFF_MS"
SHED_QUEUE_DEPTH_ENV = "PTPU_FLEET_SHED_QUEUE_DEPTH"

#: seconds a stream's coalesced "deliver" span may stay open before
#: the router flushes it (finish always flushes).  Bounds both the
#: span-emission rate on the pump hot path and the deliver coverage a
#: router crash can lose.
DELIVER_FLUSH_S = 0.25


def default_retry_max() -> int:
    return int(os.environ.get(RETRY_MAX_ENV, "3"))


def default_retry_backoff_ms() -> float:
    return float(os.environ.get(RETRY_BACKOFF_MS_ENV, "50"))


def default_shed_queue_depth() -> int:
    return int(os.environ.get(SHED_QUEUE_DEPTH_ENV, "64"))


def _pctl(values, p: float) -> Optional[float]:
    """Nearest-rank percentile over a small sample; None when empty."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(len(ordered) * p / 100.0))
    return float(ordered[idx])


class FleetOverloaded(RuntimeError):
    """Fleet-level admission refusal (every replica is past the shed
    threshold, the aggregate queue is, or the retry budget is dry) —
    the client's 429."""


class DispatchExhausted(RuntimeError):
    """Dispatch retries exhausted; the message names every replica
    tried so operators see the blast radius, not just the last error."""


class StreamJournal:
    """One client stream's durable record: the prompt plus every token
    the router has accepted — exactly the spill-format record a fresh
    engine re-admits token-exactly on failover."""

    def __init__(self, request_id: str, prompt: Sequence[int],
                 max_new_tokens: int, eos_token_id: Optional[int],
                 session: Optional[str] = None,
                 trace_id: Optional[str] = None):
        self.request_id = request_id
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.session = session
        self.tokens: List[int] = []     # accepted (journaled) tokens
        self.finished = False
        self.reason: Optional[str] = None
        self.replica_id: Optional[int] = None
        self.failovers = 0
        # request tracing (ISSUE 18): the fleet-wide trace context plus
        # the router-side (client-observed) clock marks.  All wall
        # clock — spans must compare across processes on this host.
        self.trace_id = trace_id
        self.resume_why: Optional[str] = None   # stamps re-dispatches
        self.submit_wall: float = time.time()
        self.first_token_wall: Optional[float] = None
        self.last_token_wall: Optional[float] = None
        self.last_progress_wall: Optional[float] = None
        self.end_wall: Optional[float] = None
        self.ttft_ms: Optional[float] = None
        # start of the not-yet-emitted "deliver" stretch.  Deliver
        # spans chain contiguously poll-to-poll, so the router
        # coalesces them and flushes one span per ~DELIVER_FLUSH_S
        # (or at finish) — same interval union as per-poll emission
        # at a fraction of the hot-path emit cost.
        self.deliver_open_wall: Optional[float] = None
        # router-observed per-component milliseconds (the /statusz
        # slow_requests breakdown; the full waterfall needs the
        # assembler)
        self.components: Dict[str, float] = {}

    def record(self) -> Dict[str, Any]:
        """Spill-format record re-admitting this stream mid-flight.
        ``trace_id`` carries the trace context across the process
        boundary; ``resume_why`` tells the receiving engine what to
        attribute the recompute-prefill to."""
        out = {"request_id": self.request_id,
               "prompt": list(self.prompt),
               "output": list(self.tokens),
               "max_new_tokens": self.max_new_tokens,
               "eos_token_id": self.eos_token_id,
               "preemptions": 0,
               "trace_id": self.trace_id}
        if self.resume_why is not None:
            out["resume_why"] = self.resume_why
        return out


class Router:
    """Dispatch + journal + failover over a replica set.

    ``replicas`` maps replica_id → client.  ``manager`` (optional,
    a :class:`..replica.ReplicaManager`) supplies the subprocess
    census for ``poll_states``-driven liveness; without one the
    router probes ``alive()`` itself (the in-process form).

    ``run_dir`` switches on the crash-safe write-ahead journal;
    ``recover`` (a run_dir) additionally rebuilds every stream from
    the journal directory before serving.  ``retry_budget`` overrides
    the process-wide bucket (tests); ``breaker_kw`` overrides the
    per-replica breaker knobs (``failures`` / ``window_secs`` /
    ``backoff_secs`` / ``clock``)."""

    def __init__(self, replicas, *, manager=None, registry=None,
                 retry_max: Optional[int] = None,
                 retry_backoff_ms: Optional[float] = None,
                 shed_queue_depth: Optional[int] = None,
                 run_dir: Optional[str] = None,
                 recover: Optional[str] = None,
                 retry_budget=None,
                 breaker_kw: Optional[Dict[str, Any]] = None,
                 sleep=time.sleep):
        if isinstance(replicas, dict):
            self.replicas = dict(replicas)
        else:
            self.replicas = {r.replica_id: r for r in replicas}
        enforce(self.replicas, "router needs at least one replica")
        self.manager = manager
        self._registry = registry
        self.retry_max = int(retry_max if retry_max is not None
                             else default_retry_max())
        self.retry_backoff_ms = float(
            retry_backoff_ms if retry_backoff_ms is not None
            else default_retry_backoff_ms())
        self.shed_queue_depth = int(
            shed_queue_depth if shed_queue_depth is not None
            else default_shed_queue_depth())
        self._sleep = sleep
        self.journals: Dict[str, StreamJournal] = {}
        self._sessions: Dict[str, int] = {}   # session -> replica_id
        self._ids = 0
        self.dispatch_fault = None   # seam: fn(replica_id, record) pre-send
        self.failovers = 0
        self.migrations = 0
        # flap resistance (ISSUE 17)
        self.budget = (retry_budget if retry_budget is not None
                       else get_retry_budget())
        self._breaker_kw = dict(breaker_kw or {})
        self.breakers: Dict[int, CircuitBreaker] = {}
        # crash-safe journal (ISSUE 17)
        if recover is not None:
            run_dir = recover
        self.store = (JournalStore(run_dir) if run_dir is not None
                      else None)
        self.recovered = {"streams": 0, "reattached": 0,
                          "redispatched": 0, "finished": 0}
        # client-observed latency tails (ISSUE 18): measured at the
        # router, so queueing / retries / failover recompute are all
        # inside the number — the gap to the engine-local serve.* SLO
        # histograms is itself the signal
        self._ttft_ms: Deque[float] = deque(maxlen=512)
        self._tpot_ms: Deque[float] = deque(maxlen=512)
        self._recent: Deque[Dict[str, Any]] = deque(maxlen=64)
        if recover is not None:
            self._recover()

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ...observability.registry import get_registry
        return get_registry()

    def _span(self, journal: StreamJournal, name: str, component: str,
              t0: float, t1: float, **fields) -> None:
        """Emit one router-side trace span and fold its duration into
        the journal's component breakdown (the /statusz table works
        even when the stream is unsampled)."""
        bucket = requesttrace.component_bucket(component)
        journal.components[bucket] = (journal.components.get(bucket, 0.0)
                                      + max(0.0, t1 - t0) * 1e3)
        requesttrace.emit_span(self._reg(), journal.trace_id,
                               journal.request_id, name, component,
                               t0, t1, "router", **fields)

    # -- replica set -------------------------------------------------------
    def _available_ids(self) -> List[int]:
        """Replicas dispatch may consider: healthy, plus flapping ones
        (their breaker gates per-candidate — the half-open probe must
        be dispatchable or an open breaker could never close)."""
        if self.manager is not None:
            states = self.manager.poll_states()
            self.replicas = {i: r for i, r
                             in enumerate(self.manager.replicas)}
            return [i for i, s in states.items()
                    if s in ("healthy", "flapping")]
        return [i for i, r in self.replicas.items() if r.alive()
                and r.healthz()[0] == 200]

    def _healthy_ids(self) -> List[int]:   # PR 16 name, kept for callers
        return self._available_ids()

    def _breaker(self, rid: int) -> CircuitBreaker:
        br = self.breakers.get(rid)
        if br is None:
            def on_transition(prev, new, b, _rid=rid):
                self._on_breaker(_rid, prev, new, b)
            br = CircuitBreaker(on_transition=on_transition,
                                **self._breaker_kw)
            self.breakers[rid] = br
        return br

    def _on_breaker(self, rid: int, prev: str, new: str,
                    breaker: CircuitBreaker) -> None:
        reg = self._reg()
        reg.emit("fleet.breaker", replica=rid, prev=prev, state=new,
                 trips=breaker.trips,
                 backoff_secs=breaker.current_backoff())
        flapping = new in ("open", "half_open")
        if self.manager is not None:
            self.manager.set_flapping(rid, flapping)
        else:
            census = "flapping" if flapping else "healthy"
            reg.emit("fleet.replica_state", replica=rid,
                     prev=("healthy" if flapping else "flapping"),
                     state=census)
            reg.gauge("fleet.replicas[state=flapping]").set(float(
                sum(1 for b in self.breakers.values()
                    if b.state in ("open", "half_open"))))
        if new == "open":
            reg.counter("fleet.breaker_trips").inc()
        vlog(0, "fleet: replica %d breaker %s -> %s (backoff %.1fs)",
             rid, prev, new, breaker.current_backoff())

    def _load(self, replica) -> float:
        """Queue-aware load score from the replica's serving stats;
        unreachable replicas sort last."""
        try:
            s = replica.serving_stats()
        except ConnectionError:
            return float("inf")
        return (float(s.get("queue_depth", 0)) + float(s.get("waiting", 0))
                + float(s.get("running", 0)))

    def _pick(self, session: Optional[str],
              healthy: List[int]) -> List[int]:
        """Candidate order: session-affine replica first (when still
        healthy), then the rest least-loaded."""
        ranked = sorted(healthy,
                        key=lambda i: (self._load(self.replicas[i]), i))
        if session is not None:
            aff = self._sessions.get(session)
            if aff in ranked:
                ranked.remove(aff)
                ranked.insert(0, aff)
        return ranked

    def fleet_depth(self, healthy: List[int]) -> float:
        """Aggregate queued work over reachable replicas (an
        unreachable probe is unknown load, not infinite load — it must
        not flip admission to shed on one dropped packet)."""
        loads = [self._load(self.replicas[i]) for i in healthy]
        return sum(x for x in loads if x != float("inf"))

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, journal: StreamJournal,
                  fresh: bool = True) -> Optional[int]:
        """Send ``journal``'s record to the best replica, retrying with
        backoff across the healthy set.  The first attempt of a fresh
        submission is free; every further send spends the retry
        budget.  Returns the replica id — or, for non-fresh work
        (failover / recovery re-dispatch), None when dispatch must be
        deferred to a later pump (budget dry, nowhere to send).

        Fresh submissions fail loudly instead: a dry budget raises
        :class:`FleetOverloaded` (degrade to load-shed), exhaustion
        raises :class:`DispatchExhausted`."""
        reg = self._reg()
        tried: List[str] = []
        backoff = self.retry_backoff_ms / 1e3
        first_free = fresh
        # trace attribution: a failover/migration re-dispatch is that
        # component's cost, not generic "dispatch"; backoff sleeps get
        # their own segments so nothing is double-counted
        comp = {"failover": "failover",
                "migration": "migration"}.get(journal.resume_why,
                                              "dispatch")
        seg0 = time.time()
        for attempt in range(self.retry_max + 1):
            healthy = self._available_ids()
            for rid in self._pick(journal.session, healthy):
                replica = self.replicas[rid]
                breaker = self._breaker(rid)
                if not breaker.allow():
                    tried.append(f"replica-{rid}: breaker "
                                 f"{breaker.state}")
                    continue
                if first_free:
                    first_free = False
                elif not self.budget.try_acquire():
                    if fresh:
                        reg.counter("fleet.shed").inc()
                        reg.emit("fleet.shed", why="retry_budget",
                                 request_id=journal.request_id)
                        raise FleetOverloaded(
                            f"{journal.request_id}: retry budget dry "
                            f"({self.budget.snapshot()}) — degrading "
                            f"to load-shed")
                    reg.counter("fleet.deferred").inc()
                    reg.emit("fleet.deferred",
                             request_id=journal.request_id,
                             why="retry_budget")
                    self._span(journal, "dispatch", comp, seg0,
                               time.time(), deferred=True)
                    return None
                try:
                    if self.dispatch_fault is not None:
                        self.dispatch_fault(rid, journal.record())
                    replica.submit(journal.record())
                except ConnectionError as e:
                    breaker.record_failure()
                    tried.append(f"replica-{rid}: {e}")
                    continue
                breaker.record_success()
                journal.replica_id = rid
                if journal.session is not None:
                    self._sessions[journal.session] = rid
                if self.store is not None:
                    self.store._append(journal.request_id,
                                       {"kind": "disp", "replica": rid,
                                        "trace_id": journal.trace_id})
                reg.counter("fleet.dispatch").inc()
                reg.emit("fleet.dispatch", request_id=journal.request_id,
                         replica=rid, attempt=attempt,
                         resumed_at=len(journal.tokens),
                         trace_id=journal.trace_id)
                now = time.time()
                self._span(journal, "dispatch", comp, seg0, now,
                           replica=rid, attempt=attempt)
                journal.last_progress_wall = now
                journal.resume_why = None
                return rid
            if attempt < self.retry_max:
                reg.counter("fleet.retries").inc()
                now = time.time()
                self._span(journal, "dispatch", comp, seg0, now,
                           attempt=attempt)
                self._sleep(backoff)
                seg0 = time.time()
                self._span(journal, "retry_backoff", "retry_backoff",
                           now, seg0, attempt=attempt)
                backoff *= 2
        if not fresh:
            reg.counter("fleet.deferred").inc()
            reg.emit("fleet.deferred", request_id=journal.request_id,
                     why="; ".join(tried[-3:]) or "no replica available")
            return None
        raise DispatchExhausted(
            f"{journal.request_id}: dispatch failed after "
            f"{self.retry_max + 1} attempts across replicas "
            f"{sorted(self.replicas)} — " + ("; ".join(tried[-6:])
                                             or "no healthy replica"))

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               request_id: Optional[str] = None,
               eos_token_id: Optional[int] = None,
               session: Optional[str] = None) -> str:
        """Admit one client stream: journal it (durably, with a
        ``run_dir``), then dispatch.  Raises :class:`FleetOverloaded`
        past the fleet shed threshold or on a dry retry budget."""
        healthy = self._available_ids()
        depth = self.fleet_depth(healthy)
        if not healthy or depth > self.shed_queue_depth:
            self._reg().counter("fleet.shed").inc()
            self._reg().emit("fleet.shed", why="queue_depth",
                             depth=depth, healthy=len(healthy))
            raise FleetOverloaded(
                f"fleet admission closed: {len(healthy)} healthy "
                f"replicas, aggregate depth {depth:.0f} > "
                f"{self.shed_queue_depth}")
        if request_id is None:
            # recovered journals may already hold fleet-N names — the
            # counter restarts at 0 after a crash, the streams did not
            while f"fleet-{self._ids}" in self.journals:
                self._ids += 1
            request_id = f"fleet-{self._ids}"
            self._ids += 1
        enforce(request_id not in self.journals,
                f"duplicate request id {request_id!r}")
        journal = StreamJournal(request_id, prompt, max_new_tokens,
                                eos_token_id, session=session,
                                trace_id=requesttrace.mint_trace_id(
                                    request_id))
        self.journals[request_id] = journal
        if self.store is not None:
            # write-ahead: the stream exists durably before dispatch
            self.store.open(request_id, journal.prompt, max_new_tokens,
                            eos_token_id, session=session,
                            trace_id=journal.trace_id)
        if journal.trace_id is not None:
            # lifecycle open: the client-observed window starts here —
            # before dispatch, so a refusal still closes to a complete
            # trace instead of leaking orphan spans
            self._reg().emit("trace.request", trace_id=journal.trace_id,
                             request_id=request_id,
                             t0=journal.submit_wall,
                             prompt_len=len(journal.prompt),
                             proc="router")
        self._reg().gauge("fleet.streams").set(float(len(
            [j for j in self.journals.values() if not j.finished])))
        try:
            self._dispatch(journal, fresh=True)
        except (FleetOverloaded, DispatchExhausted):
            # the client saw a refusal — no ghost stream may linger
            if journal.trace_id is not None:
                self._reg().emit("trace.request_end",
                                 trace_id=journal.trace_id,
                                 request_id=request_id, t1=time.time(),
                                 reason="shed", tokens=0, proc="router")
            del self.journals[request_id]
            if self.store is not None:
                self.store.discard(request_id)
            raise
        return request_id

    # -- recovery (ISSUE 17) ----------------------------------------------
    def _probe_owner(self, journal: StreamJournal,
                     prefer: Optional[int]) -> Optional[int]:
        """Find a replica that still owns ``journal`` (router crashed,
        replicas survived): last-dispatched first, then the rest."""
        order = [i for i in ([prefer] if prefer is not None else [])
                 if i in self.replicas]
        order += [i for i in self._available_ids() if i not in order]
        for rid in order:
            try:
                self.replicas[rid].poll(journal.request_id,
                                        start=len(journal.tokens))
            except Exception:   # unknown rid / unreachable — not ours
                continue
            return rid
        return None

    def _recover(self) -> None:
        """Rebuild every stream from the journal directory: re-attach
        to a replica that still runs it, or re-dispatch the journal
        record through the ``admit_record`` recompute-prefill seam."""
        reg = self._reg()
        for rec in self.store.recover():
            rid = rec["request_id"]
            journal = StreamJournal(rid, rec["prompt"],
                                    rec["max_new_tokens"],
                                    rec["eos_token_id"],
                                    session=rec["session"],
                                    trace_id=rec.get("trace_id"))
            journal.tokens = list(rec["tokens"])
            # the trace window survives the router crash: latency is
            # still measured from the journaled open, not the restart
            if rec.get("opened_ts") is not None:
                journal.submit_wall = float(rec["opened_ts"])
            self.journals[rid] = journal
            self.recovered["streams"] += 1
            if rec["finished"]:
                journal.finished = True
                journal.reason = rec["reason"]
                self.recovered["finished"] += 1
                self.store.retire(rid, rec["reason"])
                continue
            owner = self._probe_owner(journal, rec.get("replica"))
            if owner is not None:
                journal.replica_id = owner
                if journal.session is not None:
                    self._sessions[journal.session] = owner
                self.recovered["reattached"] += 1
            else:
                # orphaned (its replica died with the router): replay
                # the journal record; None = deferred to pump().  The
                # recompute this forces is failover cost.
                journal.resume_why = "failover"
                if self._dispatch(journal, fresh=False) is not None:
                    self.recovered["redispatched"] += 1
        if self.recovered["streams"]:
            reg.counter("fleet.recovered").inc(self.recovered["streams"])
        reg.emit("fleet.recover", **self.recovered)
        self._reg().gauge("fleet.streams").set(float(len(
            [j for j in self.journals.values() if not j.finished])))
        vlog(0, "fleet: recovered %d streams (%d reattached, %d "
             "redispatched, %d already finished)",
             self.recovered["streams"], self.recovered["reattached"],
             self.recovered["redispatched"], self.recovered["finished"])

    # -- streaming / failover ---------------------------------------------
    def _poll_journal(self, journal: StreamJournal) -> bool:
        """Pull new tokens for one live stream into its journal; True
        when progress or completion was observed.  ConnectionError
        propagates — pump() turns it into failover."""
        replica = self.replicas[journal.replica_id]
        out = replica.poll(journal.request_id, start=len(journal.tokens))
        new = [int(t) for t in out["tokens"]]
        now = time.time()
        if new or out["finished"]:
            # client-observed delivery: the stretch since the router
            # last saw progress on this stream.  Generation overlaps
            # it, so the assembler charges "deliver" only the residue
            # no other span covers (poll starvation, HTTP lag) —
            # emitted straight to the registry, NOT folded into the
            # journal's component table, which tracks exclusive time.
            # Consecutive stretches chain contiguously, so they are
            # coalesced and flushed at finish or every DELIVER_FLUSH_S
            # (bounding what a router crash can lose).
            if journal.deliver_open_wall is None:
                journal.deliver_open_wall = (journal.last_progress_wall
                                             or journal.submit_wall)
            if (out["finished"]
                    or now - journal.deliver_open_wall >= DELIVER_FLUSH_S):
                requesttrace.emit_span(self._reg(), journal.trace_id,
                                       journal.request_id, "deliver",
                                       "deliver",
                                       journal.deliver_open_wall, now,
                                       "router")
                journal.deliver_open_wall = now
        if new:
            if self.store is not None:
                # write-ahead: tokens are durable before they count
                self.store.append_tokens(journal.request_id, new)
            journal.tokens.extend(new)
            reg = self._reg()
            if journal.first_token_wall is None:
                journal.first_token_wall = now
                ttft = (now - journal.submit_wall) * 1e3
                journal.ttft_ms = ttft
                reg.histogram("fleet.ttft_ms").observe(ttft)
                self._ttft_ms.append(ttft)
            elif journal.last_token_wall is not None:
                # client-observed inter-token time, split evenly over
                # the tokens this poll surfaced
                per_tok = ((now - journal.last_token_wall)
                           / len(new)) * 1e3
                for _ in new:
                    reg.histogram("fleet.tpot_ms").observe(per_tok)
                    self._tpot_ms.append(per_tok)
            journal.last_token_wall = now
            journal.last_progress_wall = now
        if out["finished"]:
            journal.finished = True
            journal.reason = out.get("reason")
            journal.end_wall = now
            if self.store is not None:
                self.store.retire(journal.request_id, journal.reason)
            if journal.trace_id is not None:
                self._reg().emit("trace.request_end",
                                 trace_id=journal.trace_id,
                                 request_id=journal.request_id,
                                 t1=now, reason=journal.reason,
                                 tokens=len(journal.tokens),
                                 proc="router")
            self._recent.append(self._slow_row(journal, now))
        return bool(new) or journal.finished

    def _failover(self, journal: StreamJournal, why: str) -> None:
        """Re-home one live stream: re-submit its journal record (the
        accepted-token tail rides along) to a healthy replica.  May
        leave the stream undispatched (budget/candidate starvation) —
        the next pump retries."""
        reg = self._reg()
        dead = journal.replica_id
        journal.failovers += 1
        self.failovers += 1
        journal.replica_id = None
        if (journal.session is not None
                and self._sessions.get(journal.session) == dead):
            del self._sessions[journal.session]
        # detection gap: from the stream's last observed progress to
        # the moment the router noticed the replica was gone — the
        # first component of the failover's latency cost
        t_detect = time.time()
        self._span(journal, "failover_detect", "failover",
                   journal.last_progress_wall or t_detect, t_detect,
                   from_replica=dead)
        journal.resume_why = "failover"
        rid = self._dispatch(journal, fresh=False)
        reg.counter("fleet.failovers").inc()
        reg.emit("fleet.failover", request_id=journal.request_id,
                 from_replica=dead, to_replica=rid, why=why,
                 accepted_tokens=len(journal.tokens),
                 trace_id=journal.trace_id)
        vlog(0, "fleet: failover %s replica %s -> %s (%s, %d tokens "
             "accepted)", journal.request_id, dead, rid, why,
             len(journal.tokens))

    def pump(self) -> int:
        """One router turn: step in-process replicas, poll every live
        stream's tokens into its journal, and fail over streams whose
        replica died.  Returns the number of live streams remaining."""
        for replica in self.replicas.values():
            try:
                replica.pump()
            except ConnectionError:
                pass                  # liveness handled per-stream below
        live = [j for j in self.journals.values() if not j.finished]
        for journal in live:
            if journal.replica_id is None:
                # deferred failover/recovery: quiet budgeted retry
                self._dispatch(journal, fresh=False)
                continue
            try:
                self._poll_journal(journal)
            except ConnectionError as e:
                replica = self.replicas.get(journal.replica_id)
                breaker = self._breaker(journal.replica_id)
                if replica is not None and replica.alive():
                    # transient — the replica is up.  Feed the breaker
                    # instead of raising: enough of these in a window
                    # and the replica is flapping, and only THEN do its
                    # streams move (churn costs more than patience).
                    breaker.record_failure()
                    if breaker.state == "closed":
                        continue
                    self._failover(journal,
                                   f"replica flapping ({e})")
                    continue
                breaker.record_failure()
                self._failover(journal, f"replica died ({e})")
        remaining = [j for j in self.journals.values() if not j.finished]
        self._reg().gauge("fleet.streams").set(float(len(remaining)))
        return len(remaining)

    def collect(self, request_id: str,
                timeout: Optional[float] = None) -> Dict[str, Any]:
        """Pump until ``request_id`` finishes; return its journal
        record (tokens are the journaled, failover-stable stream)."""
        journal = self.journals.get(request_id)
        enforce(journal is not None, f"unknown stream {request_id!r}")
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not journal.finished:
            enforce(deadline is None or time.monotonic() < deadline,
                    f"{request_id}: fleet stream not finished after "
                    f"{timeout}s (replica={journal.replica_id}, "
                    f"accepted={len(journal.tokens)})")
            self.pump()
            if not journal.finished:
                self._sleep(0.002)
        return {"request_id": request_id,
                "tokens": list(journal.tokens),
                "finish_reason": journal.reason,
                "replica_id": journal.replica_id,
                "failovers": journal.failovers}

    def run(self, timeout: Optional[float] = None) -> None:
        """Pump until every journaled stream finishes."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while self.pump() > 0:
            enforce(deadline is None or time.monotonic() < deadline,
                    f"fleet streams not drained after {timeout}s")
            self._sleep(0.002)

    # -- drain / rolling upgrade -------------------------------------------
    def drain_replica(self, rid: int,
                      timeout: Optional[float] = None) -> int:
        """Gracefully drain one replica and migrate its spilled
        streams to the rest of the fleet; returns the migration
        count.  The replica ends ``stopped`` — restart it via the
        manager before re-adding."""
        if self.manager is not None:
            # the manager may have spawned slots since construction
            # (autoscaler scale-up) — refresh before indexing
            self.replicas = {i: r for i, r
                             in enumerate(self.manager.replicas)}
        replica = self.replicas[rid]
        report = replica.drain(timeout=timeout)
        migrated = 0
        by_rid = {j.request_id: j for j in self.journals.values()}
        for rec in report.get("spilled_records", []):
            journal = by_rid.get(rec["request_id"])
            if journal is None or journal.finished:
                continue
            # trust the engine's record — it may hold tokens a poll
            # never fetched; both prefixes agree (greedy decode)
            if len(rec.get("output", [])) > len(journal.tokens):
                ahead = [int(t) for t in
                         rec["output"][len(journal.tokens):]]
                if self.store is not None:
                    self.store.append_tokens(journal.request_id, ahead)
                journal.tokens.extend(ahead)
            journal.replica_id = None
            if (journal.session is not None
                    and self._sessions.get(journal.session) == rid):
                del self._sessions[journal.session]
            now = time.time()
            self._span(journal, "migration_wait", "migration",
                       journal.last_progress_wall or now, now,
                       from_replica=rid)
            journal.resume_why = "migration"
            self._dispatch(journal, fresh=True)
            migrated += 1
            self.migrations += 1
            self._reg().counter("fleet.migrations").inc()
        # finished-on-drain streams: pull their final tokens before the
        # replica goes away entirely
        for journal in self.journals.values():
            if journal.replica_id == rid and not journal.finished:
                try:
                    self._poll_journal(journal)
                except ConnectionError:
                    pass
        self._reg().emit("fleet.drain", replica=rid, migrated=migrated,
                         finished=report.get("finished"))
        return migrated

    def rolling_upgrade(self,
                        timeout_per_replica: Optional[float] = None
                        ) -> Dict[int, int]:
        """Drain + respawn every replica one at a time while the rest
        of the fleet absorbs the load; returns replica_id → migrated
        stream count.  Requires a manager (subprocess fleet)."""
        enforce(self.manager is not None,
                "rolling_upgrade() needs a ReplicaManager")
        migrated: Dict[int, int] = {}
        for rid in sorted(self.replicas):
            if self.manager.states.get(rid) in ("dead", "retired"):
                continue
            migrated[rid] = self.drain_replica(
                rid, timeout=timeout_per_replica)
            self.manager.restart(rid)
            self.replicas[rid] = self.manager.replicas[rid]
            self.breakers.pop(rid, None)   # fresh worker, fresh record
            deadline = time.monotonic() + 60.0
            while self.manager.poll_states().get(rid) != "healthy":
                enforce(time.monotonic() < deadline,
                        f"replica {rid} not healthy after respawn")
                self._sleep(0.05)
            vlog(0, "fleet: rolling upgrade — replica %d respawned "
                 "(%d streams migrated)", rid, migrated[rid])
        return migrated

    # -- observability ------------------------------------------------------
    def census(self) -> Dict[int, str]:
        """Replica states with the ``flapping`` overlay: a replica the
        base census calls healthy whose breaker is open/half-open is
        flapping — alive, polled, but not dispatchable."""
        if self.manager is not None:
            base = self.manager.poll_states()
        else:
            base = {i: ("healthy" if r.alive() else "dead")
                    for i, r in self.replicas.items()}
            for i, br in self.breakers.items():
                if (base.get(i) == "healthy"
                        and br.state in ("open", "half_open")):
                    base[i] = "flapping"
        return base

    def _slow_row(self, journal: StreamJournal,
                  now: Optional[float] = None) -> Dict[str, Any]:
        """One ``slow_requests`` table row: client-observed latency so
        far plus the router-side component breakdown."""
        now = time.time() if now is None else now
        end = journal.end_wall if journal.finished else now
        return {"request_id": journal.request_id,
                "trace_id": journal.trace_id,
                "state": "finished" if journal.finished else "live",
                "latency_ms": round(
                    (end - journal.submit_wall) * 1e3, 3),
                "ttft_ms": (None if journal.ttft_ms is None
                            else round(journal.ttft_ms, 3)),
                "tokens": len(journal.tokens),
                "failovers": journal.failovers,
                "replica": journal.replica_id,
                "components": {k: round(v, 3) for k, v
                               in sorted(journal.components.items())}}

    def slow_requests(self, k: int = 8) -> List[Dict[str, Any]]:
        """Top-``k`` slowest streams (in-flight + recently finished) by
        client-observed latency — the ``/statusz`` tail table."""
        now = time.time()
        rows = [self._slow_row(j, now)
                for j in self.journals.values() if not j.finished]
        rows += list(self._recent)
        rows.sort(key=lambda r: r["latency_ms"], reverse=True)
        return rows[:max(0, int(k))]

    def slo_stats(self) -> Dict[str, Any]:
        """Client-observed SLO snapshot shaped like the engine's
        ``serving_stats()`` — the ``PTPU_FLEET_SLO_SOURCE=router``
        feed for :class:`..autoscaler.ServingSLO`."""
        live = [j for j in self.journals.values() if not j.finished]
        return {"queue_depth": self.fleet_depth(self._available_ids()),
                "waiting": 0,
                "running": len(live),
                "slo": {"ttft_ms": {"p50": _pctl(self._ttft_ms, 50),
                                    "p99": _pctl(self._ttft_ms, 99),
                                    "samples": len(self._ttft_ms)},
                        "tpot_ms": {"p50": _pctl(self._tpot_ms, 50),
                                    "p99": _pctl(self._tpot_ms, 99),
                                    "samples": len(self._tpot_ms)}}}

    def stats(self) -> Dict[str, Any]:
        """Fleet snapshot for ``/statusz`` and the doctor."""
        live = [j for j in self.journals.values() if not j.finished]
        states = self.census()
        counts: Dict[str, int] = {}
        for s in states.values():
            counts[s] = counts.get(s, 0) + 1
        out = {"replicas": len(self.replicas),
               "states": counts,
               "streams": {"live": len(live),
                           "finished": len(self.journals) - len(live)},
               "failovers": self.failovers,
               "migrations": self.migrations,
               "sessions": len(self._sessions),
               "breakers": {i: br.snapshot()
                            for i, br in sorted(self.breakers.items())},
               "retry_budget": self.budget.snapshot(),
               "slo": self.slo_stats()["slo"],
               "slow_requests": self.slow_requests()}
        if self.store is not None:
            out["journal"] = {"live": self.store.live_count(),
                              "appends": self.store.appends,
                              "drops": dict(self.store.drops),
                              "recovered": dict(self.recovered)}
        return out
