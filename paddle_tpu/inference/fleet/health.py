"""Flap-resistant fleet health control (ISSUE 17).

PR 16's census is binary — healthy or dead — so one *flapping* replica
(intermittent ConnectionErrors from a half-wedged worker) bounces in
and out of the dispatch set, and every bounce costs a failover plus a
retry storm against a fleet that is already degraded.  Two primitives
fix that:

- :class:`CircuitBreaker` — per-replica failure-rate hysteresis:

      closed ──N failures in window──▶ open
        ▲  ▲                            │ backoff elapses
        │  └───── probe succeeds ── half-open
        │                                │ probe fails
        └────────────────────────────────┴──▶ open (backoff doubles)

  A replica whose breaker is open is *flapping*: excluded from
  dispatch candidates (and surfaced as a ``flapping`` census state)
  without being declared dead — its in-flight streams keep polling,
  and one half-open probe per backoff window checks for recovery.
  Consecutive trips double the backoff (hysteresis), so a replica
  that recovers only to flap again is probed ever less eagerly.

- :class:`RetryBudget` — a process-wide token bucket
  (``PTPU_FLEET_RETRY_BUDGET`` capacity, ``PTPU_FLEET_RETRY_REFILL_PER_S``
  refill): every dispatch retry and failover re-dispatch costs one
  token; the first attempt of a fresh submission is free.  When the
  bucket is dry, new submissions degrade to load-shed
  (:class:`..router.FleetOverloaded`) and failovers defer to the next
  pump instead of hammering the fleet — retries can never outnumber
  capacity + refill·time, which is what "no retry storm" means.

Both take an injectable clock so drills run on fake time.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Deque, Optional

__all__ = ["BREAKER_FAILURES_ENV", "BREAKER_WINDOW_SECS_ENV",
           "BREAKER_BACKOFF_SECS_ENV", "RETRY_BUDGET_ENV",
           "RETRY_REFILL_ENV", "default_breaker_failures",
           "default_breaker_window_secs", "default_breaker_backoff_secs",
           "default_retry_budget", "default_retry_refill_per_s",
           "CircuitBreaker", "RetryBudget", "get_retry_budget",
           "reset_retry_budget"]

BREAKER_FAILURES_ENV = "PTPU_FLEET_BREAKER_FAILURES"
BREAKER_WINDOW_SECS_ENV = "PTPU_FLEET_BREAKER_WINDOW_SECS"
BREAKER_BACKOFF_SECS_ENV = "PTPU_FLEET_BREAKER_BACKOFF_SECS"
RETRY_BUDGET_ENV = "PTPU_FLEET_RETRY_BUDGET"
RETRY_REFILL_ENV = "PTPU_FLEET_RETRY_REFILL_PER_S"

_BACKOFF_CAP_MULT = 16               # consecutive-trip backoff ceiling


def default_breaker_failures() -> int:
    return int(os.environ.get(BREAKER_FAILURES_ENV, "5"))


def default_breaker_window_secs() -> float:
    return float(os.environ.get(BREAKER_WINDOW_SECS_ENV, "10"))


def default_breaker_backoff_secs() -> float:
    return float(os.environ.get(BREAKER_BACKOFF_SECS_ENV, "2"))


def default_retry_budget() -> int:
    return int(os.environ.get(RETRY_BUDGET_ENV, "64"))


def default_retry_refill_per_s() -> float:
    return float(os.environ.get(RETRY_REFILL_ENV, "8"))


class CircuitBreaker:
    """Failure-rate hysteresis for one replica.

    ``record_failure()`` / ``record_success()`` feed it transport
    outcomes; ``allow()`` answers "may I send this replica new work?"
    — and performs the open → half-open transition when the backoff
    has elapsed (granting exactly ONE probe per window).

    ``on_transition(prev, new, breaker)`` — when given — fires on every
    state change; the router uses it to emit ``fleet.breaker`` timeline
    records and flip the ``flapping`` census state.
    """

    def __init__(self, failures: Optional[int] = None,
                 window_secs: Optional[float] = None,
                 backoff_secs: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None):
        self.failures = int(failures if failures is not None
                            else default_breaker_failures())
        self.window_secs = float(window_secs if window_secs is not None
                                 else default_breaker_window_secs())
        self.backoff_secs = float(backoff_secs if backoff_secs is not None
                                  else default_breaker_backoff_secs())
        self.clock = clock
        self.on_transition = on_transition
        self.state = "closed"            # closed | open | half_open
        self.trips = 0                   # lifetime open transitions
        self._consecutive_trips = 0      # resets on a closed recovery
        self._recent: Deque[float] = deque()
        self._opened_at: Optional[float] = None
        self._probe_out = False

    def _transition(self, new: str) -> None:
        prev, self.state = self.state, new
        if prev != new and self.on_transition is not None:
            self.on_transition(prev, new, self)

    def _prune(self, now: float) -> None:
        while self._recent and now - self._recent[0] > self.window_secs:
            self._recent.popleft()

    def current_backoff(self) -> float:
        mult = min(_BACKOFF_CAP_MULT,
                   2 ** max(0, self._consecutive_trips - 1))
        return self.backoff_secs * mult

    # -- outcomes ----------------------------------------------------------
    def record_failure(self) -> None:
        now = float(self.clock())
        if self.state == "half_open":
            # the probe failed: reopen, and back off harder
            self._probe_out = False
            self._consecutive_trips += 1
            self.trips += 1
            self._opened_at = now
            self._recent.clear()
            self._transition("open")
            return
        self._recent.append(now)
        self._prune(now)
        if self.state == "closed" and len(self._recent) >= self.failures:
            self._consecutive_trips += 1
            self.trips += 1
            self._opened_at = now
            self._recent.clear()
            self._transition("open")

    def record_success(self) -> None:
        if self.state == "half_open":
            self._probe_out = False
            self._consecutive_trips = 0
            self._recent.clear()
            self._transition("closed")
        elif self.state == "closed":
            # healthy traffic ages failures out via the window; nothing
            # else to do — hysteresis lives in the trip/backoff path
            self._prune(float(self.clock()))

    # -- gating ------------------------------------------------------------
    def allow(self) -> bool:
        """True when this replica may receive new work right now.  In
        ``open``, flips to ``half_open`` once the backoff elapses and
        grants a single probe; further calls say no until the probe
        resolves."""
        if self.state == "closed":
            return True
        now = float(self.clock())
        if self.state == "open":
            opened = now if self._opened_at is None else self._opened_at
            if now - opened >= self.current_backoff():
                self._probe_out = True
                self._transition("half_open")
                return True
            return False
        # half_open: one probe at a time
        if not self._probe_out:
            self._probe_out = True
            return True
        return False

    def snapshot(self) -> dict:
        return {"state": self.state, "trips": self.trips,
                "recent_failures": len(self._recent),
                "backoff_secs": self.current_backoff()}


class RetryBudget:
    """Process-wide retry token bucket.

    ``try_acquire()`` refills by ``refill_per_s`` × elapsed (capped at
    ``capacity``) and spends one token when available.  ``spent`` /
    ``denied`` make "total retries bounded by the budget" directly
    assertable in drills.
    """

    def __init__(self, capacity: Optional[int] = None,
                 refill_per_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.capacity = float(capacity if capacity is not None
                              else default_retry_budget())
        self.refill_per_s = float(
            refill_per_s if refill_per_s is not None
            else default_retry_refill_per_s())
        self.clock = clock
        self._lock = threading.Lock()
        self._tokens = self.capacity
        self._last = float(clock())
        self.spent = 0
        self.denied = 0

    def _refill(self, now: float) -> None:
        self._tokens = min(self.capacity, self._tokens
                           + (now - self._last) * self.refill_per_s)
        self._last = now

    def try_acquire(self, n: int = 1) -> bool:
        with self._lock:
            self._refill(float(self.clock()))
            if self._tokens >= n:
                self._tokens -= n
                self.spent += n
                return True
            self.denied += n
            return False

    def available(self) -> float:
        with self._lock:
            self._refill(float(self.clock()))
            return self._tokens

    def snapshot(self) -> dict:
        return {"capacity": self.capacity, "available": self.available(),
                "refill_per_s": self.refill_per_s, "spent": self.spent,
                "denied": self.denied}


_budget_lock = threading.Lock()
_global_budget: Optional[RetryBudget] = None


def get_retry_budget() -> RetryBudget:
    """The process-wide bucket every router shares by default — retry
    pressure is a *fleet* property, not a per-router one."""
    global _global_budget
    with _budget_lock:
        if _global_budget is None:
            _global_budget = RetryBudget()
        return _global_budget


def reset_retry_budget() -> None:
    """Drop the process-wide bucket (tests)."""
    global _global_budget
    with _budget_lock:
        _global_budget = None
