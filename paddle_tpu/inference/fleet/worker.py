"""Fleet engine worker: one ServingEngine behind localhost HTTP.

Spawned by :class:`..replica.ReplicaManager` as

    python -m paddle_tpu.inference.fleet.worker \
        --replica-id I [--port P] [--run-dir D] --model '<json spec>'

The model spec is ``{"seed": s, "config": {GPTConfig kwargs},
"engine": {ServingEngine kwargs}}``.  Every worker seeds identically
(``pt.seed(seed)`` before building), so fleet replicas hold identical
weights — the invariant that makes greedy decode token-exact across
replicas and router failover provable against a single-engine
reference.

Once the server is bound the worker prints ONE handshake line

    ptpu-fleet-worker ready replica=<i> port=<p> pid=<pid>

and flushes — with ephemeral ports (``PTPU_FLEET_PORT_BASE=0``) this
is how the manager learns where to dial.  A background thread steps
the engine whenever work is queued; HTTP handlers and the step loop
share one lock, so requests observe step-boundary state.

Endpoints (all JSON): ``POST /submit`` (spill-format record →
``admit_record``), ``GET /poll?rid=&start=``, ``POST /cancel``,
``POST /drain`` (returns ``spilled_records`` inline for migration),
``POST /shutdown``, ``GET /healthz``, ``GET /statusz``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

__all__ = ["build_engine", "serve_worker", "main"]


def build_engine(spec, replica_id: int, run_dir=None):
    """Deterministically build the decoder + engine from a JSON spec."""
    import paddle_tpu as pt
    from ...models import GPTConfig, GPTForCausalLM
    from ..engine import ServingEngine

    pt.seed(int(spec.get("seed", 7)))
    cfg = GPTConfig(**spec.get("config", {}))
    model = GPTForCausalLM(cfg)
    model.eval()
    kw = dict(spec.get("engine", {}))
    return ServingEngine(model, replica_id=replica_id, run_dir=run_dir,
                         **kw)


class _WorkerState:
    def __init__(self, engine):
        self.engine = engine
        self.lock = threading.Lock()
        self.shutdown = threading.Event()

    def step_loop(self):
        while not self.shutdown.is_set():
            stepped = False
            with self.lock:
                if (self.engine.state == "serving"
                        and self.engine.has_work()):
                    self.engine.step()
                    stepped = True
            if not stepped:
                time.sleep(0.002)


def _make_handler(state: _WorkerState):
    engine = state.engine

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):   # no per-request stderr spam
            pass

        def _reply(self, code: int, payload) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self):
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw.decode() or "{}")

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/healthz":
                with state.lock:
                    st = engine.state
                    shed = engine.should_shed()
                if st != "serving":
                    return self._reply(503, {"state": st})
                if shed:
                    return self._reply(503, {"state": "load-shed"})
                return self._reply(200, {"state": "serving"})
            if url.path == "/statusz":
                with state.lock:
                    return self._reply(200, {"serving": engine.stats()})
            if url.path == "/poll":
                q = parse_qs(url.query)
                rid = q.get("rid", [""])[0]
                start = int(q.get("start", ["0"])[0])
                with state.lock:
                    seq = engine.sched.finished.get(rid)
                    if seq is None:
                        live = (list(engine.sched.running)
                                + list(engine.sched.waiting))
                        seq = next((s for s in live
                                    if s.request_id == rid), None)
                    if seq is None:
                        return self._reply(
                            404, {"error": f"unknown request {rid!r}"})
                    return self._reply(
                        200, {"tokens": list(seq.output[start:]),
                              "finished": seq.finish_reason is not None,
                              "reason": seq.finish_reason})
            return self._reply(404, {"error": f"no route {url.path}"})

        def do_POST(self):
            url = urlparse(self.path)
            try:
                body = self._body()
            except Exception as e:
                return self._reply(400, {"error": f"bad JSON: {e}"})
            if url.path == "/submit":
                try:
                    with state.lock:
                        rid = engine.admit_record(body["record"])
                    return self._reply(200, {"request_id": rid})
                except Exception as e:
                    return self._reply(503, {"error": str(e)})
            if url.path == "/cancel":
                with state.lock:
                    ok = engine.cancel(body.get("request_id", ""))
                return self._reply(200, {"cancelled": ok})
            if url.path == "/drain":
                try:
                    with state.lock:
                        report = engine.drain(timeout=body.get("timeout"))
                    return self._reply(
                        200, {"finished": report["finished"],
                              "spilled_records": report["spilled_records"],
                              "timed_out": report["timed_out"]})
                except Exception as e:
                    return self._reply(500, {"error": str(e)})
            if url.path == "/shutdown":
                with state.lock:
                    if engine.state == "serving":
                        engine.stop()
                state.shutdown.set()
                threading.Thread(target=self.server.shutdown,
                                 daemon=True).start()
                return self._reply(200, {"stopped": True})
            return self._reply(404, {"error": f"no route {url.path}"})

    return Handler


def serve_worker(engine, replica_id: int, port: int = 0,
                 host: str = "127.0.0.1",
                 handshake_stream=None) -> None:
    """Run the worker loop until ``/shutdown`` (blocking)."""
    state = _WorkerState(engine)
    httpd = ThreadingHTTPServer((host, port), _make_handler(state))
    bound = httpd.server_address[1]
    stream = handshake_stream or sys.stdout
    print(f"ptpu-fleet-worker ready replica={replica_id} "  # noqa: print — the spawn handshake IS the console contract
          f"port={bound} pid={os.getpid()}", file=stream, flush=True)
    stepper = threading.Thread(target=state.step_loop,
                               name=f"fleet-step-{replica_id}",
                               daemon=True)
    stepper.start()
    try:
        httpd.serve_forever(poll_interval=0.05)
    finally:
        state.shutdown.set()
        stepper.join(timeout=5)
        httpd.server_close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replica-id", type=int, required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--run-dir", default=None)
    ap.add_argument("--model", required=True,
                    help="JSON model spec (seed/config/engine kwargs)")
    args = ap.parse_args(argv)
    spec = json.loads(args.model)
    run_dir = args.run_dir
    if run_dir is None:
        # drain() must always have somewhere durable to spill — a
        # worker without an operator-chosen run_dir gets a private one
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="ptpu-fleet-worker-")
    mdir = os.environ.get("PTPU_METRICS_DIR")
    if mdir:
        # request tracing (ISSUE 18): write this worker's stream under
        # its own id (router owns worker-0) so the per-replica JSONL
        # files merge without colliding, and flush every record so the
        # SIGKILL victim's spans survive for the trace assembler
        from ...observability.registry import get_registry
        from ...observability.sinks import MetricsWriter
        reg = get_registry()
        for sink in list(reg.sinks):
            if isinstance(sink, MetricsWriter):
                reg.remove_sink(sink)
        reg.add_sink(MetricsWriter(mdir, worker_id=args.replica_id + 1,
                                   flush_every=1))
    engine = build_engine(spec, args.replica_id, run_dir=run_dir)
    serve_worker(engine, args.replica_id, port=args.port)
    return 0


if __name__ == "__main__":
    sys.exit(main())
