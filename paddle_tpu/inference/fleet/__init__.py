"""Fault-tolerant multi-replica serving fleet (ISSUE 16).

The layer above the single-host ServingEngine: a
:class:`ReplicaManager` spawns/monitors N engine worker subprocesses
(:mod:`.worker`, localhost HTTP, states starting/healthy/draining/
dead), and a :class:`Router` dispatches client streams queue-aware
least-loaded with session affinity, fleet-level admission control,
bounded retry-with-backoff, and **token-exact failover**: the router
journals every stream's prompt + accepted tokens, so a SIGKILLed
replica's survivors re-enter a healthy engine through the
recompute-prefill path and finish with exactly the tokens an
uninterrupted run would have produced.  ``rolling_upgrade()`` drains
one replica at a time with zero client-visible drops.

See docs/ARCHITECTURE.md "Serving fleet" for the state machine,
failover sequence, and the ``PTPU_FLEET_*`` knob table.
"""
from .replica import (HEARTBEAT_SECS_ENV, PORT_BASE_ENV, REPLICAS_ENV,
                      HttpReplica, LocalReplica, ReplicaManager,
                      default_heartbeat_secs, default_port_base,
                      default_replicas)
from .router import (RETRY_BACKOFF_MS_ENV, RETRY_MAX_ENV,
                     SHED_QUEUE_DEPTH_ENV, DispatchExhausted,
                     FleetOverloaded, Router, StreamJournal,
                     default_retry_backoff_ms, default_retry_max,
                     default_shed_queue_depth)

__all__ = [
    "LocalReplica", "HttpReplica", "ReplicaManager", "Router",
    "StreamJournal", "FleetOverloaded", "DispatchExhausted",
    "REPLICAS_ENV", "PORT_BASE_ENV", "HEARTBEAT_SECS_ENV",
    "RETRY_MAX_ENV", "RETRY_BACKOFF_MS_ENV", "SHED_QUEUE_DEPTH_ENV",
    "default_replicas", "default_port_base", "default_heartbeat_secs",
    "default_retry_max", "default_retry_backoff_ms",
    "default_shed_queue_depth",
]
