"""Fault-tolerant multi-replica serving fleet (ISSUE 16 + 17).

The layer above the single-host ServingEngine: a
:class:`ReplicaManager` spawns/monitors N engine worker subprocesses
(:mod:`.worker`, localhost HTTP, states starting/healthy/flapping/
draining/dead/retired), and a :class:`Router` dispatches client
streams queue-aware least-loaded with session affinity, fleet-level
admission control, bounded retry-with-backoff, and **token-exact
failover**: the router journals every stream's prompt + accepted
tokens, so a SIGKILLed replica's survivors re-enter a healthy engine
through the recompute-prefill path and finish with exactly the tokens
an uninterrupted run would have produced.  ``rolling_upgrade()``
drains one replica at a time with zero client-visible drops.

ISSUE 17 makes the fleet self-healing and self-sizing:

- :mod:`.journal` — the router's crash-safe write-ahead log;
  ``Router(recover=run_dir)`` rebuilds every in-flight stream from
  ``<run_dir>/fleet/journal/`` alone, token-exact after a router
  SIGKILL with zero replica restarts.
- :mod:`.health` — per-replica :class:`CircuitBreaker` (the
  ``flapping`` census state) and the process-wide :class:`RetryBudget`
  that degrades retry storms to load-shed.
- :mod:`.autoscaler` — :class:`FleetAutoscaler`, an SLO burn-rate loop
  driving ``ReplicaManager.spawn`` / ``retire`` between
  ``PTPU_FLEET_MIN`` and ``PTPU_FLEET_MAX``.

See docs/ARCHITECTURE.md "Serving fleet" for the state machines,
failover/recovery sequences, and the ``PTPU_FLEET_*`` knob table.
"""
from .autoscaler import (MAX_ENV, MIN_ENV, SCALE_COOLDOWN_SECS_ENV,
                         SCALE_WINDOW_SECS_ENV, FleetAutoscaler,
                         ServingSLO, default_fleet_max,
                         default_fleet_min, default_scale_cooldown_secs,
                         default_scale_window_secs)
from .health import (BREAKER_BACKOFF_SECS_ENV, BREAKER_FAILURES_ENV,
                     BREAKER_WINDOW_SECS_ENV, RETRY_BUDGET_ENV,
                     RETRY_REFILL_ENV, CircuitBreaker, RetryBudget,
                     default_breaker_backoff_secs,
                     default_breaker_failures,
                     default_breaker_window_secs, default_retry_budget,
                     default_retry_refill_per_s, get_retry_budget,
                     reset_retry_budget)
from .journal import JOURNAL_KEEP_ENV, JournalStore, default_journal_keep
from .replica import (DRAIN_SLACK_SECS_ENV, HEARTBEAT_SECS_ENV,
                      PORT_BASE_ENV, REPLICAS_ENV, HttpReplica,
                      LocalReplica, LocalReplicaManager, ReplicaManager,
                      default_drain_slack_secs, default_heartbeat_secs,
                      default_port_base, default_replicas)
from .router import (RETRY_BACKOFF_MS_ENV, RETRY_MAX_ENV,
                     SHED_QUEUE_DEPTH_ENV, DispatchExhausted,
                     FleetOverloaded, Router, StreamJournal,
                     default_retry_backoff_ms, default_retry_max,
                     default_shed_queue_depth)

__all__ = [
    "LocalReplica", "HttpReplica", "ReplicaManager",
    "LocalReplicaManager", "Router", "StreamJournal", "FleetOverloaded",
    "DispatchExhausted", "JournalStore", "CircuitBreaker", "RetryBudget",
    "get_retry_budget", "reset_retry_budget", "FleetAutoscaler",
    "ServingSLO",
    "REPLICAS_ENV", "PORT_BASE_ENV", "HEARTBEAT_SECS_ENV",
    "DRAIN_SLACK_SECS_ENV", "RETRY_MAX_ENV", "RETRY_BACKOFF_MS_ENV",
    "SHED_QUEUE_DEPTH_ENV", "JOURNAL_KEEP_ENV", "BREAKER_FAILURES_ENV",
    "BREAKER_WINDOW_SECS_ENV", "BREAKER_BACKOFF_SECS_ENV",
    "RETRY_BUDGET_ENV", "RETRY_REFILL_ENV", "MIN_ENV", "MAX_ENV",
    "SCALE_WINDOW_SECS_ENV", "SCALE_COOLDOWN_SECS_ENV",
    "default_replicas", "default_port_base", "default_heartbeat_secs",
    "default_drain_slack_secs", "default_retry_max",
    "default_retry_backoff_ms", "default_shed_queue_depth",
    "default_journal_keep", "default_breaker_failures",
    "default_breaker_window_secs", "default_breaker_backoff_secs",
    "default_retry_budget", "default_retry_refill_per_s",
    "default_fleet_min", "default_fleet_max",
    "default_scale_window_secs", "default_scale_cooldown_secs",
]
