"""Replica clients + the ReplicaManager (ISSUE 16).

A *replica* is one ServingEngine the fleet router can dispatch to.  Two
client shapes speak the same duck-typed protocol:

- :class:`LocalReplica` — wraps an in-process engine; ``pump()`` steps
  it.  This is the deterministic form the router unit tests and the
  ``serve_fleet`` bench scenario use.
- :class:`HttpReplica` — speaks localhost HTTP to a
  :mod:`.worker` subprocess (``/submit`` ``/poll`` ``/drain``
  ``/healthz`` ``/statusz``); ``pump()`` is a no-op because the worker
  steps itself.

The protocol (all a router needs):

    submit(record)          admit one spill-format request record
    poll(rid, start)        {"tokens": output[start:], "finished", "reason"}
    serving_stats()         the /statusz serving section (load score)
    healthz()               (http_code, state_string)
    alive()                 False once the process/engine is gone
    pump()                  advance work (in-process engines only)
    drain(timeout)          {"finished", "spilled_records": [...]}

:class:`ReplicaManager` spawns/monitors N worker subprocesses: states
``starting`` (spawned, /healthz not yet 200) → ``healthy`` (200 +
fresh heartbeat) → ``draining`` (503 draining) → ``dead`` (process
exited or heartbeat older than ``PTPU_FLEET_HEARTBEAT_SECS``), mirrors
the census into ``fleet.replicas[state=...]`` gauges, and can
``restart()`` a slot — the rolling-upgrade primitive.  ISSUE 17 adds
two overlay states: ``flapping`` (alive but its router-side circuit
breaker is open — see :mod:`.health`) and ``retired`` (scaled down by
the :mod:`.autoscaler`; the slot stays in the list so replica ids
stay stable), plus :meth:`spawn` / :meth:`retire` — the autoscaler's
actuators.  :class:`LocalReplicaManager` is the in-process mirror of
that protocol for deterministic drills.

Env knobs: ``PTPU_FLEET_REPLICAS``, ``PTPU_FLEET_PORT_BASE``,
``PTPU_FLEET_HEARTBEAT_SECS``, ``PTPU_FLEET_DRAIN_SLACK_SECS`` (see
docs/ARCHITECTURE.md "Serving fleet").
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ...framework.errors import enforce
from ...framework.log import vlog

__all__ = ["REPLICAS_ENV", "PORT_BASE_ENV", "HEARTBEAT_SECS_ENV",
           "DRAIN_SLACK_SECS_ENV", "default_replicas",
           "default_port_base", "default_heartbeat_secs",
           "default_drain_slack_secs", "LocalReplica", "HttpReplica",
           "ReplicaManager", "LocalReplicaManager"]

REPLICAS_ENV = "PTPU_FLEET_REPLICAS"
PORT_BASE_ENV = "PTPU_FLEET_PORT_BASE"
HEARTBEAT_SECS_ENV = "PTPU_FLEET_HEARTBEAT_SECS"
DRAIN_SLACK_SECS_ENV = "PTPU_FLEET_DRAIN_SLACK_SECS"


def default_replicas() -> int:
    return int(os.environ.get(REPLICAS_ENV, "2"))


def default_port_base() -> int:
    """0 = every worker binds an ephemeral port and reports it on the
    spawn handshake line — the CI-safe default (no port collisions)."""
    return int(os.environ.get(PORT_BASE_ENV, "0"))


def default_heartbeat_secs() -> float:
    return float(os.environ.get(HEARTBEAT_SECS_ENV, "10"))


def default_drain_slack_secs() -> float:
    """HTTP-read margin over the engine-side drain budget (the worker
    finishes/spills *inside* the /drain call)."""
    return float(os.environ.get(DRAIN_SLACK_SECS_ENV, "30"))


class LocalReplica:
    """In-process replica: a ServingEngine behind the replica protocol.

    The router's unit tests and the bench scenario run whole fleets of
    these in one process — same dispatch/journal/failover code paths as
    the subprocess form, no IPC nondeterminism."""

    def __init__(self, engine, replica_id: int = 0):
        self.engine = engine
        self.replica_id = int(replica_id)
        if engine.replica_id is None:
            engine.replica_id = self.replica_id

    def _check_up(self) -> None:
        # a dead in-process engine fails like a dead worker: the
        # transport error is the router's failover signal
        if self.engine.state == "stopped":
            raise ConnectionError(
                f"replica {self.replica_id}: engine stopped")

    def submit(self, record: Dict[str, Any]) -> None:
        self._check_up()
        self.engine.admit_record(record)

    def poll(self, request_id: str, start: int = 0) -> Dict[str, Any]:
        self._check_up()
        eng = self.engine
        seq = eng.sched.finished.get(request_id)
        if seq is None:
            for s in list(eng.sched.running) + list(eng.sched.waiting):
                if s.request_id == request_id:
                    seq = s
                    break
        enforce(seq is not None,
                f"replica {self.replica_id}: unknown request "
                f"{request_id!r}")
        finished = seq.finish_reason is not None
        return {"tokens": list(seq.output[start:]),
                "finished": finished,
                "reason": seq.finish_reason}

    def pump(self) -> bool:
        """One engine step when work is queued; True when it stepped."""
        if self.engine.state == "serving" and self.engine.has_work():
            self.engine.step()
            return True
        return False

    def serving_stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def healthz(self):
        if self.engine.state != "serving":
            return 503, self.engine.state
        if self.engine.should_shed():
            return 503, \
                f"load-shed:queue_depth={self.engine.sched.queue_depth}"
        return 200, "serving"

    def alive(self) -> bool:
        return self.engine.state != "stopped"

    def drain(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        report = self.engine.drain(timeout=timeout)
        return {"finished": report["finished"],
                "spilled_records": report["spilled_records"]}

    def stop(self) -> None:
        self.engine.stop()


class HttpReplica:
    """Localhost-HTTP client for one :mod:`.worker` subprocess.

    Transport errors surface as ``ConnectionError`` from every call —
    the router's retry/failover signal.  ``process`` (when the manager
    spawned the worker) lets ``alive()`` notice a SIGKILLed worker
    immediately instead of waiting out a connect timeout."""

    def __init__(self, replica_id: int, port: int,
                 host: str = "127.0.0.1", timeout: float = 5.0,
                 process: Optional[subprocess.Popen] = None):
        self.replica_id = int(replica_id)
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self.process = process

    def _url(self, path: str) -> str:
        return f"http://{self.host}:{self.port}{path}"

    def _call(self, path: str, payload: Optional[Dict] = None,
              timeout: Optional[float] = None) -> Dict[str, Any]:
        data = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(
            self._url(path), data=data,
            headers={"Content-Type": "application/json"} if data else {})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            raise ConnectionError(
                f"replica {self.replica_id} {path}: HTTP {e.code} "
                f"{body[:200]}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ConnectionError(
                f"replica {self.replica_id} {path}: {e}") from e

    def submit(self, record: Dict[str, Any]) -> None:
        self._call("/submit", {"record": record})

    def poll(self, request_id: str, start: int = 0) -> Dict[str, Any]:
        return self._call(f"/poll?rid={request_id}&start={int(start)}")

    def pump(self) -> bool:
        return False                  # the worker steps itself

    def serving_stats(self) -> Dict[str, Any]:
        return self._call("/statusz").get("serving") or {}

    def healthz(self):
        try:
            out = self._call("/healthz")
            return 200, out.get("state", "serving")
        except ConnectionError as e:
            cause = e.__cause__
            if isinstance(cause, urllib.error.HTTPError):
                try:
                    return cause.code, json.loads(
                        str(e).split(" ", 3)[-1]).get("state", "unknown")
                except Exception:  # noqa: swallow — health probe must answer
                    return cause.code, "unhealthy"
            raise

    def alive(self) -> bool:
        if self.process is not None and self.process.poll() is not None:
            return False
        try:
            self.healthz()
            return True
        except ConnectionError:
            return False

    def drain(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        http_timeout = (self.timeout if timeout is None
                        else float(timeout) + default_drain_slack_secs())
        return self._call("/drain", {"timeout": timeout},
                          timeout=http_timeout)

    def stop(self) -> None:
        try:
            self._call("/shutdown", {})
        except ConnectionError:
            pass                      # already gone — that is the goal


class ReplicaManager:
    """Spawn + monitor N engine worker subprocesses.

    ``model_spec`` is the JSON-able dict :mod:`.worker` rebuilds the
    decoder from (config kwargs + seed) — every replica seeds
    identically, so greedy decode is token-exact across the fleet and
    failover is provable against a single-engine reference.

    State machine per slot (mirrored into ``fleet.replicas[state=...]``
    gauges by :meth:`poll_states`):

        starting --/healthz 200--> healthy --503 draining--> draining
            |                        |                          |
            +---- process exit / stale heartbeat ----> dead <---+
    """

    def __init__(self, model_spec: Dict[str, Any], *,
                 replicas: Optional[int] = None,
                 port_base: Optional[int] = None,
                 run_dir: Optional[str] = None,
                 registry=None,
                 heartbeat_secs: Optional[float] = None,
                 env: Optional[Dict[str, str]] = None,
                 spawn_timeout: float = 120.0):
        self.model_spec = dict(model_spec)
        self.num_replicas = int(replicas if replicas is not None
                                else default_replicas())
        enforce(self.num_replicas >= 1, "fleet needs >= 1 replica")
        self.port_base = int(port_base if port_base is not None
                             else default_port_base())
        self.run_dir = run_dir
        self._registry = registry
        self.heartbeat_secs = float(
            heartbeat_secs if heartbeat_secs is not None
            else default_heartbeat_secs())
        self.env = dict(env or {})
        self.spawn_timeout = float(spawn_timeout)
        self.replicas: List[HttpReplica] = []
        self.states: Dict[int, str] = {}
        self._last_beat: Dict[int, float] = {}
        self.restarts = 0
        self._flapping: set = set()    # router-marked (breaker open)
        self._retired: set = set()     # autoscaler-marked (slot stable)

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ...observability.registry import get_registry
        return get_registry()

    # -- spawning ----------------------------------------------------------
    def _spawn(self, idx: int) -> HttpReplica:
        port = self.port_base + idx if self.port_base > 0 else 0
        cmd = [sys.executable, "-m", "paddle_tpu.inference.fleet.worker",
               "--replica-id", str(idx), "--port", str(port),
               "--model", json.dumps(self.model_spec)]
        if self.run_dir:
            cmd += ["--run-dir", self.run_dir]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self.env)
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True,
                                env=env)
        # handshake: the worker prints ONE line once its server is bound
        # (ephemeral ports make this the only way to learn the port)
        deadline = time.monotonic() + self.spawn_timeout
        line = ""
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("ptpu-fleet-worker"):
                break
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {idx} died before handshake "
                    f"(rc={proc.returncode})")
        enforce(line.startswith("ptpu-fleet-worker"),
                f"fleet worker {idx}: no handshake within "
                f"{self.spawn_timeout}s")
        fields = dict(kv.split("=", 1) for kv in line.split()
                      if "=" in kv)
        replica = HttpReplica(idx, int(fields["port"]), process=proc)
        self.states[idx] = "starting"
        self._last_beat[idx] = time.monotonic()
        vlog(0, "fleet: worker %d up on port %d (pid %s)", idx,
             replica.port, fields.get("pid"))
        return replica

    def start(self) -> List[HttpReplica]:
        enforce(not self.replicas, "fleet already started")
        self.replicas = [self._spawn(i)
                         for i in range(self.num_replicas)]
        self.poll_states()
        return self.replicas

    def restart(self, idx: int) -> HttpReplica:
        """Replace slot ``idx`` with a fresh worker (rolling upgrade /
        post-failover respawn).  The old process, if any, is killed."""
        old = self.replicas[idx]
        if old.process is not None and old.process.poll() is None:
            old.process.kill()
            old.process.wait(timeout=10)
        self.replicas[idx] = self._spawn(idx)
        self.restarts += 1
        self._flapping.discard(idx)   # fresh worker, fresh record
        self._retired.discard(idx)
        self._reg().counter("fleet.restarts").inc()
        self.poll_states()
        return self.replicas[idx]

    # -- autoscaler actuators (ISSUE 17) -----------------------------------
    def spawn(self) -> HttpReplica:
        """Scale up: add one fresh worker slot at the end of the list
        (replica ids are stable — slots are never renumbered).  A
        retired slot is reused before the list grows."""
        for idx in sorted(self._retired):
            return self.restart(idx)
        idx = len(self.replicas)
        self.replicas.append(self._spawn(idx))
        self.num_replicas = len(self.replicas)
        self.poll_states()
        return self.replicas[idx]

    def retire(self, idx: int) -> None:
        """Scale down: stop slot ``idx`` and mark it ``retired`` *in
        place* — the list keeps its shape so every other replica id
        (and every router journal naming one) stays valid.  Drain
        first (``router.drain_replica``) — retire only stops."""
        replica = self.replicas[idx]
        replica.stop()
        proc = replica.process
        if proc is not None:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        self._retired.add(idx)
        self._flapping.discard(idx)
        self.states[idx] = "retired"
        self._reg().emit("fleet.replica_state", replica=idx,
                         prev="draining", state="retired")
        self.update_gauges()

    # -- flap overlay (ISSUE 17) -------------------------------------------
    def set_flapping(self, idx: int, flapping: bool) -> None:
        """Router-side breaker verdict for slot ``idx``; reflected as
        the ``flapping`` census state while the probe says healthy."""
        if flapping:
            self._flapping.add(idx)
        else:
            self._flapping.discard(idx)
        self.poll_states()

    # -- monitoring --------------------------------------------------------
    def _probe(self, idx: int, replica: HttpReplica) -> str:
        if idx in self._retired:
            return "retired"
        proc = replica.process
        if proc is not None and proc.poll() is not None:
            return "dead"
        try:
            code, state = replica.healthz()
            self._last_beat[idx] = time.monotonic()
        except ConnectionError:
            age = time.monotonic() - self._last_beat.get(idx, 0.0)
            if age > self.heartbeat_secs:
                return "dead"
            return self.states.get(idx, "starting")
        if code == 200:
            return "flapping" if idx in self._flapping else "healthy"
        if str(state).startswith(("draining", "stopped")):
            return "draining"
        if str(state).startswith("load-shed"):
            return "healthy"          # shedding, but alive and serving
        return self.states.get(idx, "starting")

    def poll_states(self) -> Dict[int, str]:
        """One health sweep: probe every slot, update the state map and
        the ``fleet.replicas[state=...]`` gauges; returns the map."""
        for idx, replica in enumerate(self.replicas):
            new = self._probe(idx, replica)
            old = self.states.get(idx)
            if new != old:
                self._reg().emit("fleet.replica_state", replica=idx,
                                 prev=old, state=new)
                vlog(1, "fleet: replica %d %s -> %s", idx, old, new)
            self.states[idx] = new
        self.update_gauges()
        return dict(self.states)

    def update_gauges(self) -> None:
        reg = self._reg()
        counts = {s: 0 for s in ("starting", "healthy", "flapping",
                                 "draining", "dead", "retired")}
        for s in self.states.values():
            counts[s] = counts.get(s, 0) + 1
        for state, n in counts.items():
            reg.gauge(f"fleet.replicas[state={state}]").set(float(n))

    def kill(self, idx: int, sig=None) -> None:
        """Hard-kill slot ``idx`` (drill seam — see
        ``testing/faults.kill_replica``)."""
        import signal as _signal
        proc = self.replicas[idx].process
        enforce(proc is not None, f"replica {idx} has no process handle")
        os.kill(proc.pid, sig if sig is not None else _signal.SIGKILL)
        proc.wait(timeout=10)
        self.poll_states()

    def stop(self) -> None:
        for replica in self.replicas:
            replica.stop()
        for replica in self.replicas:
            proc = replica.process
            if proc is None:
                continue
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        for idx in range(len(self.replicas)):
            self.states[idx] = "dead"
        self.update_gauges()


class LocalReplicaManager:
    """In-process fleet manager: :class:`LocalReplica` slots behind the
    same census / spawn / retire / flap protocol as
    :class:`ReplicaManager`, so routers, drills and the autoscaler run
    deterministically in one process (no subprocess nondeterminism).

    ``engine_factory(replica_id)`` builds one ServingEngine per slot —
    the caller seeds them identically when token-exactness matters."""

    def __init__(self, engine_factory, *, replicas: int = 2,
                 registry=None):
        enforce(replicas >= 1, "fleet needs >= 1 replica")
        self.engine_factory = engine_factory
        self._registry = registry
        self.replicas: List[LocalReplica] = [
            LocalReplica(engine_factory(i), replica_id=i)
            for i in range(replicas)]
        self.num_replicas = len(self.replicas)
        self.states: Dict[int, str] = {}
        self.restarts = 0
        self._flapping: set = set()
        self._retired: set = set()
        self.poll_states()

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ...observability.registry import get_registry
        return get_registry()

    def _probe(self, idx: int, replica: LocalReplica) -> str:
        if idx in self._retired:
            return "retired"
        if not replica.alive():
            return "dead"
        code, state = replica.healthz()
        if code == 200:
            return "flapping" if idx in self._flapping else "healthy"
        if str(state).startswith(("draining", "stopped")):
            return "draining"
        return "healthy"

    def poll_states(self) -> Dict[int, str]:
        for idx, replica in enumerate(self.replicas):
            new = self._probe(idx, replica)
            old = self.states.get(idx)
            if new != old:
                self._reg().emit("fleet.replica_state", replica=idx,
                                 prev=old, state=new)
            self.states[idx] = new
        self.update_gauges()
        return dict(self.states)

    update_gauges = ReplicaManager.update_gauges
    set_flapping = ReplicaManager.set_flapping

    def restart(self, idx: int) -> LocalReplica:
        old = self.replicas[idx]
        if old.alive():
            old.stop()
        self.replicas[idx] = LocalReplica(self.engine_factory(idx),
                                          replica_id=idx)
        self.restarts += 1
        self._flapping.discard(idx)
        self._retired.discard(idx)
        self._reg().counter("fleet.restarts").inc()
        self.poll_states()
        return self.replicas[idx]

    def spawn(self) -> LocalReplica:
        for idx in sorted(self._retired):
            return self.restart(idx)
        idx = len(self.replicas)
        self.replicas.append(LocalReplica(self.engine_factory(idx),
                                          replica_id=idx))
        self.num_replicas = len(self.replicas)
        self.poll_states()
        return self.replicas[idx]

    def retire(self, idx: int) -> None:
        replica = self.replicas[idx]
        if replica.alive():
            replica.stop()
        self._retired.add(idx)
        self._flapping.discard(idx)
        self.states[idx] = "retired"
        self._reg().emit("fleet.replica_state", replica=idx,
                         prev="draining", state="retired")
        self.update_gauges()

    def stop(self) -> None:
        for replica in self.replicas:
            if replica.alive():
                replica.stop()
        for idx in range(len(self.replicas)):
            self.states[idx] = "dead"
        self.update_gauges()
