"""Crash-safe stream journal: the router's write-ahead log (ISSUE 17).

PR 16's :class:`..router.StreamJournal` lives in a dict — a router
crash loses every in-flight stream even though each replica would have
survived.  :class:`JournalStore` makes the journal durable: one JSONL
file per stream under ``<run_dir>/fleet/journal/``, every accepted
token batch appended through the fsync'd :mod:`~paddle_tpu.utils.fsio`
seam *before* the in-memory journal advances (write-ahead), so a fresh
``Router(recover=run_dir)`` rebuilds each stream from the directory
alone and completions stay token-exact across a router SIGKILL.

File format (one JSON object per line):

    {"v": 1, "kind": "open", "request_id": ..., "prompt": [...],
     "max_new_tokens": N, "eos_token_id": E, "session": S,
     "trace_id": T}
    {"kind": "disp", "replica": R, "trace_id": T}   # dispatched/failed-over
    {"kind": "tok", "t": [t0, t1, ...]}        # accepted tokens
    {"kind": "fin", "reason": "length"}         # terminal marker

Every line additionally carries ``ts`` (wall clock) and ``mono``
(monotonic) stamps (ISSUE 18) so the trace assembler can align WAL
events with router/worker spans and order them within a file even
across wall-clock steps.

Recovery follows the ``aggregate.StreamTail`` / ledger reader
discipline: only complete lines count — a torn tail (the append the
crash interrupted) is dropped with accounting, never an error.  A
dropped token line merely shrinks the accepted prefix; the replica (or
a recompute re-dispatch) regenerates the same tokens, greedy decode
being deterministic.  A file whose ``open`` header is unreadable is
quarantined to ``*.corrupt`` — the stream is lost to recovery (the
prompt never became durable) but the directory stays parseable.

On completion a stream's file is retired (renamed ``*.done``) and
retired files are GC'd down to the newest ``PTPU_FLEET_JOURNAL_KEEP``
— the bounded-quarantine discipline ``step-N.corrupt`` uses, so a
long-lived router never accumulates evidence without bound.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Sequence

from ...utils import fsio

__all__ = ["JOURNAL_KEEP_ENV", "default_journal_keep", "JournalStore"]

JOURNAL_KEEP_ENV = "PTPU_FLEET_JOURNAL_KEEP"

_SUFFIX = ".jsonl"
_DONE_SUFFIX = ".jsonl.done"
_CORRUPT_SUFFIX = ".jsonl.corrupt"


def default_journal_keep() -> int:
    """Retired journal files kept per directory (newest first)."""
    return int(os.environ.get(JOURNAL_KEEP_ENV, "16"))


def journal_dir(run_dir: str) -> str:
    return os.path.join(run_dir, "fleet", "journal")


class JournalStore:
    """Durable per-stream WAL under ``<run_dir>/fleet/journal/``.

    All writes go through ``fsio.append_bytes`` (fsync'd, fault-
    injectable); :meth:`recover` is torn-tail tolerant.  ``drops``
    accounts for what recovery discarded (mirroring the worker-stream
    readers): ``torn_lines`` and ``corrupt_files``.
    """

    def __init__(self, run_dir: str, keep: Optional[int] = None):
        self.run_dir = run_dir
        self.directory = journal_dir(run_dir)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep if keep is not None
                        else default_journal_keep())
        self.appends = 0
        self.drops: Dict[str, int] = {"torn_lines": 0,
                                      "corrupt_files": 0}

    def _path(self, request_id: str) -> str:
        safe = re.sub(r"[^\w.-]", "_", str(request_id))
        return os.path.join(self.directory, safe + _SUFFIX)

    def _append(self, request_id: str, payload: Dict[str, Any]) -> None:
        # every WAL line is timestamped (ISSUE 18): wall clock for
        # cross-process trace alignment, monotonic for intra-file
        # ordering that survives wall-clock steps
        payload.setdefault("ts", time.time())
        payload.setdefault("mono", time.monotonic())
        fsio.append_bytes(self._path(request_id),
                          (json.dumps(payload) + "\n").encode())
        self.appends += 1

    # -- writing -----------------------------------------------------------
    def open(self, request_id: str, prompt: Sequence[int],
             max_new_tokens: int, eos_token_id: Optional[int],
             session: Optional[str] = None,
             tokens: Sequence[int] = (),
             trace_id: Optional[str] = None) -> None:
        """Durably record a stream's existence (before first dispatch).
        ``tokens`` seeds an already-accepted prefix — the re-journal
        path when recovery itself crashes before finishing."""
        self._append(request_id,
                     {"v": 1, "kind": "open", "request_id": request_id,
                      "prompt": [int(t) for t in prompt],
                      "max_new_tokens": int(max_new_tokens),
                      "eos_token_id": eos_token_id, "session": session,
                      "trace_id": trace_id})
        if tokens:
            self.append_tokens(request_id, tokens)

    def append_tokens(self, request_id: str,
                      tokens: Sequence[int]) -> None:
        """Write-ahead one accepted token batch."""
        self._append(request_id,
                     {"kind": "tok", "t": [int(t) for t in tokens]})

    def retire(self, request_id: str,
               reason: Optional[str] = None) -> None:
        """Mark a stream finished and move its file out of the live
        set; bounded GC runs afterward.  Missing files are fine (the
        stream may predate journaling or have been retired already)."""
        path = self._path(request_id)
        if not os.path.exists(path):
            return
        self._append(request_id, {"kind": "fin", "reason": reason})
        os.replace(path, path[: -len(_SUFFIX)] + _DONE_SUFFIX)  # noqa: fsio — rename of an already-fsync'd file; dir fsync'd below
        fsio.fsync_dir(self.directory)
        self.gc()

    def discard(self, request_id: str) -> None:
        """Drop a stream's journal without the finished marker (the
        admission it recorded was refused)."""
        try:
            os.remove(self._path(request_id))
        except OSError:
            pass

    # -- recovery ----------------------------------------------------------
    def _read_one(self, path: str,
                  quarantine: bool = True) -> Optional[Dict[str, Any]]:
        """Parse one journal file, complete lines only."""
        try:
            raw = fsio.read_bytes(path)
        except OSError:
            return None
        end = raw.rfind(b"\n")
        if end >= 0 and end + 1 < len(raw):
            self.drops["torn_lines"] += 1     # mid-append tail dropped
        lines = raw[: end + 1].decode("utf-8", errors="replace") \
            .splitlines() if end >= 0 else []
        header: Optional[Dict[str, Any]] = None
        tokens: List[int] = []
        finished = False
        reason = None
        replica: Optional[int] = None
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                self.drops["torn_lines"] += 1
                continue
            kind = rec.get("kind")
            if kind == "open" and header is None:
                header = rec
            elif kind == "disp":
                replica = rec.get("replica")
            elif kind == "tok":
                tokens.extend(int(t) for t in rec.get("t", []))
            elif kind == "fin":
                finished = True
                reason = rec.get("reason")
        if header is None:
            # the prompt never became durable — nothing to resume
            self.drops["corrupt_files"] += 1
            if quarantine:
                os.replace(path,  # noqa: fsio — quarantine rename; dir fsync'd below
                           path[: -len(_SUFFIX)] + _CORRUPT_SUFFIX)
                fsio.fsync_dir(self.directory)
            return None
        return {"request_id": header["request_id"],
                "prompt": [int(t) for t in header.get("prompt", [])],
                "max_new_tokens": int(header.get("max_new_tokens", 0)),
                "eos_token_id": header.get("eos_token_id"),
                "session": header.get("session"),
                "tokens": tokens, "finished": finished,
                "reason": reason, "replica": replica,
                "trace_id": header.get("trace_id"),
                "opened_ts": header.get("ts")}

    def recover(self) -> List[Dict[str, Any]]:
        """Every stream's durable state, oldest-first — the input
        ``Router(recover=...)`` rebuilds its journals from.  Live files
        first; retired (``.done``) files ride along as finished streams
        so a client that re-asks the recovered router for a stream that
        completed JUST before the crash still gets its tokens (bounded
        by the ``.done`` GC keep, not forever)."""
        try:
            listing = os.listdir(self.directory)
        except OSError:
            return []
        out: List[Dict[str, Any]] = []
        seen = set()
        for name in sorted(n for n in listing if n.endswith(_SUFFIX)):
            rec = self._read_one(os.path.join(self.directory, name))
            if rec is not None:
                seen.add(rec["request_id"])
                out.append(rec)
        for name in sorted(n for n in listing
                           if n.endswith(_DONE_SUFFIX)):
            rec = self._read_one(os.path.join(self.directory, name),
                                 quarantine=False)
            if rec is not None and rec["request_id"] not in seen:
                rec["finished"] = True   # the rename IS the fin marker
                seen.add(rec["request_id"])
                out.append(rec)
        return out

    # -- hygiene -----------------------------------------------------------
    def gc(self, keep: Optional[int] = None) -> int:
        """Bound retired/corrupt files to the newest ``keep`` of each
        kind regardless of age (the ``step-N.corrupt`` discipline);
        returns how many were removed."""
        keep = self.keep if keep is None else int(keep)
        removed = 0
        for suffix in (_DONE_SUFFIX, _CORRUPT_SUFFIX):
            try:
                done = [n for n in os.listdir(self.directory)
                        if n.endswith(suffix)]
            except OSError:
                return removed
            done.sort(key=lambda n: os.path.getmtime(
                os.path.join(self.directory, n)), reverse=True)
            for name in done[keep:]:
                try:
                    os.remove(os.path.join(self.directory, name))
                    removed += 1
                except OSError:
                    pass
        return removed

    def live_count(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.directory)
                       if n.endswith(_SUFFIX))
        except OSError:
            return 0
