"""ServingEngine (ISSUE 6): the continuous-batching serving loop.

The training side of this codebase drives a model with one jitted step
over a fixed batch; serving traffic is the opposite shape — requests
arrive at random times, with ragged prompts, and leave when *they* are
done.  The engine turns that traffic into fixed-shape device work:

    engine = ServingEngine(model, max_seqs=8, kv_block_size=16)
    rid = engine.submit([1, 5, 9], max_new_tokens=32)
    while engine.step():            # one prefill OR one decode batch
        ...
    out = engine.collect(rid)       # {"tokens": [...], "ttft_ms": ...}

Pieces (all under ``paddle_tpu/inference/``):

- ``kv_cache.PagedKVCache`` — block-pooled KV with per-sequence tables;
- ``scheduler.ContinuousBatchingScheduler`` — admission by block
  budget, newest-first preemption, prefill/decode interleaving;
- ``paged_attention`` — the ragged decode kernel (lax fallback on CPU);
- this module — the jitted step functions, sampling, SLO metrics, and
  the submit/step/collect surface.

Step shapes come from a closed set — decode is always
``(max_seqs, 1)``; prefill is padded to power-of-two buckets — and each
shape's jitted function is wrapped in the PR 4 compile tracker under its
own name (``serve_decode``, ``serve_prefill_b<bucket>``), so a full
serve run compiles **once per bucket** and any retrace is attributable.

SLO telemetry rides the PR 3 registry: gauges ``serve.queue_depth`` /
``serve.running`` / ``serve.waiting`` / ``serve.kv_occupancy``,
histograms ``serve.ttft_ms`` / ``serve.tpot_ms``, counters
``serve.tokens`` / ``serve.requests`` / ``serve.finished`` /
``serve.preemptions``.  ``start_status_server()`` exposes them on the
PR 5 monitor (``/statusz`` serving section; ``/healthz`` goes 503 when
the admission queue exceeds ``PTPU_SHED_QUEUE_DEPTH`` — load shedding).

Token callbacks (``submit(..., on_token=fn)``) are dispatched from a
separate drain thread: a slow consumer (``testing/faults.slow_call``)
delays its own stream, never the batch.  Consumer exceptions are
counted (``serve.callback_errors``) and timelined, never fatal.

The request-lifecycle guard (ISSUE 15) wraps all of the above in the
same robustness treatment the training path earned:

- **deadlines & cancellation** — ``submit(deadline_ms=,
  ttft_deadline_ms=)`` and ``cancel(rid)``; a between-steps reaper
  evicts expired/cancelled sequences with every KV block returned and a
  terminal reason (``deadline`` / ``cancelled``) through ``collect()``
  and the callback path;
- **poisoned-request quarantine** — the jitted step runs inside a fault
  boundary; a step exception (or a nonfinite logits row under
  ``PTPU_SERVE_NAN_GUARD``) bisects the batch, evicts the culprit(s)
  with ``reason="poisoned"`` plus a durable record under
  ``<run_dir>/serve_quarantine/``, and replays the step so every other
  request completes token-exact (decode rows are independent);
- **supervision + graceful drain** — ``step()`` arms the PR 2 watchdog
  (a hung step gets a stack dump; the engine rebuilds its jitted fns
  and re-admits the running set via recompute-prefill), and
  ``drain(timeout=)`` stops admission (``/healthz`` → 503 ``draining``),
  finishes what it can, spills the rest to a JSON file a fresh engine
  ``resume()``s from, then stops the callback thread.

Durable artifacts are namespaced per replica (ISSUE 16): quarantine
records land under ``<run_dir>/serve/replica-<i>/quarantine/`` and the
drain spill at ``<run_dir>/serve/replica-<i>/spill.json`` (``<i>`` is
``replica_id``, 0 when unset), so N engines sharing one run_dir — the
fleet layout — never collide.  ``resume()`` without an explicit path
reads the namespaced location and falls back to the legacy
``<run_dir>/serve_spill.json``.

Env knobs: ``PTPU_MAX_SEQS``, ``PTPU_KV_BLOCK_SIZE``,
``PTPU_SHED_QUEUE_DEPTH``, ``PTPU_SERVE_NAN_GUARD``,
``PTPU_SERVE_DEADLINE_MS``, ``PTPU_SERVE_DRAIN_SECS``.  Single-host by
design: the page scatter and the Pallas kernel are opaque to GSPMD (the
engine enforces no mesh).
"""
from __future__ import annotations

import itertools
import json
import os
import queue
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.errors import enforce
from ..observability import requesttrace
from ..observability.compilation import track_jit
from ..supervisor.watchdog import StepTimeout, Watchdog, guarded
from ..utils import fsio
from .kv_cache import PagedKVCache, default_kv_block_size
from .scheduler import (ContinuousBatchingScheduler, SequenceState,
                        StepPlan)

__all__ = ["MAX_SEQS_ENV", "SHED_QUEUE_DEPTH_ENV", "NAN_GUARD_ENV",
           "DEADLINE_MS_ENV", "DRAIN_SECS_ENV", "default_max_seqs",
           "default_shed_queue_depth", "default_nan_guard",
           "default_deadline_ms", "default_drain_secs", "CollectTimeout",
           "ServingEngine"]

MAX_SEQS_ENV = "PTPU_MAX_SEQS"
SHED_QUEUE_DEPTH_ENV = "PTPU_SHED_QUEUE_DEPTH"
NAN_GUARD_ENV = "PTPU_SERVE_NAN_GUARD"
DEADLINE_MS_ENV = "PTPU_SERVE_DEADLINE_MS"
DRAIN_SECS_ENV = "PTPU_SERVE_DRAIN_SECS"

_PAD_SEQ = "__pad__"          # never a real request id
_CB_STOP = object()           # callback-thread shutdown sentinel

# recompute cause → trace-span component (ISSUE 18): the re-prefill (and
# the re-queue wait before it) is attributed to whatever evicted the KV
_RESUME_COMPONENT = {"preempt": "preempt_recompute",
                     "failover": "failover_recompute",
                     "migration": "migration_recompute"}


def _pctl(values, p: float) -> Optional[float]:
    """Nearest-rank percentile over a small sample; None when empty."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(len(ordered) * p / 100.0))
    return float(ordered[idx])


def default_max_seqs() -> int:
    return int(os.environ.get(MAX_SEQS_ENV, "8"))


def default_shed_queue_depth() -> int:
    return int(os.environ.get(SHED_QUEUE_DEPTH_ENV, "64"))


def default_nan_guard() -> bool:
    return os.environ.get(NAN_GUARD_ENV, "0").lower() in ("1", "true",
                                                          "yes", "on")


def default_deadline_ms() -> Optional[float]:
    raw = os.environ.get(DEADLINE_MS_ENV)
    return None if raw is None else float(raw)


def default_drain_secs() -> float:
    return float(os.environ.get(DRAIN_SECS_ENV, "30"))


class CollectTimeout(TimeoutError):
    """``collect(timeout=)`` expired before the request finished; the
    message names the request's current scheduler state."""


class _NonfiniteLogits(RuntimeError):
    """NaN-guard verdict: the named rows came back nonfinite — unlike a
    raised step error this carries the culprits, no bisection needed."""

    def __init__(self, request_ids: List[str]):
        super().__init__(f"nonfinite logits for {request_ids}")
        self.request_ids = list(request_ids)


class ServingEngine:
    """Paged-KV continuous-batching serving engine over a decoder model.

    ``model`` must expose the ``GPTForCausalLM`` serving surface:
    ``.config`` (num_layers / num_heads / head_dim /
    max_position_embeddings / dtype), ``.state_dict()``, ``.eval()`` and
    an ``apply(..., method="serving_step")`` entry point returning
    ``(logits, new_caches)`` over ``PagedLayerCache`` lists.

    ``temperature`` is engine-level (it is baked into the jitted step;
    per-request temperatures would multiply the compile set).
    ``capture_logits=True`` keeps every sampled position's logits row on
    the host per request — the numerics-equality hook for tests.

    Resilience knobs (ISSUE 15): ``nan_guard`` enables the per-step
    nonfinite-logits check (env ``PTPU_SERVE_NAN_GUARD``);
    ``step_timeout`` arms a watchdog around every step (or pass a shared
    ``watchdog``) — set it above the worst-case COLD compile of your
    shape set (the watchdog cannot tell XLA compiling from a wedged
    device), or warm the shapes first; ``run_dir`` is where quarantine
    records and the drain spill file land; ``step_fault`` is the test
    seam the ``testing/faults.poison_request`` injector plugs into — it
    is called as ``fault(engine, kind, request_ids, logits)`` on every
    executed step, bisection probes included.
    """

    def __init__(self, model, *, max_seqs: Optional[int] = None,
                 kv_block_size: Optional[int] = None,
                 num_kv_blocks: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 temperature: float = 0.0,
                 capture_logits: bool = False,
                 shed_queue_depth: Optional[int] = None,
                 registry=None, seed: int = 0,
                 clock: Callable[[], float] = time.time,
                 nan_guard: Optional[bool] = None,
                 step_timeout: Optional[float] = None,
                 watchdog: Optional[Watchdog] = None,
                 run_dir: Optional[str] = None,
                 replica_id: Optional[int] = None,
                 step_fault: Optional[Callable] = None):
        from ..distributed.topology import get_mesh
        enforce(get_mesh() is None,
                "ServingEngine is single-host (the paged path is opaque "
                "to GSPMD) — run it outside fleet meshes")
        cfg = model.config
        self.model = model
        model.eval()
        self._params = model.state_dict()
        self.max_seqs = int(max_seqs if max_seqs is not None
                            else default_max_seqs())
        self.max_model_len = int(max_model_len if max_model_len is not None
                                 else cfg.max_position_embeddings)
        enforce(self.max_model_len <= cfg.max_position_embeddings,
                f"max_model_len {self.max_model_len} exceeds the model's "
                f"{cfg.max_position_embeddings} positions")
        block_size = (default_kv_block_size() if kv_block_size is None
                      else int(kv_block_size))
        blocks_per_seq = -(-self.max_model_len // block_size)
        if num_kv_blocks is None:
            # roomy default: every batch slot can hold a full-length
            # sequence (tests pass tight pools to exercise preemption)
            num_kv_blocks = self.max_seqs * blocks_per_seq
        dtype = (jnp.dtype(cfg.dtype) if cfg.dtype != "float32"
                 else jnp.float32)
        self.cache = PagedKVCache(cfg.num_layers, cfg.num_heads,
                                  cfg.head_dim, num_kv_blocks,
                                  block_size=block_size, dtype=dtype)
        self.sched = ContinuousBatchingScheduler(
            self.cache, self.max_seqs, self.max_model_len, clock=clock)
        self.temperature = float(temperature)
        self.capture_logits = bool(capture_logits)
        self.shed_queue_depth = int(
            shed_queue_depth if shed_queue_depth is not None
            else default_shed_queue_depth())
        self._registry = registry
        self.clock = clock
        self._key = jax.random.PRNGKey(seed)
        self._ids = itertools.count()
        self.steps = 0
        self.status_server = None
        self._decode_tracked = None
        self._prefill_tracked: Dict[int, Callable] = {}
        self._cb_queue: Optional[queue.Queue] = None
        self._cb_thread: Optional[threading.Thread] = None
        # request-lifecycle guard (ISSUE 15)
        self.nan_guard = (default_nan_guard() if nan_guard is None
                          else bool(nan_guard))
        self.run_dir = run_dir
        self.replica_id = None if replica_id is None else int(replica_id)
        self.step_fault = step_fault      # fault seam for the drills
        self.step_timeout = step_timeout
        self._owns_watchdog = watchdog is None and step_timeout is not None
        self._watchdog = (Watchdog(timeout=step_timeout)
                          if self._owns_watchdog else watchdog)
        self._state = "serving"           # serving | draining | stopped
        self._submit_order: List[str] = []
        self.quarantined: Dict[str, Dict[str, Any]] = {}
        self.watchdog_restarts = 0
        self.lifecycle_counts = {"deadline": 0, "cancelled": 0,
                                 "poisoned": 0, "spilled": 0}
        self._cb_dispatched = 0
        self._cb_errors = 0
        self._last_callback_error: Optional[str] = None
        # engine-local latency tails for the stats() "slo" section —
        # per-replica, unlike the (possibly fleet-shared) registry
        # histograms, so the autoscaler sees THIS engine's p99
        self._ttft_ms: Deque[float] = deque(maxlen=512)
        self._tpot_ms: Deque[float] = deque(maxlen=512)
        # request tracing (ISSUE 18): the process tag every span this
        # engine emits carries, and the set of request ids whose trace
        # lifecycle THIS engine owns (direct submissions — fleet
        # streams are owned by the router, which emits the
        # trace.request / trace.request_end records itself)
        self._proc = f"replica-{self.replica_id or 0}"
        self._trace_owned: set = set()
        # padding-waste accounting (ISSUE 19): pow2 prefill buckets and
        # fixed-shape decode both process padded slots; real-vs-padded
        # counts feed serve.padding_frac (and the bench row's roofline
        # padding sink) so padded rows stop inflating tokens/s and MFU
        self._pad_real_tokens = 0
        self._pad_slot_tokens = 0

    # -- plumbing ----------------------------------------------------------
    def serve_dir(self) -> Optional[str]:
        """Per-replica durable-artifact namespace (ISSUE 16):
        ``<run_dir>/serve/replica-<i>`` — quarantine records and the
        drain spill live here so N engines sharing one ``run_dir``
        never collide.  None without a ``run_dir``."""
        if self.run_dir is None:
            return None
        return os.path.join(self.run_dir, "serve",
                            f"replica-{self.replica_id or 0}")

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..observability.registry import get_registry
        return get_registry()

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- jitted step functions --------------------------------------------
    def _build_step_fn(self):
        model, temperature = self.model, self.temperature

        def fn(params, ids, positions, last_index, caches, key):
            logits, new_caches = model.apply(
                params, ids, caches, positions, last_index,
                method="serving_step")
            logits = logits.astype(jnp.float32)
            if temperature <= 0.0:
                nxt = jnp.argmax(logits, axis=-1)
            else:
                nxt = jax.random.categorical(key, logits / temperature,
                                             axis=-1)
            return nxt.astype(jnp.int32), logits, new_caches

        return jax.jit(fn)

    def _decode_fn(self):
        if self._decode_tracked is None:
            self._jit_step = getattr(self, "_jit_step", None) \
                or self._build_step_fn()
            self._decode_tracked = track_jit(
                self._jit_step, name="serve_decode",
                arg_names=("params", "ids", "positions", "last_index",
                           "caches", "key"))
        return self._decode_tracked

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_tracked.get(bucket)
        if fn is None:
            # same underlying jitted callable (jax caches per shape);
            # a per-bucket tracker name makes "one compile per bucket"
            # directly observable and keeps retrace counts at zero
            self._jit_step = getattr(self, "_jit_step", None) \
                or self._build_step_fn()
            fn = track_jit(self._jit_step, name=f"serve_prefill_b{bucket}",
                           arg_names=("params", "ids", "positions",
                                      "last_index", "caches", "key"))
            self._prefill_tracked[bucket] = fn
        return fn

    # -- intake ------------------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int = 32,
               request_id: Optional[str] = None,
               eos_token_id: Optional[int] = None,
               on_token: Optional[Callable] = None,
               deadline_ms: Optional[float] = None,
               ttft_deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None) -> str:
        """Queue one request; returns its id.  ``on_token(request_id,
        token, finished)`` — when given — is invoked from the callback
        drain thread, decoupled from the step loop.

        ``deadline_ms`` bounds the whole request (default from
        ``PTPU_SERVE_DEADLINE_MS``; None = no deadline);
        ``ttft_deadline_ms`` bounds the wait for the FIRST token only —
        both relative to now, enforced by the between-steps reaper with
        terminal ``reason="deadline"``."""
        enforce(self._state == "serving",
                f"engine is {self._state} — not accepting new requests")
        rid = request_id or f"req-{next(self._ids)}"
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        now = float(self.clock())
        if deadline_ms is None:
            deadline_ms = default_deadline_ms()
        seq = SequenceState(request_id=rid, prompt=prompt,
                            max_new_tokens=int(max_new_tokens),
                            eos_token_id=eos_token_id,
                            arrival=now,
                            on_token=on_token,
                            capture_logits=self.capture_logits,
                            deadline=(None if deadline_ms is None
                                      else now + float(deadline_ms) / 1e3),
                            ttft_deadline=(
                                None if ttft_deadline_ms is None
                                else now + float(ttft_deadline_ms) / 1e3))
        # trace context (ISSUE 18): a fleet router passes its minted
        # ``trace_id``; direct submissions mint (and own) their own, so
        # standalone engines get waterfalls too
        if trace_id is None:
            trace_id = requesttrace.mint_trace_id(rid)
            if trace_id is not None:
                self._trace_owned.add(rid)
        seq.trace_id = trace_id
        self.sched.submit(seq)
        self._submit_order.append(rid)
        reg = self._reg()
        reg.counter("serve.requests").inc()
        reg.emit("serve.request", request_id=rid, prompt_len=len(prompt),
                 max_new_tokens=seq.max_new_tokens, trace_id=trace_id)
        if rid in self._trace_owned:
            reg.emit("trace.request", trace_id=trace_id, request_id=rid,
                     t0=now, prompt_len=len(prompt), proc=self._proc)
        self._update_gauges()
        return rid

    def cancel(self, request_id: str) -> bool:
        """Flag a live request for eviction at the next step boundary
        (terminal ``reason="cancelled"``, KV blocks returned).  False
        when the request already finished or was never submitted."""
        for seq in list(self.sched.running) + list(self.sched.waiting):
            if seq.request_id == request_id:
                seq.cancelled = True
                return True
        return False

    def should_shed(self) -> bool:
        """Load-shed signal: the admission queue is past the knob —
        ``/healthz`` turns 503 so the balancer drains elsewhere."""
        return self.sched.queue_depth > self.shed_queue_depth

    # -- the step ----------------------------------------------------------
    def _trace_end(self, seq: SequenceState, reason: str) -> None:
        """Close an engine-owned trace at a terminal transition.  Fleet
        streams are closed by the router (it observes the finish through
        its own poll, which is the client-observed end)."""
        if seq.trace_id is None or seq.request_id not in self._trace_owned:
            return
        self._trace_owned.discard(seq.request_id)
        self._reg().emit("trace.request_end", trace_id=seq.trace_id,
                         request_id=seq.request_id,
                         t1=float(self.clock()), reason=reason,
                         tokens=len(seq.output), proc=self._proc)

    def _evict(self, seq: SequenceState, reason: str) -> Dict[str, Any]:
        """Terminal eviction with reason ``deadline`` / ``cancelled``:
        free blocks, bump counters, emit the timeline record, and deliver
        the terminal event down the callback path."""
        self.sched.evict(seq, reason)
        self.lifecycle_counts[reason] += 1
        reg = self._reg()
        if reason == "cancelled":
            reg.counter("serve.cancelled").inc()
            reg.emit("serve.cancel", request_id=seq.request_id,
                     generated=len(seq.output), trace_id=seq.trace_id)
        else:
            reg.counter("serve.deadline_misses").inc()
            reg.emit("serve.deadline_miss", request_id=seq.request_id,
                     generated=len(seq.output), trace_id=seq.trace_id,
                     miss=("ttft" if seq.first_token_time is None
                           and seq.ttft_deadline is not None else "total"))
        self._trace_end(seq, reason)
        event = {"request_id": seq.request_id, "token": None,
                 "finished": True, "reason": reason}
        if seq.on_token is not None:
            self._dispatch_callback(seq.on_token, event, seq)
        return event

    def _reap(self) -> List[Dict[str, Any]]:
        """Between-steps lifecycle sweep: evict cancelled and
        deadline-expired sequences (running or waiting) before the
        scheduler plans this step — their blocks fund the admissions."""
        now = float(self.clock())
        events = []
        for seq in list(self.sched.running) + list(self.sched.waiting):
            if seq.cancelled:
                events.append(self._evict(seq, "cancelled"))
            elif seq.deadline is not None and now >= seq.deadline:
                events.append(self._evict(seq, "deadline"))
            elif (seq.ttft_deadline is not None
                    and seq.first_token_time is None
                    and now >= seq.ttft_deadline):
                events.append(self._evict(seq, "deadline"))
        return events

    def _step_guard(self):
        if self._watchdog is not None:
            return self._watchdog.armed("serve_step",
                                        timeout=self.step_timeout)
        return guarded("serve_step")

    def step(self) -> List[Dict[str, Any]]:
        """Run one scheduler-chosen unit of work (one prefill or one
        decode batch) inside the lifecycle guard: reap expired/cancelled
        requests first, arm the watchdog around the device work, recover
        from a hung step by rebuilding the jitted fns and re-admitting
        the running set (recompute-prefill).  Returns the token events
        produced; empty when idle AND no queued work remains."""
        events = self._reap()
        try:
            with self._step_guard():
                events += self._step_inner()
        except StepTimeout:
            events += self._recover_from_hang()
        self.steps += 1
        self._update_gauges()
        return events

    def _step_inner(self) -> List[Dict[str, Any]]:
        plan = self.sched.schedule()
        reg = self._reg()
        for victim in plan.preempted:
            reg.counter("serve.preemptions").inc()
            reg.emit("serve.preempt", request_id=victim.request_id,
                     generated=len(victim.output),
                     trace_id=victim.trace_id)
            now = float(self.clock())
            requesttrace.emit_span(reg, victim.trace_id,
                                   victim.request_id, "preempt",
                                   "preempt", now, now, self._proc)
        if plan.kind not in ("prefill", "decode"):
            return []
        # head-of-line stall: residents live on this engine but not in
        # this step's batch wait the full step out.  When the served
        # step is induced work (a recompute prefill), their stall is
        # that cause's cost — the survivor decodes late *because of*
        # the failover, not by scheduler bad luck.
        stall_comp = "stall"
        if plan.kind == "prefill" and plan.seqs:
            why = plan.seqs[0].resume_why
            if why:
                stall_comp = _RESUME_COMPONENT.get(why, "stall")
        served = {s.request_id for s in plan.seqs}
        t_step0 = float(self.clock())
        if plan.kind == "prefill":
            events = self._run_prefill(plan)
        else:
            events = self._run_decode(plan)
        stalled = [(s.request_id, s.trace_id)
                   for s in self.sched.running
                   if s.request_id not in served and s.trace_id is not None]
        if stalled:
            requesttrace.emit_stall_span(reg, stalled, t_step0,
                                         float(self.clock()), self._proc,
                                         component=stall_comp,
                                         cause=plan.kind)
        return events

    def _recover_from_hang(self) -> List[Dict[str, Any]]:
        """Hung-step recovery: the watchdog already dumped every thread's
        stack.  Device work in flight is abandoned — host state is still
        consistent (marks/pages only mutate after a step returns) — so
        rebuild the jitted fns and preempt the running set back to the
        queue; recompute-prefill replays them token-exact."""
        self._jit_step = None
        self._decode_tracked = None
        self._prefill_tracked = {}
        victims = self.sched.preempt_all()
        self.watchdog_restarts += 1
        reg = self._reg()
        reg.counter("serve.watchdog_restarts").inc()
        reg.emit("serve.watchdog_restart", step=self.steps,
                 victims=[s.request_id for s in victims])
        return []

    def has_work(self) -> bool:
        return self.sched.has_work()

    def run(self, max_steps: Optional[int] = None) -> int:
        """Drive :meth:`step` until every submitted request finishes;
        returns the number of steps taken."""
        taken = 0
        while self.sched.has_work():
            self.step()
            taken += 1
            if max_steps is not None and taken > max_steps:
                stuck = ([s.request_id for s in self.sched.running]
                         + [s.request_id for s in self.sched.waiting])
                raise RuntimeError(
                    f"engine did not drain in {max_steps} steps; stuck "
                    f"requests: {', '.join(stuck) or 'none'}")
        return taken

    # -- prefill / decode execution ---------------------------------------
    # The _apply_* helpers run the jitted step and read the result back
    # to host WITHOUT mutating any host state (no update_pages, no
    # scheduler marks) — that purity is what makes the quarantine
    # bisection probes and the post-eviction replay safe: a failed or
    # probed step leaves nothing behind.

    def _apply_fault(self, kind: str, seqs: List[SequenceState],
                     logits_np: np.ndarray) -> np.ndarray:
        """Fault seam + NaN guard, applied to every executed step
        (bisection probes included — injected faults must re-fire on the
        subset that still contains the target)."""
        if self.step_fault is not None:
            out = self.step_fault(self, kind,
                                  [s.request_id for s in seqs], logits_np)
            if out is not None:
                logits_np = np.asarray(out)
        if self.nan_guard:
            bad = [s.request_id for i, s in enumerate(seqs)
                   if not np.isfinite(logits_np[i]).all()]
            if bad:
                raise _NonfiniteLogits(bad)
        return logits_np

    def _apply_prefill(self, seq: SequenceState, bucket: int, key):
        ctx = seq.context()
        L = len(ctx)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :L] = ctx
        self._note_padding(L, bucket)
        tables = self.cache.table_array([seq.request_id],
                                        self.sched.max_blocks_per_seq)
        lens = np.asarray([L], np.int32)
        slots = self.cache.slot_array([seq.request_id], [0], bucket)
        caches = self.cache.layer_caches(tables, lens, slots)
        nxt, logits, new_caches = self._prefill_fn(bucket)(
            self._params, jnp.asarray(ids), jnp.zeros((1,), jnp.int32),
            jnp.asarray(L - 1, jnp.int32), caches, key)
        nxt_np = np.asarray(nxt)
        logits_np = self._apply_fault("prefill", [seq],
                                      np.asarray(logits))
        return nxt_np, logits_np, new_caches

    def _apply_decode(self, seqs: List[SequenceState], key):
        B = self.max_seqs
        enforce(len(seqs) <= B, f"{len(seqs)} decode rows > max_seqs {B}")
        self._note_padding(len(seqs), B)
        sids = [s.request_id for s in seqs] + \
            [_PAD_SEQ] * (B - len(seqs))
        ids = np.zeros((B, 1), np.int32)
        positions = np.zeros((B,), np.int32)
        lens = np.zeros((B,), np.int32)
        starts = [-1] * B
        for i, s in enumerate(seqs):
            enforce(s.pending is not None,
                    f"{s.request_id}: decode row without a pending token")
            ids[i, 0] = s.pending
            positions[i] = s.computed_len
            lens[i] = s.computed_len + 1      # includes the written token
            starts[i] = s.computed_len
        tables = self.cache.table_array(sids,
                                        self.sched.max_blocks_per_seq)
        slots = self.cache.slot_array(sids, starts, 1)
        caches = self.cache.layer_caches(tables, lens, slots)
        nxt, logits, new_caches = self._decode_fn()(
            self._params, jnp.asarray(ids), jnp.asarray(positions),
            jnp.asarray(0, jnp.int32), caches, key)
        nxt_np = np.asarray(nxt)
        logits_np = self._apply_fault("decode", seqs, np.asarray(logits))
        return nxt_np, logits_np, new_caches

    def _run_prefill(self, plan: StepPlan) -> List[Dict[str, Any]]:
        seq = plan.seqs[0]
        key = self._next_key()
        t_prefill0 = float(self.clock())
        try:
            nxt_np, logits_np, new_caches = self._apply_prefill(
                seq, plan.bucket, key)
        except StepTimeout:
            raise                      # the watchdog owns this one
        except Exception as e:
            self._quarantine_step("prefill", [seq], e, key)
            return []
        self.cache.update_pages(new_caches)
        self.sched.mark_prefilled(seq)
        reg = self._reg()
        reg.counter("serve.prefills").inc()
        if seq.trace_id is not None:
            # the (re-)prefill plus the queue wait before it; a
            # recompute's wait is attributed to its cause, not "queue"
            comp = _RESUME_COMPONENT.get(seq.resume_why, "prefill")
            t_q0 = seq.trace_enqueued
            if t_q0 is None:
                t_q0 = seq.arrival
            if t_prefill0 > t_q0:
                requesttrace.emit_span(
                    reg, seq.trace_id, seq.request_id, "queue",
                    "queue" if seq.resume_why is None else comp,
                    t_q0, t_prefill0, self._proc)
            requesttrace.emit_span(reg, seq.trace_id, seq.request_id,
                                   "prefill", comp, t_prefill0,
                                   float(self.clock()), self._proc,
                                   bucket=plan.bucket)
        seq.resume_why = None
        seq.trace_enqueued = None
        if seq.pending is not None:
            # recompute prefill after preemption: the next token was
            # already sampled (and streamed) before eviction — only the
            # KV was rebuilt; nothing new to emit
            return []
        return [self._accept_token(seq, int(nxt_np[0]),
                                   logits_np[0], first=True)]

    def _run_decode(self, plan: StepPlan) -> List[Dict[str, Any]]:
        seqs = plan.seqs
        key = self._next_key()
        t0 = float(self.clock())
        try:
            nxt_np, logits_np, new_caches = self._apply_decode(seqs, key)
        except StepTimeout:
            raise
        except Exception as e:
            survivors = self._quarantine_step("decode", seqs, e, key)
            if not survivors:
                return []
            # replay: the culprit rows are gone, every surviving row is
            # re-run with the same pending tokens — per-row paged
            # attention makes the survivors' logits (and, greedy,
            # their tokens) identical to the un-faulted step
            return self._run_decode(StepPlan("decode", survivors))
        self.cache.update_pages(new_caches)
        reg = self._reg()
        reg.counter("serve.decode_steps").inc()
        reg.histogram("serve.decode_batch").observe(float(len(seqs)))
        events = []
        for i, s in enumerate(seqs):
            self.sched.mark_decoded(s)
            events.append(self._accept_token(s, int(nxt_np[i]),
                                             logits_np[i], first=False))
        # one batch-level decode span; the assembler amortizes the step
        # across its residents to produce per-request decode time
        requesttrace.emit_decode_span(
            reg, [(s.request_id, s.trace_id) for s in seqs], len(seqs),
            t0, float(self.clock()), self._proc)
        return events

    # -- poisoned-request quarantine ---------------------------------------
    def _probe(self, seqs: List[SequenceState], key) -> bool:
        """Re-run the decode step on a subset; True when it faults.
        Pure — no host state mutates — so probing is free to repeat."""
        try:
            self._apply_decode(seqs, key)
        except StepTimeout:
            raise
        except Exception:
            return True
        return False

    def _bisect(self, seqs: List[SequenceState],
                key) -> List[SequenceState]:
        """Find the faulting sequence(s) by halving.  A passing half is
        exonerated (faults here are deterministic per-row).  When the
        whole group faults but neither half does, the fault is an
        interaction — quarantine the whole group rather than loop."""
        if len(seqs) == 1:
            return seqs
        mid = len(seqs) // 2
        left, right = seqs[:mid], seqs[mid:]
        culprits: List[SequenceState] = []
        if self._probe(left, key):
            culprits += self._bisect(left, key)
        if self._probe(right, key):
            culprits += self._bisect(right, key)
        return culprits or seqs

    def _quarantine_step(self, kind: str, seqs: List[SequenceState],
                         error: Exception, key) -> List[SequenceState]:
        """Fault-boundary handler: identify the culprit rows, evict each
        with ``reason="poisoned"`` and a durable record, return the
        surviving sequences for replay."""
        t0 = float(self.clock())
        if isinstance(error, _NonfiniteLogits):
            bad = set(error.request_ids)
            culprits = [s for s in seqs if s.request_id in bad]
        elif kind == "prefill" or len(seqs) == 1:
            culprits = list(seqs)
        else:
            culprits = self._bisect(seqs, key)
        for seq in culprits:
            self._quarantine(seq, error, kind)
        # the bisect stalls every row in the faulted batch — attribute
        # that time to quarantine for culprits and survivors alike
        t1 = float(self.clock())
        reg = self._reg()
        for seq in seqs:
            requesttrace.emit_span(reg, seq.trace_id, seq.request_id,
                                   "quarantine_bisect", "quarantine",
                                   t0, t1, self._proc)
        return [s for s in seqs if s not in culprits]

    def _quarantine(self, seq: SequenceState, error: Exception,
                    kind: str) -> None:
        self.sched.evict(seq, "poisoned")
        self.lifecycle_counts["poisoned"] += 1
        record = {"request_id": seq.request_id, "reason": "poisoned",
                  "step_kind": kind, "error": repr(error),
                  "engine_step": self.steps,
                  "prompt_len": len(seq.prompt),
                  "generated": len(seq.output),
                  "output": list(seq.output),
                  "trace_id": seq.trace_id,
                  "time": float(self.clock())}
        self.quarantined[seq.request_id] = record
        reg = self._reg()
        reg.counter("serve.poisoned").inc()
        reg.emit("serve.quarantine", **record)
        self._trace_end(seq, "poisoned")
        if self.run_dir is not None:
            qdir = os.path.join(self.serve_dir(), "quarantine")
            os.makedirs(qdir, exist_ok=True)
            fname = re.sub(r"[^\w.-]", "_", seq.request_id) + ".json"
            fsio.atomic_write_bytes(
                os.path.join(qdir, fname),
                json.dumps(record, indent=1).encode())
        event = {"request_id": seq.request_id, "token": None,
                 "finished": True, "reason": "poisoned"}
        if seq.on_token is not None:
            self._dispatch_callback(seq.on_token, event, seq)

    def _accept_token(self, seq: SequenceState, token: int, logits_row,
                      first: bool) -> Dict[str, Any]:
        now = float(self.clock())
        seq.output.append(token)
        seq.pending = token
        reg = self._reg()
        if first:
            seq.first_token_time = now
            ttft = (now - seq.arrival) * 1e3
            reg.histogram("serve.ttft_ms").observe(ttft)
            self._ttft_ms.append(ttft)
        elif seq.last_token_time is not None:
            tpot = (now - seq.last_token_time) * 1e3
            reg.histogram("serve.tpot_ms").observe(tpot)
            self._tpot_ms.append(tpot)
        seq.last_token_time = now
        reg.counter("serve.tokens").inc()
        if seq.capture_logits:
            seq.logits.append(np.asarray(logits_row))
        reason = seq.should_finish()
        if reason is not None:
            self.sched.complete(seq, reason)
            reg.counter("serve.finished").inc()
            reg.emit("serve.finish", request_id=seq.request_id,
                     reason=reason, generated=len(seq.output),
                     preemptions=seq.preemptions, trace_id=seq.trace_id)
            self._trace_end(seq, reason)
        event = {"request_id": seq.request_id, "token": token,
                 "finished": reason is not None, "reason": reason}
        if seq.on_token is not None:
            self._dispatch_callback(seq.on_token, event, seq)
        return event

    # -- decoupled token callbacks ----------------------------------------
    def _dispatch_callback(self, cb: Callable, event: Dict[str, Any],
                           seq: Optional[SequenceState] = None) -> None:
        if self._cb_queue is None:
            self._cb_queue = queue.Queue()
            self._cb_thread = threading.Thread(
                target=self._cb_worker, name="ptpu-serve-callbacks",
                daemon=True)
            self._cb_thread.start()
        self._cb_dispatched += 1
        self._cb_queue.put((cb, event,
                            None if seq is None else seq.trace_id))

    def _cb_worker(self) -> None:
        while True:
            item = self._cb_queue.get()
            try:
                if item is _CB_STOP:
                    return
                cb, event, trace_id = item
                cb_t0 = float(self.clock())
                try:
                    cb(event["request_id"], event["token"],
                       event["finished"])
                except Exception as e:  # consumer bug must not kill serving
                    self._cb_errors += 1
                    self._last_callback_error = \
                        f"{event['request_id']}: {e!r}"
                    reg = self._reg()
                    reg.counter("serve.callback_errors").inc()
                    reg.emit("serve.callback_error",
                             request_id=event["request_id"], error=repr(e))
                    from ..framework.log import vlog
                    vlog(0, "serving: on_token callback failed for %s: %r",
                         event["request_id"], e)
                requesttrace.emit_span(self._reg(), trace_id,
                                       event["request_id"], "callback",
                                       "callback", cb_t0,
                                       float(self.clock()), self._proc)
            finally:
                self._cb_queue.task_done()

    def _stop_callbacks(self, timeout: Optional[float] = None) -> bool:
        """Stop the callback thread after it drains the queue; True when
        it exited within the timeout (or was never started)."""
        if self._cb_thread is None:
            return True
        self._cb_queue.put(_CB_STOP)
        self._cb_thread.join(timeout=timeout)
        alive = self._cb_thread.is_alive()
        if not alive:
            self._cb_thread = None
            self._cb_queue = None
        return not alive

    def drain_callbacks(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued on_token callback ran (tests); True
        when drained."""
        if self._cb_queue is None:
            return True
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._cb_queue.unfinished_tasks:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)
        return True

    # -- results ------------------------------------------------------------
    def _request_state(self, request_id: str) -> str:
        """Human-readable scheduler state for timeout/stuck messages."""
        for seq in self.sched.running:
            if seq.request_id == request_id:
                return (f"state=running, generated={len(seq.output)}/"
                        f"{seq.max_new_tokens}, "
                        f"computed_len={seq.computed_len}")
        for pos, seq in enumerate(self.sched.waiting):
            if seq.request_id == request_id:
                return (f"state={seq.state}, queue_position={pos}, "
                        f"queue_depth={len(self.sched.waiting)}")
        return "state=unknown (never submitted?)"

    def collect(self, request_id: str,
                max_steps: Optional[int] = None,
                timeout: Optional[float] = None) -> Dict[str, Any]:
        """Drive the engine until ``request_id`` finishes; return its
        result record.  ``timeout`` (seconds, wall clock) bounds the
        wait — on expiry raises :class:`CollectTimeout` naming the
        request's current scheduler state instead of spinning forever."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while request_id not in self.sched.finished:
            enforce(self.sched.has_work(),
                    f"{request_id}: unknown request (never submitted?)")
            if deadline is not None and time.monotonic() >= deadline:
                raise CollectTimeout(
                    f"{request_id}: not finished after {timeout}s "
                    f"({self._request_state(request_id)})")
            self.step()
            if max_steps is not None:
                max_steps -= 1
                enforce(max_steps >= 0, f"{request_id}: step budget spent")
        seq = self.sched.finished[request_id]
        n = len(seq.output)
        tpot = None
        if (n > 1 and seq.first_token_time is not None
                and seq.last_token_time is not None):
            tpot = (seq.last_token_time - seq.first_token_time) / (n - 1)
        out = {"request_id": request_id, "tokens": list(seq.output),
               "finish_reason": seq.finish_reason,
               "preemptions": seq.preemptions,
               "ttft_ms": (None if seq.first_token_time is None else
                           (seq.first_token_time - seq.arrival) * 1e3),
               "tpot_ms": None if tpot is None else tpot * 1e3}
        if seq.capture_logits:
            out["logits"] = list(seq.logits)
        return out

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Batch convenience: submit every prompt, drain, return the
        generated token lists in submit order."""
        rids = [self.submit(p, max_new_tokens=max_new_tokens,
                            eos_token_id=eos_token_id) for p in prompts]
        self.run()
        return [self.collect(r)["tokens"] for r in rids]

    # -- graceful drain / resume -------------------------------------------
    @property
    def state(self) -> str:
        """``serving`` | ``draining`` | ``stopped`` — mirrored on
        ``/healthz`` (503 once not ``serving``)."""
        return self._state

    def begin_drain(self) -> None:
        """Stop admission without blocking: new ``submit()`` calls are
        refused, ``/healthz`` goes 503 ``draining``, but already-admitted
        work keeps stepping.  Idempotent; ``drain()`` calls it first."""
        if self._state != "serving":
            return
        self._state = "draining"
        self.sched.admission_open = False
        c = self.sched.counts()
        self._reg().emit("serve.drain_begin", running=c["running"],
                         waiting=c["waiting"])

    def drain(self, timeout: Optional[float] = None,
              spill_path: Optional[str] = None) -> Dict[str, Any]:
        """Graceful shutdown: stop admission, finish what fits inside
        ``timeout`` (default ``PTPU_SERVE_DRAIN_SECS``), spill the rest
        to ``spill_path`` (default
        ``<run_dir>/serve/replica-<i>/spill.json``) as a JSON file a
        fresh engine can :meth:`resume` from, stop the callback thread,
        and mark the engine ``stopped``.  The report carries the spill
        records inline (``"spilled_records"``) so a fleet router can
        migrate them without re-reading the file."""
        if timeout is None:
            timeout = default_drain_secs()
        self.begin_drain()
        hard = time.monotonic() + float(timeout)
        timed_out = False
        finished = 0
        while (self.sched.running
               or any(s.output for s in self.sched.waiting)):
            if time.monotonic() >= hard:
                timed_out = True
                break
            before = len(self.sched.finished)
            self.step()
            finished += len(self.sched.finished) - before
        # spill whatever is still live — running sequences that ran out
        # of time spill too (their generated tokens ride along, resume
        # recomputes their KV and continues decoding)
        leftovers = list(self.sched.running) + list(self.sched.waiting)
        spilled = []
        for seq in leftovers:
            spilled.append({"request_id": seq.request_id,
                            "prompt": list(seq.prompt),
                            "output": list(seq.output),
                            "max_new_tokens": seq.max_new_tokens,
                            "eos_token_id": seq.eos_token_id,
                            "preemptions": seq.preemptions,
                            # trace context survives the spill; ownership
                            # transfers to whichever engine resumes it
                            "trace_id": seq.trace_id,
                            "trace_owner": seq.request_id in
                            self._trace_owned,
                            "resume_why": "migration"})
            self._trace_owned.discard(seq.request_id)
            self.sched.evict(seq, "spilled")
            self.lifecycle_counts["spilled"] += 1
            self._reg().counter("serve.spilled").inc()
        if spilled:
            if spill_path is None and self.run_dir is not None:
                os.makedirs(self.serve_dir(), exist_ok=True)
                spill_path = os.path.join(self.serve_dir(), "spill.json")
            enforce(spill_path is not None,
                    "drain spilled requests but no spill_path was given "
                    "and the engine has no run_dir")
            fsio.atomic_write_bytes(
                spill_path,
                json.dumps({"version": 1, "spilled": spilled},
                           indent=1).encode())
        callbacks_stopped = self._stop_callbacks(timeout=5.0)
        self._state = "stopped"
        self._reg().emit("serve.drain_end", finished=finished,
                         spilled=len(spilled), timed_out=timed_out)
        self._update_gauges()
        return {"finished": finished, "spilled": len(spilled),
                "spill_path": spill_path if spilled else None,
                "spilled_records": spilled,
                "timed_out": timed_out,
                "callbacks_stopped": callbacks_stopped}

    def admit_record(self, record: Dict[str, Any]) -> str:
        """Admit one spill-format record (``request_id`` / ``prompt`` /
        ``output`` / ``max_new_tokens`` / ``eos_token_id``) into this
        serving engine.  The generated ``output`` tail is preserved and
        its newest token becomes ``pending``, so the recompute-prefill
        path rebuilds the KV and decoding continues **token-exact** —
        the seam both :meth:`resume` and the fleet router's failover
        re-submission go through.  Returns the request id.

        Idempotent on ``request_id``: a record the engine already holds
        (running, waiting or finished) is NOT re-admitted — the router's
        crash recovery may race a re-dispatch against a replica that
        still owns the stream, and a duplicate sequence would double-
        schedule it."""
        enforce(self._state == "serving",
                f"admit_record() needs a serving engine "
                f"(state={self._state})")
        rid = record["request_id"]
        if rid in self.sched.finished or any(
                s.request_id == rid for s in
                list(self.sched.running) + list(self.sched.waiting)):
            self._reg().counter("serve.readmit_dupes").inc()
            return rid
        seq = SequenceState(
            request_id=record["request_id"],
            prompt=[int(t) for t in record["prompt"]],
            max_new_tokens=int(record["max_new_tokens"]),
            eos_token_id=record.get("eos_token_id"),
            arrival=float(self.clock()),
            capture_logits=self.capture_logits)
        seq.output = [int(t) for t in record.get("output", [])]
        seq.pending = seq.output[-1] if seq.output else None
        seq.preemptions = int(record.get("preemptions", 0))
        # trace context (ISSUE 18): keep the record's trace_id so the
        # assembled waterfall stitches across engines.  An explicit
        # ``"trace_id": None`` is a deliberate decision (disabled or
        # sampled out at the router) and must survive the process
        # boundary; only a record WITHOUT the key (pre-tracing spill,
        # direct admit) gets an engine-owned trace minted here
        if "trace_id" in record:
            seq.trace_id = record["trace_id"]
            if seq.trace_id is not None and record.get("trace_owner"):
                self._trace_owned.add(rid)
        else:
            seq.trace_id = requesttrace.mint_trace_id(rid)
            if seq.trace_id is not None:
                self._trace_owned.add(rid)
                self._reg().emit("trace.request", trace_id=seq.trace_id,
                                 request_id=rid, t0=seq.arrival,
                                 prompt_len=len(seq.prompt),
                                 proc=self._proc)
        if seq.output:
            seq.resume_why = record.get("resume_why") or "failover"
        self.sched.submit(seq)
        self._submit_order.append(seq.request_id)
        self._reg().counter("serve.resumed").inc()
        self._update_gauges()
        return seq.request_id

    def resume(self, spill_path: Optional[str] = None) -> List[str]:
        """Re-admit a drain spill file into THIS (fresh, serving)
        engine.  Sequences resume exactly where they left off: generated
        output is preserved and the newest token becomes ``pending``, so
        the recompute-prefill path rebuilds the KV and decoding
        continues token-exact.  Returns the resumed request ids.

        Without ``spill_path`` the engine reads its namespaced
        ``<run_dir>/serve/replica-<i>/spill.json``, falling back to the
        pre-ISSUE-16 ``<run_dir>/serve_spill.json`` so old run dirs
        stay resumable."""
        enforce(self._state == "serving",
                f"resume() needs a serving engine (state={self._state})")
        if spill_path is None:
            enforce(self.run_dir is not None,
                    "resume() without a spill_path needs a run_dir")
            spill_path = os.path.join(self.serve_dir(), "spill.json")
            if not os.path.exists(spill_path):
                legacy = os.path.join(self.run_dir, "serve_spill.json")
                enforce(os.path.exists(legacy),
                        f"no spill file at {spill_path} or {legacy}")
                spill_path = legacy
        payload = json.loads(fsio.read_bytes(spill_path).decode())
        enforce(payload.get("version") == 1,
                f"unknown spill-file version {payload.get('version')!r}")
        return [self.admit_record(rec) for rec in payload["spilled"]]

    # -- observability ------------------------------------------------------
    def _note_padding(self, real: int, total: int) -> None:
        """One padded launch (prefill bucket or fixed decode batch):
        ``real`` of ``total`` token slots carried actual work.  Keeps
        the cumulative ``serve.padding_frac`` gauge current."""
        real = max(0, int(real))
        total = max(real, int(total))
        self._pad_real_tokens += real
        self._pad_slot_tokens += total
        reg = self._reg()
        reg.counter("serve.tokens_real").inc(real)
        reg.counter("serve.tokens_padded").inc(total - real)
        if self._pad_slot_tokens:
            reg.gauge("serve.padding_frac").set(
                1.0 - self._pad_real_tokens / self._pad_slot_tokens)

    def padding_frac(self) -> float:
        """Cumulative fraction of launched token slots that were pad
        (0.0 before any launch)."""
        if not self._pad_slot_tokens:
            return 0.0
        return 1.0 - self._pad_real_tokens / self._pad_slot_tokens

    def _update_gauges(self) -> None:
        reg = self._reg()
        c = self.sched.counts()
        reg.gauge("serve.queue_depth").set(float(self.sched.queue_depth))
        reg.gauge("serve.waiting").set(float(c["waiting"]))
        reg.gauge("serve.running").set(float(c["running"]))
        reg.gauge("serve.kv_occupancy").set(self.cache.occupancy())
        reg.gauge("serve.kv_blocks_used").set(
            float(self.cache.allocator.num_used))
        reg.gauge("serve.shed").set(1.0 if self.should_shed() else 0.0)

    def stats(self) -> Dict[str, Any]:
        """Engine-state snapshot for ``/statusz`` (counts the registry
        cannot derive: pool geometry, scheduler queues, shed state, the
        resilience section)."""
        c = self.sched.counts()
        leak = self.cache.leak_report()
        return {
            "steps": self.steps,
            "replica_id": self.replica_id,
            "queue_depth": self.sched.queue_depth,
            "waiting": c["waiting"],
            "running": c["running"],
            "finished": c["finished"],
            "preemptions": c["preemptions"],
            "max_seqs": self.max_seqs,
            "max_model_len": self.max_model_len,
            "kv_block_size": self.cache.block_size,
            "kv_blocks": {"total": self.cache.num_blocks,
                          "used": self.cache.allocator.num_used,
                          "occupancy": self.cache.occupancy(),
                          "high_water": leak["high_water"],
                          "leaked": leak["leaked_blocks"],
                          "balanced": leak["balanced"]},
            "load_shed": {"active": self.should_shed(),
                          "queue_threshold": self.shed_queue_depth},
            "padding": {"real_tokens": self._pad_real_tokens,
                        "padded_slots": self._pad_slot_tokens,
                        "frac": self.padding_frac()},
            "slo": {"ttft_ms": {"p50": _pctl(self._ttft_ms, 50),
                                "p99": _pctl(self._ttft_ms, 99),
                                "samples": len(self._ttft_ms)},
                    "tpot_ms": {"p50": _pctl(self._tpot_ms, 50),
                                "p99": _pctl(self._tpot_ms, 99),
                                "samples": len(self._tpot_ms)}},
            "resilience": {
                "state": self._state,
                "deadline_misses": self.lifecycle_counts["deadline"],
                "cancelled": self.lifecycle_counts["cancelled"],
                "poisoned": self.lifecycle_counts["poisoned"],
                "spilled": self.lifecycle_counts["spilled"],
                "watchdog_restarts": self.watchdog_restarts,
                "quarantined": sorted(self.quarantined),
                "callbacks": {"dispatched": self._cb_dispatched,
                              "errors": self._cb_errors,
                              "last_error": self._last_callback_error},
            },
        }

    def defrag(self) -> bool:
        """Compact the KV pool (see ``PagedKVCache.defrag``)."""
        return self.cache.defrag()

    def start_status_server(self, port: int = 0, host: str = "0.0.0.0"):
        """Expose serving SLOs on the PR 5 monitor; returns the server
        (``.port`` holds the bound port)."""
        from ..observability.monitor import StatusServer
        self.status_server = StatusServer(port=port, host=host,
                                          registry=self._registry,
                                          engine=self).start()
        return self.status_server

    def stop(self) -> None:
        self._stop_callbacks(timeout=1.0)
        if self._owns_watchdog and self._watchdog is not None:
            self._watchdog.close()
            self._watchdog = None
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None
        self._state = "stopped"
