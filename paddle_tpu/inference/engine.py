"""ServingEngine (ISSUE 6): the continuous-batching serving loop.

The training side of this codebase drives a model with one jitted step
over a fixed batch; serving traffic is the opposite shape — requests
arrive at random times, with ragged prompts, and leave when *they* are
done.  The engine turns that traffic into fixed-shape device work:

    engine = ServingEngine(model, max_seqs=8, kv_block_size=16)
    rid = engine.submit([1, 5, 9], max_new_tokens=32)
    while engine.step():            # one prefill OR one decode batch
        ...
    out = engine.collect(rid)       # {"tokens": [...], "ttft_ms": ...}

Pieces (all under ``paddle_tpu/inference/``):

- ``kv_cache.PagedKVCache`` — block-pooled KV with per-sequence tables;
- ``scheduler.ContinuousBatchingScheduler`` — admission by block
  budget, newest-first preemption, prefill/decode interleaving;
- ``paged_attention`` — the ragged decode kernel (lax fallback on CPU);
- this module — the jitted step functions, sampling, SLO metrics, and
  the submit/step/collect surface.

Step shapes come from a closed set — decode is always
``(max_seqs, 1)``; prefill is padded to power-of-two buckets — and each
shape's jitted function is wrapped in the PR 4 compile tracker under its
own name (``serve_decode``, ``serve_prefill_b<bucket>``), so a full
serve run compiles **once per bucket** and any retrace is attributable.

SLO telemetry rides the PR 3 registry: gauges ``serve.queue_depth`` /
``serve.running`` / ``serve.waiting`` / ``serve.kv_occupancy``,
histograms ``serve.ttft_ms`` / ``serve.tpot_ms``, counters
``serve.tokens`` / ``serve.requests`` / ``serve.finished`` /
``serve.preemptions``.  ``start_status_server()`` exposes them on the
PR 5 monitor (``/statusz`` serving section; ``/healthz`` goes 503 when
the admission queue exceeds ``PTPU_SHED_QUEUE_DEPTH`` — load shedding).

Token callbacks (``submit(..., on_token=fn)``) are dispatched from a
separate drain thread: a slow consumer (``testing/faults.slow_call``)
delays its own stream, never the batch.

Env knobs: ``PTPU_MAX_SEQS``, ``PTPU_KV_BLOCK_SIZE``,
``PTPU_SHED_QUEUE_DEPTH``.  Single-host by design: the page scatter and
the Pallas kernel are opaque to GSPMD (the engine enforces no mesh).
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.errors import enforce
from ..observability.compilation import track_jit
from .kv_cache import PagedKVCache, default_kv_block_size
from .scheduler import (ContinuousBatchingScheduler, SequenceState,
                        StepPlan)

__all__ = ["MAX_SEQS_ENV", "SHED_QUEUE_DEPTH_ENV", "default_max_seqs",
           "default_shed_queue_depth", "ServingEngine"]

MAX_SEQS_ENV = "PTPU_MAX_SEQS"
SHED_QUEUE_DEPTH_ENV = "PTPU_SHED_QUEUE_DEPTH"

_PAD_SEQ = "__pad__"          # never a real request id


def default_max_seqs() -> int:
    return int(os.environ.get(MAX_SEQS_ENV, "8"))


def default_shed_queue_depth() -> int:
    return int(os.environ.get(SHED_QUEUE_DEPTH_ENV, "64"))


class ServingEngine:
    """Paged-KV continuous-batching serving engine over a decoder model.

    ``model`` must expose the ``GPTForCausalLM`` serving surface:
    ``.config`` (num_layers / num_heads / head_dim /
    max_position_embeddings / dtype), ``.state_dict()``, ``.eval()`` and
    an ``apply(..., method="serving_step")`` entry point returning
    ``(logits, new_caches)`` over ``PagedLayerCache`` lists.

    ``temperature`` is engine-level (it is baked into the jitted step;
    per-request temperatures would multiply the compile set).
    ``capture_logits=True`` keeps every sampled position's logits row on
    the host per request — the numerics-equality hook for tests.
    """

    def __init__(self, model, *, max_seqs: Optional[int] = None,
                 kv_block_size: Optional[int] = None,
                 num_kv_blocks: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 temperature: float = 0.0,
                 capture_logits: bool = False,
                 shed_queue_depth: Optional[int] = None,
                 registry=None, seed: int = 0,
                 clock: Callable[[], float] = time.time):
        from ..distributed.topology import get_mesh
        enforce(get_mesh() is None,
                "ServingEngine is single-host (the paged path is opaque "
                "to GSPMD) — run it outside fleet meshes")
        cfg = model.config
        self.model = model
        model.eval()
        self._params = model.state_dict()
        self.max_seqs = int(max_seqs if max_seqs is not None
                            else default_max_seqs())
        self.max_model_len = int(max_model_len if max_model_len is not None
                                 else cfg.max_position_embeddings)
        enforce(self.max_model_len <= cfg.max_position_embeddings,
                f"max_model_len {self.max_model_len} exceeds the model's "
                f"{cfg.max_position_embeddings} positions")
        block_size = (default_kv_block_size() if kv_block_size is None
                      else int(kv_block_size))
        blocks_per_seq = -(-self.max_model_len // block_size)
        if num_kv_blocks is None:
            # roomy default: every batch slot can hold a full-length
            # sequence (tests pass tight pools to exercise preemption)
            num_kv_blocks = self.max_seqs * blocks_per_seq
        dtype = (jnp.dtype(cfg.dtype) if cfg.dtype != "float32"
                 else jnp.float32)
        self.cache = PagedKVCache(cfg.num_layers, cfg.num_heads,
                                  cfg.head_dim, num_kv_blocks,
                                  block_size=block_size, dtype=dtype)
        self.sched = ContinuousBatchingScheduler(
            self.cache, self.max_seqs, self.max_model_len, clock=clock)
        self.temperature = float(temperature)
        self.capture_logits = bool(capture_logits)
        self.shed_queue_depth = int(
            shed_queue_depth if shed_queue_depth is not None
            else default_shed_queue_depth())
        self._registry = registry
        self.clock = clock
        self._key = jax.random.PRNGKey(seed)
        self._ids = itertools.count()
        self.steps = 0
        self.status_server = None
        self._decode_tracked = None
        self._prefill_tracked: Dict[int, Callable] = {}
        self._cb_queue: Optional[queue.Queue] = None
        self._cb_thread: Optional[threading.Thread] = None

    # -- plumbing ----------------------------------------------------------
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..observability.registry import get_registry
        return get_registry()

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- jitted step functions --------------------------------------------
    def _build_step_fn(self):
        model, temperature = self.model, self.temperature

        def fn(params, ids, positions, last_index, caches, key):
            logits, new_caches = model.apply(
                params, ids, caches, positions, last_index,
                method="serving_step")
            logits = logits.astype(jnp.float32)
            if temperature <= 0.0:
                nxt = jnp.argmax(logits, axis=-1)
            else:
                nxt = jax.random.categorical(key, logits / temperature,
                                             axis=-1)
            return nxt.astype(jnp.int32), logits, new_caches

        return jax.jit(fn)

    def _decode_fn(self):
        if self._decode_tracked is None:
            self._jit_step = getattr(self, "_jit_step", None) \
                or self._build_step_fn()
            self._decode_tracked = track_jit(
                self._jit_step, name="serve_decode",
                arg_names=("params", "ids", "positions", "last_index",
                           "caches", "key"))
        return self._decode_tracked

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_tracked.get(bucket)
        if fn is None:
            # same underlying jitted callable (jax caches per shape);
            # a per-bucket tracker name makes "one compile per bucket"
            # directly observable and keeps retrace counts at zero
            self._jit_step = getattr(self, "_jit_step", None) \
                or self._build_step_fn()
            fn = track_jit(self._jit_step, name=f"serve_prefill_b{bucket}",
                           arg_names=("params", "ids", "positions",
                                      "last_index", "caches", "key"))
            self._prefill_tracked[bucket] = fn
        return fn

    # -- intake ------------------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int = 32,
               request_id: Optional[str] = None,
               eos_token_id: Optional[int] = None,
               on_token: Optional[Callable] = None) -> str:
        """Queue one request; returns its id.  ``on_token(request_id,
        token, finished)`` — when given — is invoked from the callback
        drain thread, decoupled from the step loop."""
        rid = request_id or f"req-{next(self._ids)}"
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        seq = SequenceState(request_id=rid, prompt=prompt,
                            max_new_tokens=int(max_new_tokens),
                            eos_token_id=eos_token_id,
                            arrival=float(self.clock()),
                            on_token=on_token,
                            capture_logits=self.capture_logits)
        self.sched.submit(seq)
        reg = self._reg()
        reg.counter("serve.requests").inc()
        reg.emit("serve.request", request_id=rid, prompt_len=len(prompt),
                 max_new_tokens=seq.max_new_tokens)
        self._update_gauges()
        return rid

    def should_shed(self) -> bool:
        """Load-shed signal: the admission queue is past the knob —
        ``/healthz`` turns 503 so the balancer drains elsewhere."""
        return self.sched.queue_depth > self.shed_queue_depth

    # -- the step ----------------------------------------------------------
    def step(self) -> List[Dict[str, Any]]:
        """Run one scheduler-chosen unit of work (one prefill or one
        decode batch).  Returns the token events it produced; empty when
        idle AND no queued work remains."""
        plan = self.sched.schedule()
        reg = self._reg()
        for victim in plan.preempted:
            reg.counter("serve.preemptions").inc()
            reg.emit("serve.preempt", request_id=victim.request_id,
                     generated=len(victim.output))
        if plan.kind == "prefill":
            events = self._run_prefill(plan)
        elif plan.kind == "decode":
            events = self._run_decode(plan)
        else:
            events = []
        self.steps += 1
        self._update_gauges()
        return events

    def has_work(self) -> bool:
        return self.sched.has_work()

    def run(self, max_steps: Optional[int] = None) -> int:
        """Drive :meth:`step` until every submitted request finishes;
        returns the number of steps taken."""
        taken = 0
        while self.sched.has_work():
            self.step()
            taken += 1
            enforce(max_steps is None or taken <= max_steps,
                    f"engine did not drain in {max_steps} steps")
        return taken

    # -- prefill / decode execution ---------------------------------------
    def _run_prefill(self, plan: StepPlan) -> List[Dict[str, Any]]:
        seq = plan.seqs[0]
        ctx = seq.context()
        L, bucket = len(ctx), plan.bucket
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :L] = ctx
        tables = self.cache.table_array([seq.request_id],
                                        self.sched.max_blocks_per_seq)
        lens = np.asarray([L], np.int32)
        slots = self.cache.slot_array([seq.request_id], [0], bucket)
        caches = self.cache.layer_caches(tables, lens, slots)
        nxt, logits, new_caches = self._prefill_fn(bucket)(
            self._params, jnp.asarray(ids), jnp.zeros((1,), jnp.int32),
            jnp.asarray(L - 1, jnp.int32), caches, self._next_key())
        self.cache.update_pages(new_caches)
        self.sched.mark_prefilled(seq)
        self._reg().counter("serve.prefills").inc()
        if seq.pending is not None:
            # recompute prefill after preemption: the next token was
            # already sampled (and streamed) before eviction — only the
            # KV was rebuilt; nothing new to emit
            return []
        return [self._accept_token(seq, int(np.asarray(nxt)[0]),
                                   logits[0], first=True)]

    def _run_decode(self, plan: StepPlan) -> List[Dict[str, Any]]:
        seqs = plan.seqs
        B = self.max_seqs
        enforce(len(seqs) <= B, f"{len(seqs)} decode rows > max_seqs {B}")
        sids = [s.request_id for s in seqs] + \
            [_PAD_SEQ] * (B - len(seqs))
        ids = np.zeros((B, 1), np.int32)
        positions = np.zeros((B,), np.int32)
        lens = np.zeros((B,), np.int32)
        starts = [-1] * B
        for i, s in enumerate(seqs):
            enforce(s.pending is not None,
                    f"{s.request_id}: decode row without a pending token")
            ids[i, 0] = s.pending
            positions[i] = s.computed_len
            lens[i] = s.computed_len + 1      # includes the written token
            starts[i] = s.computed_len
        tables = self.cache.table_array(sids,
                                        self.sched.max_blocks_per_seq)
        slots = self.cache.slot_array(sids, starts, 1)
        caches = self.cache.layer_caches(tables, lens, slots)
        nxt, logits, new_caches = self._decode_fn()(
            self._params, jnp.asarray(ids), jnp.asarray(positions),
            jnp.asarray(0, jnp.int32), caches, self._next_key())
        self.cache.update_pages(new_caches)
        nxt_np = np.asarray(nxt)
        reg = self._reg()
        reg.counter("serve.decode_steps").inc()
        reg.histogram("serve.decode_batch").observe(float(len(seqs)))
        events = []
        for i, s in enumerate(seqs):
            self.sched.mark_decoded(s)
            events.append(self._accept_token(s, int(nxt_np[i]), logits[i],
                                             first=False))
        return events

    def _accept_token(self, seq: SequenceState, token: int, logits_row,
                      first: bool) -> Dict[str, Any]:
        now = float(self.clock())
        seq.output.append(token)
        seq.pending = token
        reg = self._reg()
        if first:
            seq.first_token_time = now
            reg.histogram("serve.ttft_ms").observe(
                (now - seq.arrival) * 1e3)
        elif seq.last_token_time is not None:
            reg.histogram("serve.tpot_ms").observe(
                (now - seq.last_token_time) * 1e3)
        seq.last_token_time = now
        reg.counter("serve.tokens").inc()
        if seq.capture_logits:
            seq.logits.append(np.asarray(logits_row))
        reason = seq.should_finish()
        if reason is not None:
            self.sched.complete(seq, reason)
            reg.counter("serve.finished").inc()
            reg.emit("serve.finish", request_id=seq.request_id,
                     reason=reason, generated=len(seq.output),
                     preemptions=seq.preemptions)
        event = {"request_id": seq.request_id, "token": token,
                 "finished": reason is not None, "reason": reason}
        if seq.on_token is not None:
            self._dispatch_callback(seq.on_token, event)
        return event

    # -- decoupled token callbacks ----------------------------------------
    def _dispatch_callback(self, cb: Callable,
                           event: Dict[str, Any]) -> None:
        if self._cb_queue is None:
            self._cb_queue = queue.Queue()
            self._cb_thread = threading.Thread(
                target=self._cb_worker, name="ptpu-serve-callbacks",
                daemon=True)
            self._cb_thread.start()
        self._cb_queue.put((cb, event))

    def _cb_worker(self) -> None:
        while True:
            cb, event = self._cb_queue.get()
            try:
                cb(event["request_id"], event["token"], event["finished"])
            except Exception as e:  # a consumer bug must not kill serving
                from ..framework.log import vlog
                vlog(0, "serving: on_token callback failed for %s: %r",
                     event["request_id"], e)
            finally:
                self._cb_queue.task_done()

    def drain_callbacks(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued on_token callback ran (tests); True
        when drained."""
        if self._cb_queue is None:
            return True
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._cb_queue.unfinished_tasks:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)
        return True

    # -- results ------------------------------------------------------------
    def collect(self, request_id: str,
                max_steps: Optional[int] = None) -> Dict[str, Any]:
        """Drive the engine until ``request_id`` finishes; return its
        result record."""
        while request_id not in self.sched.finished:
            enforce(self.sched.has_work(),
                    f"{request_id}: unknown request (never submitted?)")
            self.step()
            if max_steps is not None:
                max_steps -= 1
                enforce(max_steps >= 0, f"{request_id}: step budget spent")
        seq = self.sched.finished[request_id]
        n = len(seq.output)
        tpot = None
        if (n > 1 and seq.first_token_time is not None
                and seq.last_token_time is not None):
            tpot = (seq.last_token_time - seq.first_token_time) / (n - 1)
        out = {"request_id": request_id, "tokens": list(seq.output),
               "finish_reason": seq.finish_reason,
               "preemptions": seq.preemptions,
               "ttft_ms": (None if seq.first_token_time is None else
                           (seq.first_token_time - seq.arrival) * 1e3),
               "tpot_ms": None if tpot is None else tpot * 1e3}
        if seq.capture_logits:
            out["logits"] = list(seq.logits)
        return out

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Batch convenience: submit every prompt, drain, return the
        generated token lists in submit order."""
        rids = [self.submit(p, max_new_tokens=max_new_tokens,
                            eos_token_id=eos_token_id) for p in prompts]
        self.run()
        return [self.collect(r)["tokens"] for r in rids]

    # -- observability ------------------------------------------------------
    def _update_gauges(self) -> None:
        reg = self._reg()
        c = self.sched.counts()
        reg.gauge("serve.queue_depth").set(float(self.sched.queue_depth))
        reg.gauge("serve.waiting").set(float(c["waiting"]))
        reg.gauge("serve.running").set(float(c["running"]))
        reg.gauge("serve.kv_occupancy").set(self.cache.occupancy())
        reg.gauge("serve.kv_blocks_used").set(
            float(self.cache.allocator.num_used))
        reg.gauge("serve.shed").set(1.0 if self.should_shed() else 0.0)

    def stats(self) -> Dict[str, Any]:
        """Engine-state snapshot for ``/statusz`` (counts the registry
        cannot derive: pool geometry, scheduler queues, shed state)."""
        c = self.sched.counts()
        return {
            "steps": self.steps,
            "queue_depth": self.sched.queue_depth,
            "waiting": c["waiting"],
            "running": c["running"],
            "finished": c["finished"],
            "preemptions": c["preemptions"],
            "max_seqs": self.max_seqs,
            "max_model_len": self.max_model_len,
            "kv_block_size": self.cache.block_size,
            "kv_blocks": {"total": self.cache.num_blocks,
                          "used": self.cache.allocator.num_used,
                          "occupancy": self.cache.occupancy()},
            "load_shed": {"active": self.should_shed(),
                          "queue_threshold": self.shed_queue_depth},
        }

    def defrag(self) -> bool:
        """Compact the KV pool (see ``PagedKVCache.defrag``)."""
        return self.cache.defrag()

    def start_status_server(self, port: int = 0, host: str = "0.0.0.0"):
        """Expose serving SLOs on the PR 5 monitor; returns the server
        (``.port`` holds the bound port)."""
        from ..observability.monitor import StatusServer
        self.status_server = StatusServer(port=port, host=host,
                                          registry=self._registry,
                                          engine=self).start()
        return self.status_server

    def stop(self) -> None:
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None
