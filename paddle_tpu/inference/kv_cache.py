"""Block-allocated paged KV cache (ISSUE 6) — the serving-side memory
manager.

Training caches (``GPTForCausalLM.make_caches``) preallocate one dense
``(batch, heads, max_len, head_dim)`` buffer per sequence, so a 32-way
decode batch of mostly-short sequences wastes most of its HBM on padding.
The paged design (PAPERS.md: *Ragged Paged Attention*, the TPU-native
paged-KV layout) carves the cache into fixed-size **blocks** shared by
every sequence: a sequence owns a *block table* (list of block ids), the
attention kernel follows the table, and memory waste is bounded by one
partial block per sequence.  That is what lets the continuous-batching
scheduler (``inference/scheduler.py``) admit by a real byte budget and
preempt by freeing a table.

Three layers in this module:

- :class:`BlockAllocator` — host-side free-list over ``num_blocks`` block
  ids: ``alloc / free / defrag`` plus occupancy accounting.  Pure python,
  no device traffic; the scheduler calls it every step.
- :class:`PagedLayerCache` — the **device-side** view one decoder layer
  sees inside a jitted step: flat ``(num_slots, heads, head_dim)`` key
  and value page arrays plus the batch's ``block_tables`` /
  ``seq_lens`` / ``slot_mapping`` int32 arrays.  It is a NamedTuple, so
  it flows through ``jax.jit`` as a pytree with fixed structure — the
  decode step never retraces on cache state.
- :class:`PagedKVCache` — the whole-model container: per-layer page
  arrays + the allocator + per-sequence tables, with the array-building
  helpers the engine uses to assemble fixed-shape step inputs.

Slots: block ``b`` owns flat rows ``[b*block_size, (b+1)*block_size)``
of the page arrays; ``slot = block_table[pos // bs] * bs + pos % bs``.
``SLOT_PAD`` (== ``num_slots``, deliberately out of bounds) marks padded
positions — page writes use ``mode="drop"`` so padding never lands.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp
import jax.tree_util as _tree_util

from ..framework.errors import enforce

__all__ = ["KV_BLOCK_SIZE_ENV", "default_kv_block_size", "BlockAllocator",
           "PagedLayerCache", "PagedKVCache"]

KV_BLOCK_SIZE_ENV = "PTPU_KV_BLOCK_SIZE"


def default_kv_block_size() -> int:
    return int(os.environ.get(KV_BLOCK_SIZE_ENV, "16"))


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size KV blocks.

    All-or-nothing ``alloc``: a request that cannot be fully satisfied
    takes nothing (the scheduler preempts and retries instead of holding
    partial grants across steps — partial holds deadlock a full pool).
    Blocks are handed out lowest-id-first so a freshly started engine
    stays dense without defrag.
    """

    def __init__(self, num_blocks: int, block_size: int):
        enforce(num_blocks > 0 and block_size > 0,
                f"bad pool shape: {num_blocks} blocks x {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._live: set = set()
        # eviction accounting (ISSUE 15): every grant and return counted
        # for the whole pool lifetime — `total_allocs - total_frees ==
        # num_used` is the invariant the leak-freedom drills pin after
        # any interleaving of finish/cancel/deadline/preempt/quarantine
        self.total_allocs = 0
        self.total_frees = 0
        self.high_water = 0

    # -- accounting --------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_blocks - len(self._free)

    def occupancy(self) -> float:
        return self.num_used / self.num_blocks

    def blocks_for_tokens(self, num_tokens: int) -> int:
        """Blocks needed to hold ``num_tokens`` cache entries."""
        return -(-max(0, int(num_tokens)) // self.block_size)

    def can_alloc(self, n: int) -> bool:
        return len(self._free) >= n

    # -- alloc / free ------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` blocks, or None (and take nothing) when the pool
        cannot satisfy the whole request."""
        if n < 0 or len(self._free) < n:
            return None
        got = [self._free.pop() for _ in range(n)]
        self._live.update(got)
        self.total_allocs += len(got)
        self.high_water = max(self.high_water, self.num_used)
        return got

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            enforce(b in self._live, f"double/foreign free of block {b}")
            self._live.discard(b)
            self._free.append(b)
        self.total_frees += len(blocks)
        # keep lowest-id-first hand-out after churn
        self._free.sort(reverse=True)

    def stats(self) -> Dict[str, object]:
        """Lifetime accounting snapshot; ``balanced`` is the
        leak-freedom invariant (allocs minus frees equals live)."""
        return {"num_blocks": self.num_blocks,
                "num_used": self.num_used,
                "num_free": self.num_free,
                "total_allocs": self.total_allocs,
                "total_frees": self.total_frees,
                "high_water": self.high_water,
                "balanced": (self.total_allocs - self.total_frees
                             == self.num_used)}

    # -- defrag ------------------------------------------------------------
    def defrag(self, tables: Dict[object, List[int]]
               ) -> Optional[np.ndarray]:
        """Compact live blocks to ids ``[0, num_used)``.

        ``tables`` maps owner -> block-id list covering every live block;
        tables are renumbered **in place**.  Returns ``perm`` with
        ``perm[new_id] = old_id`` (length ``num_blocks``) for permuting
        the device page arrays, or None when already compact (no device
        traffic needed).  With fixed-size blocks there is no external
        fragmentation — defrag exists to re-densify the pool after heavy
        churn so long-lived pools keep locality (and so snapshots of the
        used prefix stay small).
        """
        live = sorted(self._live)
        referenced = sorted({b for t in tables.values() for b in t})
        enforce(referenced == live,
                f"defrag: tables cover {referenced} but live={live}")
        if live == list(range(len(live))):
            return None
        mapping = {old: new for new, old in enumerate(live)}
        for t in tables.values():
            t[:] = [mapping[b] for b in t]
        spare = [b for b in range(self.num_blocks) if b not in mapping]
        perm = np.empty(self.num_blocks, np.int64)
        for old, new in mapping.items():
            perm[new] = old
        perm[len(live):] = spare
        self._live = set(range(len(live)))
        self._free = list(range(self.num_blocks - 1, len(live) - 1, -1))
        return perm


class PagedLayerCache:
    """One decoder layer's jit-visible paged-cache view.

    ``k_pages`` / ``v_pages``: ``(num_slots + 1, heads, head_dim)`` flat
    page arrays (the +1 row never holds data — the pad-slot sentinel
    lands out of bounds and is dropped, reads never touch it).
    ``block_tables``: ``(batch, max_blocks_per_seq)`` int32 block ids
    (padded rows/entries are 0 — masked out by ``seq_lens``).
    ``seq_lens``: ``(batch,)`` int32 context length *including* the
    tokens written by this call (0 = padding row).
    ``slot_mapping``: ``(batch, chunk)`` int32 flat write slot per new
    token; ``num_slots`` (out of bounds) marks padding.

    Registered as a pytree with ``block_size`` as static aux data, so a
    jitted step sees the arrays as traced leaves but the page geometry
    as a compile-time constant (the attention kernel's grid needs it).
    """

    __slots__ = ("k_pages", "v_pages", "block_tables", "seq_lens",
                 "slot_mapping", "block_size")

    def __init__(self, k_pages, v_pages, block_tables, seq_lens,
                 slot_mapping, block_size: int):
        self.k_pages = k_pages
        self.v_pages = v_pages
        self.block_tables = block_tables
        self.seq_lens = seq_lens
        self.slot_mapping = slot_mapping
        self.block_size = int(block_size)

    def replace(self, **kw) -> "PagedLayerCache":
        fields = {s: getattr(self, s) for s in self.__slots__}
        fields.update(kw)
        return PagedLayerCache(**fields)


def _plc_flatten(c: PagedLayerCache):
    return ((c.k_pages, c.v_pages, c.block_tables, c.seq_lens,
             c.slot_mapping), c.block_size)


def _plc_unflatten(block_size, children):
    return PagedLayerCache(*children, block_size=block_size)


_tree_util.register_pytree_node(PagedLayerCache, _plc_flatten,
                                _plc_unflatten)


class PagedKVCache:
    """Whole-model paged KV store: per-layer page arrays + the allocator
    + per-sequence block tables.

    The engine owns one of these; the scheduler talks to ``allocator``
    and the per-sequence helpers; the jitted step consumes the
    fixed-shape arrays from :meth:`layer_caches` and hands back updated
    page arrays through :meth:`update_pages`.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: Optional[int] = None,
                 dtype=jnp.float32):
        block_size = (default_kv_block_size() if block_size is None
                      else int(block_size))
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = block_size
        self.num_blocks = int(num_blocks)
        self.num_slots = self.num_blocks * block_size
        self.slot_pad = self.num_slots          # OOB sentinel, mode="drop"
        self.dtype = jnp.dtype(dtype)
        self.allocator = BlockAllocator(self.num_blocks, block_size)
        self._tables: Dict[object, List[int]] = {}
        shape = (self.num_slots + 1, self.num_heads, self.head_dim)
        self._pages: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype))
            for _ in range(self.num_layers)]

    # -- per-sequence table management ------------------------------------
    def table(self, seq_id) -> List[int]:
        return self._tables.get(seq_id, [])

    def live_seqs(self) -> List[object]:
        return list(self._tables)

    def ensure_capacity(self, seq_id, num_tokens: int) -> bool:
        """Grow ``seq_id``'s table to cover ``num_tokens`` cache slots;
        False (nothing taken) when the pool cannot supply the growth."""
        table = self._tables.setdefault(seq_id, [])
        need = self.allocator.blocks_for_tokens(num_tokens) - len(table)
        if need <= 0:
            return True
        got = self.allocator.alloc(need)
        if got is None:
            if not table:
                del self._tables[seq_id]
            return False
        table.extend(got)
        return True

    def free_seq(self, seq_id) -> None:
        table = self._tables.pop(seq_id, None)
        if table:
            self.allocator.free(table)

    def slot(self, seq_id, pos: int) -> int:
        """Flat page slot of cache position ``pos`` for ``seq_id``."""
        table = self._tables[seq_id]
        block = pos // self.block_size
        enforce(0 <= block < len(table),
                f"pos {pos} outside {seq_id}'s {len(table)}-block table")
        return table[block] * self.block_size + pos % self.block_size

    def occupancy(self) -> float:
        return self.allocator.occupancy()

    def leak_report(self) -> Dict[str, object]:
        """Eviction-accounting view (ISSUE 15): allocator lifetime
        counters plus the table-coverage cross-check.  A nonzero
        ``leaked_blocks`` means some blocks are marked used but no
        sequence's table covers them — exactly the state a missed
        eviction path (cancel/deadline/quarantine) would leave."""
        report = self.allocator.stats()
        tabled = sum(len(t) for t in self._tables.values())
        report["live_seqs"] = len(self._tables)
        report["tabled_blocks"] = tabled
        report["leaked_blocks"] = int(report["num_used"]) - tabled
        return report

    # -- fixed-shape step inputs ------------------------------------------
    def table_array(self, seq_ids: Sequence[object],
                    max_blocks: int) -> np.ndarray:
        """``(len(seq_ids), max_blocks)`` int32 block-table matrix; rows
        of absent/short tables are 0-padded (masked by seq_lens)."""
        out = np.zeros((len(seq_ids), max_blocks), np.int32)
        for i, sid in enumerate(seq_ids):
            t = self._tables.get(sid, [])
            enforce(len(t) <= max_blocks,
                    f"{sid}: {len(t)} blocks > table width {max_blocks}")
            out[i, :len(t)] = t
        return out

    def slot_array(self, seq_ids: Sequence[object],
                   starts: Sequence[int], chunk: int) -> np.ndarray:
        """``(len(seq_ids), chunk)`` write-slot matrix for tokens at
        positions ``starts[i] .. starts[i]+chunk-1``; positions past the
        sequence's table get the OOB pad sentinel."""
        out = np.full((len(seq_ids), chunk), self.slot_pad, np.int32)
        for i, (sid, start) in enumerate(zip(seq_ids, starts)):
            if start < 0:        # padding row
                continue
            table = self._tables.get(sid, [])
            cap = len(table) * self.block_size
            for j in range(chunk):
                pos = start + j
                if pos < cap:
                    out[i, j] = (table[pos // self.block_size]
                                 * self.block_size
                                 + pos % self.block_size)
        return out

    def layer_caches(self, block_tables: np.ndarray, seq_lens: np.ndarray,
                     slot_mapping: np.ndarray) -> List[PagedLayerCache]:
        bt = jnp.asarray(block_tables, jnp.int32)
        sl = jnp.asarray(seq_lens, jnp.int32)
        sm = jnp.asarray(slot_mapping, jnp.int32)
        return [PagedLayerCache(k, v, bt, sl, sm,
                                block_size=self.block_size)
                for (k, v) in self._pages]

    def update_pages(self, new_caches: Sequence[PagedLayerCache]) -> None:
        enforce(len(new_caches) == self.num_layers,
                f"{len(new_caches)} layer caches for {self.num_layers} "
                "layers")
        self._pages = [(c.k_pages, c.v_pages) for c in new_caches]

    # -- defrag ------------------------------------------------------------
    def defrag(self) -> bool:
        """Compact the pool (see :meth:`BlockAllocator.defrag`) and
        permute the device page arrays to match.  Returns True when a
        permutation was applied."""
        perm = self.allocator.defrag(self._tables)
        if perm is None:
            return False
        slot_perm = (perm[:, None] * self.block_size
                     + np.arange(self.block_size)[None, :]).reshape(-1)
        # the sentinel row stays the sentinel row
        slot_perm = np.concatenate([slot_perm, [self.num_slots]])
        idx = jnp.asarray(slot_perm)
        self._pages = [(jnp.take(k, idx, axis=0), jnp.take(v, idx, axis=0))
                       for (k, v) in self._pages]
        return True
