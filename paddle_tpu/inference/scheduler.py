"""Continuous-batching scheduler (ISSUE 6).

Static batching pads every request to the batch's slowest member; a
serving engine under ragged traffic wastes most of its step time on
finished or not-yet-started rows.  Continuous batching re-forms the
batch **every step**: finished sequences leave immediately, waiting
sequences are admitted the moment KV blocks free up, and the decode
batch only ever contains live rows (PAPERS.md: *ClusterFusion++*'s
per-step decode unit; the vLLM-style admit/evict loop on top).

This module is the pure-host half: no jax, no device traffic — just
sequence state machines and block accounting against
``kv_cache.BlockAllocator``.  That makes every policy decision unit
testable with a fake clock and a tiny pool (``tests/test_serving.py``).

Sequence lifecycle::

    WAITING --admit(prefill)--> RUNNING --eos/max_tokens--> FINISHED
       ^                          |                            ^
       +------- PREEMPTED <-- OOM on next-token block          |
       |                                                       |
       +--- cancel / deadline / poisoned / spilled (evict) ----+

Terminal reasons beyond ``eos`` / ``max_new_tokens`` (ISSUE 15):
``cancelled`` and ``deadline`` land through the engine's between-steps
reaper, ``poisoned`` through the fault-boundary quarantine, ``spilled``
through graceful drain.  All of them go through :meth:`evict`, which
frees the sequence's blocks from *any* live state — waiting sequences
hold no blocks, but removing them from the queue here keeps the
lifecycle single-exit.

- **Admission** is by KV-block budget: a sequence is admitted only when
  the allocator can hold its whole prefill context *now* (all-or-nothing
  — partial holds deadlock a full pool).  Preempted sequences re-admit
  ahead of new arrivals (front of queue) so preemption cannot starve a
  request forever.
- **Preemption** frees the victim's entire table (recompute-style: its
  tokens so far become the new, longer prefill prompt).  Victims are
  picked newest-admitted-first, so the oldest running sequence always
  survives and finishes — the loop cannot livelock.
- **Prefill/decode interleaving**: each ``schedule()`` returns either
  ONE prefill (padded to a power-of-two bucket) or one decode batch over
  all running sequences (fixed ``max_seqs`` × 1 shape).  Step shapes
  therefore come from a small closed set, and the PR 4 compile tracker
  sees exactly one compilation per bucket — no retrace storms from
  ragged traffic.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence

from ..framework.errors import enforce
from .kv_cache import PagedKVCache

__all__ = ["WAITING", "RUNNING", "PREEMPTED", "FINISHED", "SequenceState",
           "StepPlan", "ContinuousBatchingScheduler", "prefill_bucket"]

WAITING = "waiting"
RUNNING = "running"
PREEMPTED = "preempted"
FINISHED = "finished"

_MIN_BUCKET = 8


def prefill_bucket(length: int, cap: int) -> int:
    """Smallest power-of-two >= ``length`` (floor ``_MIN_BUCKET``),
    capped at ``cap`` — the closed set of prefill step shapes."""
    enforce(0 < length <= cap, f"prefill length {length} outside (0, {cap}]")
    b = _MIN_BUCKET
    while b < length:
        b *= 2
    return min(b, cap)


@dataclasses.dataclass
class SequenceState:
    """One request's scheduling state.  Token bookkeeping:

    - ``prompt``: the submitted prompt ids (never mutated);
    - ``output``: every token generated so far (streamed to the caller);
    - ``context()``: the tokens whose KV must be cached before the next
      decode step — prompt + generated output *except* ``pending`` (the
      newest sampled token, whose KV is written by the step that feeds
      it back in);
    - ``computed_len``: cache entries currently on device for this
      sequence (0 after preemption — recompute rebuilds them).
    """
    request_id: str
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival: float = 0.0
    on_token: Optional[Callable] = None
    capture_logits: bool = False

    # request-lifecycle guard (ISSUE 15): absolute clock() times — the
    # engine computes them from submit()'s relative deadline_ms knobs
    deadline: Optional[float] = None
    ttft_deadline: Optional[float] = None
    cancelled: bool = False

    state: str = WAITING
    output: List[int] = dataclasses.field(default_factory=list)
    pending: Optional[int] = None       # sampled, KV not yet cached
    computed_len: int = 0
    logits: List = dataclasses.field(default_factory=list)
    first_token_time: Optional[float] = None
    last_token_time: Optional[float] = None
    finish_reason: Optional[str] = None
    preemptions: int = 0

    # request tracing (ISSUE 18): the fleet-wide trace context.
    # ``trace_id`` is minted by the router (or the engine for direct
    # submissions) and rides every ``trace.span`` this sequence emits;
    # ``resume_why`` marks a recompute's cause ("preempt" / "failover" /
    # "migration") so the next prefill span is attributed to it;
    # ``trace_enqueued`` is the wall time the sequence (re-)entered the
    # waiting queue — the start of its next queue span.
    trace_id: Optional[str] = None
    resume_why: Optional[str] = None
    trace_enqueued: Optional[float] = None

    def context(self) -> List[int]:
        """Tokens needing cached KV before the next decode step.
        ``pending`` (invariantly ``output[-1]`` when set) is excluded:
        its KV is written by the decode step that consumes it."""
        toks = list(self.prompt) + list(self.output)
        return toks[:-1] if self.pending is not None else toks

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.output)

    def should_finish(self) -> Optional[str]:
        if (self.eos_token_id is not None and self.output
                and self.output[-1] == self.eos_token_id):
            return "eos"
        if len(self.output) >= self.max_new_tokens:
            return "max_new_tokens"
        return None


@dataclasses.dataclass
class StepPlan:
    """What the engine should run this step."""
    kind: str                               # "prefill" | "decode" | "idle"
    seqs: List[SequenceState]
    bucket: int = 0                         # prefill pad length
    preempted: List[SequenceState] = dataclasses.field(default_factory=list)


class ContinuousBatchingScheduler:
    """Admission / preemption / interleaving policy over a
    :class:`PagedKVCache`'s allocator.

    The engine loop is ``plan = schedule(); run(plan); feedback via
    mark_prefilled / mark_decoded / complete``.  The scheduler owns the
    queues and the block accounting; it never touches device arrays.
    """

    def __init__(self, cache: PagedKVCache, max_seqs: int,
                 max_model_len: int, clock: Callable[[], float] = time.time):
        enforce(max_seqs >= 1, "max_seqs must be >= 1")
        self.cache = cache
        self.max_seqs = int(max_seqs)
        self.max_model_len = int(max_model_len)
        self.max_blocks_per_seq = cache.allocator.blocks_for_tokens(
            self.max_model_len)
        self.clock = clock
        self.waiting: Deque[SequenceState] = deque()
        self.running: List[SequenceState] = []
        self.finished: Dict[str, SequenceState] = {}
        self.preemptions = 0
        # drain gate (ISSUE 15): closed admission still lets preempted
        # sequences (anything that already produced output) re-admit —
        # drain must finish started work, only fresh arrivals wait out
        self.admission_open = True

    # -- intake ------------------------------------------------------------
    def submit(self, seq: SequenceState) -> None:
        worst = len(seq.prompt) + seq.max_new_tokens
        enforce(worst <= self.max_model_len,
                f"{seq.request_id}: prompt {len(seq.prompt)} + "
                f"max_new {seq.max_new_tokens} exceeds max_model_len "
                f"{self.max_model_len}")
        enforce(self.cache.allocator.blocks_for_tokens(worst)
                <= self.cache.num_blocks,
                f"{seq.request_id}: needs more KV blocks than the whole "
                f"pool holds ({self.cache.num_blocks})")
        enforce(len(seq.prompt) >= 1, f"{seq.request_id}: empty prompt")
        seq.state = WAITING
        seq.arrival = seq.arrival or float(self.clock())
        if seq.trace_enqueued is None:
            seq.trace_enqueued = seq.arrival
        self.waiting.append(seq)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- the per-step decision ---------------------------------------------
    def schedule(self) -> StepPlan:
        """Pick this step's work: one prefill when a waiting sequence
        fits the block budget and a batch slot, else one decode batch
        over the running set (preempting on next-token OOM), else idle.
        Prefill-first keeps TTFT low under load; decode throughput costs
        at most one interleaved step per admission."""
        plan_preempted: List[SequenceState] = []

        if (self.waiting and len(self.running) < self.max_seqs
                and (self.admission_open or self.waiting[0].output)):
            seq = self.waiting[0]
            ctx = len(seq.context())
            need = self.cache.allocator.blocks_for_tokens(ctx)
            if (need <= self.max_blocks_per_seq
                    and self.cache.ensure_capacity(seq.request_id, ctx)):
                self.waiting.popleft()
                seq.state = RUNNING
                self.running.append(seq)
                bucket = prefill_bucket(ctx, self.max_model_len)
                return StepPlan("prefill", [seq], bucket=bucket)

        if self.running:
            survivors: List[SequenceState] = []
            for seq in list(self.running):
                if seq.state != RUNNING:
                    continue      # already preempted as a victim above
                # a decode step writes the pending token's KV at position
                # computed_len — grow the table to cover it, preempting
                # newest-admitted sequences on OOM
                while not self.cache.ensure_capacity(
                        seq.request_id, seq.computed_len + 1):
                    victim = self.running[-1]
                    self._preempt(victim)
                    plan_preempted.append(victim)
                    if victim is seq:
                        break
                else:
                    survivors.append(seq)
            if survivors:
                return StepPlan("decode", survivors,
                                preempted=plan_preempted)
        return StepPlan("idle", [], preempted=plan_preempted)

    def _preempt(self, seq: SequenceState) -> None:
        self.running.remove(seq)
        self.cache.free_seq(seq.request_id)
        seq.computed_len = 0
        seq.state = PREEMPTED
        seq.preemptions += 1
        self.preemptions += 1
        # trace attribution (ISSUE 18): the wait + re-prefill this
        # preemption causes belongs to the preemption, not to "queue"
        seq.resume_why = "preempt"
        seq.trace_enqueued = float(self.clock())
        # head of the queue: preempted work re-admits before new arrivals
        self.waiting.appendleft(seq)

    def preempt_all(self) -> List[SequenceState]:
        """Evict every running sequence back to the queue (recompute) —
        the engine's hang-recovery path.  Device-side work in flight is
        abandoned; host state stays consistent because engine feedback
        (``mark_*``) only lands after a step returns.  Newest-first so
        re-admission replays in the original admission order."""
        victims = list(reversed(self.running))
        for seq in victims:
            self._preempt(seq)
        return victims

    # -- engine feedback ---------------------------------------------------
    def mark_prefilled(self, seq: SequenceState) -> None:
        seq.computed_len = len(seq.context())

    def mark_decoded(self, seq: SequenceState) -> None:
        seq.computed_len += 1

    def complete(self, seq: SequenceState, reason: str) -> None:
        """Evict a finished sequence: free its blocks immediately so the
        next schedule() can admit into the reclaimed space."""
        self.evict(seq, reason)

    def evict(self, seq: SequenceState, reason: str) -> None:
        """Terminal eviction from ANY live state — finish, cancel,
        deadline, quarantine and drain-spill all exit through here:
        remove the sequence from whichever queue holds it, free its
        blocks, record the reason, file it under ``finished``."""
        if seq in self.running:
            self.running.remove(seq)
        else:
            try:
                self.waiting.remove(seq)
            except ValueError:
                pass
        self.cache.free_seq(seq.request_id)
        seq.state = FINISHED
        seq.finish_reason = reason
        self.finished[seq.request_id] = seq

    # -- introspection ------------------------------------------------------
    def counts(self) -> Dict[str, int]:
        return {"waiting": len(self.waiting),
                "running": len(self.running),
                "finished": len(self.finished),
                "preemptions": self.preemptions}
