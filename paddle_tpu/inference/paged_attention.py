"""Ragged paged-attention decode (ISSUE 6).

The serving engine's decode batch is **ragged**: every sequence in the
batch attends to a different-length context, and that context lives in
shared fixed-size KV blocks addressed through a per-sequence block table
(``inference/kv_cache.py``).  This module computes, for a batch of
single-token queries,

    out[b] = softmax(q[b] · K[b]^T * scale) · V[b]

where ``K[b]/V[b]`` are gathered by ``block_tables[b]`` and truncated at
``seq_lens[b]`` — the TPU-native layout of PAPERS.md's *Ragged Paged
Attention* (block-tabled KV, ragged decode batches).

Two implementations behind one routing entry point:

- :func:`paged_attention_pallas` — the kernel, built on the same Pallas
  surface as ``ops/flash_attention.py`` (shared ``_dot`` precision rule,
  lane-broadcast statistics, online-softmax recurrence).  Grid is
  ``(batch, heads, max_blocks)`` with the block table and sequence
  lengths as **scalar-prefetch** operands, so the k/v BlockSpec index
  maps dereference the table and Mosaic DMAs exactly one KV block per
  grid step — per-step VMEM residency is O(block_size · head_dim)
  regardless of pool size, and a block past ``seq_lens[b]`` is skipped
  (its flash state update is predicated off; the redundant page-0 DMA it
  still costs is the ragged tax also paid by the upstream TPU kernel).
- :func:`paged_attention_reference` — a pure ``jax.numpy``/``lax``
  gather-softmax with identical semantics.  It is the default off-TPU
  (interpret-mode Pallas is orders slower than XLA CPU), which is what
  lets the tier-1 CPU suite run the full serving path; it is also the
  numerics oracle the kernel is tested against.

Routing: :func:`paged_attention` picks the kernel on a TPU backend, the
reference elsewhere; ``PTPU_PAGED_KERNEL=pallas|reference`` forces one
(the CPU kernel test forces ``pallas`` to run it under interpret).

Decode is memory-bound, so the win is never FLOPs — it is that the
gather never materializes a per-sequence contiguous KV copy in HBM.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..framework.errors import enforce
from ..ops.flash_attention import _dot, _interpret, _LANES, _NEG_INF

__all__ = ["paged_attention", "paged_attention_pallas",
           "paged_attention_reference"]

PAGED_KERNEL_ENV = "PTPU_PAGED_KERNEL"


def _check_shapes(q, k_pages, v_pages, block_tables, seq_lens,
                  block_size: int):
    b, h, d = q.shape
    enforce(k_pages.ndim == 3 and k_pages.shape == v_pages.shape,
            f"page shape mismatch: k={k_pages.shape} v={v_pages.shape}")
    enforce(k_pages.shape[1] == h and k_pages.shape[2] == d,
            f"pages {k_pages.shape} disagree with q {q.shape}")
    enforce(block_tables.shape[0] == b and seq_lens.shape == (b,),
            f"tables {block_tables.shape} / lens {seq_lens.shape} "
            f"disagree with batch {b}")
    num_slots = k_pages.shape[0] - 1    # trailing sentinel row
    enforce(num_slots % block_size == 0,
            f"{num_slots} slots not a multiple of block_size "
            f"{block_size}")


# ---------------------------------------------------------------------------
# Reference: gather + masked softmax (the CPU serving path and the oracle)
# ---------------------------------------------------------------------------
def paged_attention_reference(q, k_pages, v_pages, block_tables, seq_lens,
                              block_size: int,
                              scale: Optional[float] = None):
    """Pure-jax ragged paged attention over ``(batch, heads, head_dim)``
    single-token queries.  A row with ``seq_lens[b] == 0`` (a padding
    row of the decode batch) returns zeros."""
    _check_shapes(q, k_pages, v_pages, block_tables, seq_lens, block_size)
    b, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    max_ctx = block_tables.shape[1] * block_size

    def per_seq(qb, table, ln):
        # (T,) block ids -> (T*bs,) flat slots -> gathered (L, h, d)
        slots = (table[:, None] * block_size
                 + jnp.arange(block_size)[None, :]).reshape(-1)
        k = jnp.take(k_pages, slots, axis=0)       # (L, h, d)
        v = jnp.take(v_pages, slots, axis=0)
        s = jnp.einsum("hd,lhd->hl", qb.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        valid = (jnp.arange(max_ctx) < ln)[None, :]
        s = jnp.where(valid, s, _NEG_INF)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.where(valid, jnp.exp(s - m), 0.0)
        l = jnp.sum(p, axis=1, keepdims=True)
        out = jnp.einsum("hl,lhd->hd", p, v.astype(jnp.float32))
        return (out / jnp.maximum(l, 1e-30)).astype(q.dtype)

    return jax.vmap(per_seq)(q, block_tables, seq_lens)


# ---------------------------------------------------------------------------
# Pallas kernel: one KV block per grid step, table-driven DMA
# ---------------------------------------------------------------------------
def _paged_decode_kernel(lens_ref, table_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale, block_size):
    # grid (batch, heads, max_blocks): the index maps already steered this
    # step's k/v refs to block_tables[b, t] via scalar prefetch; the flash
    # (m, l, acc) state lives in VMEM scratch across the innermost t steps
    # (same recurrence as ops/flash_attention._fwd_kernel).
    b = pl.program_id(0)
    t = pl.program_id(2)
    num_t = pl.num_programs(2)
    kv_len = lens_ref[b]

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    @pl.when(t * block_size < kv_len)
    def _step():
        q = q_ref[0, 0][None, :]                       # (1, d)
        k = k_ref[0, :, 0, :]                          # (bs, d)
        v = v_ref[0, :, 0, :]
        s = _dot(q, k, (((1,), (1,)), ((), ()))) * scale   # (1, bs)
        cols = t * block_size + lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s = jnp.where(cols < kv_len, s, _NEG_INF)
        m_prev = m_scr[...]                            # (1, _LANES)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
        p = jnp.where(cols < kv_len,
                      jnp.exp(s - m_new[:, :1]), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = alpha * l_scr[...] + jnp.sum(p, axis=1)[:, None]
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + _dot(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())))
        m_scr[...] = m_new

    @pl.when(t == num_t - 1)
    def _finalize():
        # kv_len == 0 (a padding row) never entered _step: l stays 0 and
        # the guarded divide returns zeros, matching the reference
        o_ref[0, 0] = (acc_scr[...][0]
                       / jnp.maximum(l_scr[...][0, :1], 1e-30)
                       ).astype(o_ref.dtype)


def paged_attention_pallas(q, k_pages, v_pages, block_tables, seq_lens,
                           block_size: int,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """The table-driven Pallas kernel (interpret-mode off TPU)."""
    from jax.experimental.pallas import tpu as pltpu
    _check_shapes(q, k_pages, v_pages, block_tables, seq_lens, block_size)
    b, h, d = q.shape
    max_blocks = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5
    # pages reshaped to (num_blocks, block_size, h, d) so one grid step's
    # BlockSpec is exactly one block of one head; the sentinel row is
    # sliced off (reads never need it)
    num_slots = k_pages.shape[0] - 1
    kp = k_pages[:num_slots].reshape(-1, block_size, h, d)
    vp = v_pages[:num_slots].reshape(-1, block_size, h, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,            # seq_lens, block_tables
        grid=(b, h, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda bi, hi, ti, lens, tbl:
                         (bi, hi, 0)),                       # q
            pl.BlockSpec((1, block_size, 1, d),
                         lambda bi, hi, ti, lens, tbl:
                         (tbl[bi, ti], 0, hi, 0)),           # k block
            pl.BlockSpec((1, block_size, 1, d),
                         lambda bi, hi, ti, lens, tbl:
                         (tbl[bi, ti], 0, hi, 0)),           # v block
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda bi, hi, ti, lens, tbl:
                               (bi, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, _LANES), jnp.float32),   # m
            pltpu.VMEM((1, _LANES), jnp.float32),   # l
            pltpu.VMEM((1, d), jnp.float32),        # acc
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, scale=float(scale),
                               block_size=int(block_size))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(jnp.asarray(seq_lens, jnp.int32),
      jnp.asarray(block_tables, jnp.int32), q, kp, vp)
    return out


# ---------------------------------------------------------------------------
# Routing
# ---------------------------------------------------------------------------
def paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                    block_size: int, scale: Optional[float] = None):
    """Ragged paged-attention decode for ``q`` of shape
    ``(batch, heads, head_dim)`` (one query token per sequence).

    TPU backends take the Pallas kernel; everything else takes the lax
    reference (same numerics) so the CPU test mesh exercises the full
    serving path at XLA speed.  ``PTPU_PAGED_KERNEL`` forces a path.
    """
    forced = os.environ.get(PAGED_KERNEL_ENV, "").strip().lower()
    if forced in ("pallas", "kernel", "1"):
        return paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                      seq_lens, block_size, scale)
    if forced in ("reference", "lax", "0"):
        return paged_attention_reference(q, k_pages, v_pages, block_tables,
                                         seq_lens, block_size, scale)
    if jax.default_backend() == "tpu":
        return paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                      seq_lens, block_size, scale)
    return paged_attention_reference(q, k_pages, v_pages, block_tables,
                                     seq_lens, block_size, scale)
