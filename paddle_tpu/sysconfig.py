"""paddle.sysconfig parity (reference python/paddle/sysconfig.py)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include() -> str:
    """Directory of framework headers (reference :20).  The TPU build has
    no C++ op headers to compile against; custom host ops use the C ABI
    (utils/cpp_extension), so this returns the native-helpers dir."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "io", "_native")


def get_lib() -> str:
    """Directory of shared libraries (reference :37): built native
    helpers (e.g. the dataloader shm ring) live beside their sources."""
    return get_include()
