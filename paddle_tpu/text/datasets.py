"""Text datasets (reference: python/paddle/text/datasets/{imdb,imikolov,
uci_housing,conll05,movielens,wmt14,wmt16}.py).

The reference downloads corpora at construction; this environment has no
egress, so each dataset generates a deterministic synthetic stand-in with
the same item schema — the gating pattern of paddle_tpu.vision.datasets.
MNIST.  ``Imdb`` and ``UCIHousing`` additionally accept an explicit local
``data_file`` (tar / whitespace table); the other corpora's wire formats
are not parsed here — passing ``data_file`` to them raises rather than
silently training on synthetic data.
"""
from __future__ import annotations

import os
import tarfile
from typing import Callable, Optional

import numpy as np

from ..framework.errors import enforce
from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens"]


class Imdb(Dataset):
    """Binary sentiment classification; items are (word-id sequence, label)
    (reference text/datasets/imdb.py)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, synthetic_size: Optional[int] = None,
                 vocab_size: int = 5000, seq_len: int = 64):
        enforce(mode in ("train", "test"), "mode must be train|test")
        self.mode = mode
        self.word_idx = {f"w{i}": i for i in range(vocab_size)}
        if data_file is not None:
            enforce(os.path.exists(data_file),
                    f"Imdb data_file {data_file!r} does not exist")
            self.docs, self.labels = self._load_tar(data_file, mode)
            return
        n = synthetic_size or (2048 if mode == "train" else 256)
        rng = np.random.RandomState(3 if mode == "train" else 5)
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        # class-conditional unigram bias makes the task learnable
        self.docs = []
        for y in self.labels:
            lo = 0 if y == 0 else vocab_size // 2
            self.docs.append(rng.randint(
                lo, lo + vocab_size // 2, seq_len).astype(np.int64))

    def _load_tar(self, path: str, mode: str):
        import zlib
        docs, labels = [], []
        vocab = len(self.word_idx)
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                if f"{mode}/pos" in member.name:
                    y = 1
                elif f"{mode}/neg" in member.name:
                    y = 0
                else:
                    continue
                data = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").split()
                # crc32 is stable across processes (builtin hash() is
                # randomized by PYTHONHASHSEED) — reload-safe word ids
                docs.append(np.asarray(
                    [zlib.crc32(w.encode()) % vocab for w in data],
                    np.int64))
                labels.append(y)
        return docs, np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset; items are n-token windows
    (reference text/datasets/imikolov.py)."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50,
                 synthetic_size: Optional[int] = None,
                 vocab_size: int = 2000):
        enforce(data_file is None,
                "Imikolov corpus parsing is not supported in this "
                "environment; omit data_file to use the synthetic stream")
        self.window_size = window_size
        n = synthetic_size or (4096 if mode == "train" else 512)
        rng = np.random.RandomState(11 if mode == "train" else 13)
        # markov-ish stream: next word depends on previous (learnable)
        stream = np.empty(n + window_size, np.int64)
        stream[0] = rng.randint(vocab_size)
        for i in range(1, len(stream)):
            stream[i] = (stream[i - 1] * 31 + 7) % vocab_size \
                if rng.rand() < 0.8 else rng.randint(vocab_size)
        self.windows = np.lib.stride_tricks.sliding_window_view(
            stream, window_size)[:n]

    def __getitem__(self, idx):
        return self.windows[idx]

    def __len__(self):
        return len(self.windows)


class UCIHousing(Dataset):
    """13-feature housing regression (reference text/datasets/
    uci_housing.py); items are (features, price)."""

    FEATURE_DIM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 synthetic_size: Optional[int] = None):
        if data_file is not None:
            enforce(os.path.exists(data_file),
                    f"UCIHousing data_file {data_file!r} does not exist")
            raw = np.loadtxt(data_file).astype(np.float32)
            # canonical 80/20 split by mode — train and test must differ
            cut = int(len(raw) * 0.8)
            raw = raw[:cut] if mode == "train" else raw[cut:]
        else:
            n = synthetic_size or (404 if mode == "train" else 102)
            rng = np.random.RandomState(17 if mode == "train" else 19)
            x = rng.randn(n, self.FEATURE_DIM).astype(np.float32)
            w = np.linspace(-2, 2, self.FEATURE_DIM).astype(np.float32)
            y = x @ w + 0.1 * rng.randn(n).astype(np.float32)
            raw = np.concatenate([x, y[:, None]], axis=1)
        self.features = raw[:, :-1]
        self.prices = raw[:, -1:]

    def __getitem__(self, idx):
        return self.features[idx], self.prices[idx]

    def __len__(self):
        return len(self.features)


class Conll05st(Dataset):
    """SRL sequence-labeling schema: (word_ids, predicate_ids, label_ids)
    (reference text/datasets/conll05.py)."""

    NUM_LABELS = 67

    def __init__(self, data_file: Optional[str] = None,
                 synthetic_size: Optional[int] = None, seq_len: int = 30,
                 vocab_size: int = 5000):
        enforce(data_file is None,
                "Conll05st corpus parsing is not supported in this "
                "environment; omit data_file for the synthetic schema")
        n = synthetic_size or 1024
        rng = np.random.RandomState(23)
        self.words = rng.randint(0, vocab_size,
                                 (n, seq_len)).astype(np.int64)
        self.predicates = rng.randint(0, vocab_size, (n,)).astype(np.int64)
        self.labels = rng.randint(0, self.NUM_LABELS,
                                  (n, seq_len)).astype(np.int64)

    def __getitem__(self, idx):
        return self.words[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return len(self.words)


class Movielens(Dataset):
    """Rating prediction: (user_id, age, job, movie_id, category, rating)
    (reference text/datasets/movielens.py)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 synthetic_size: Optional[int] = None,
                 num_users: int = 943, num_movies: int = 1682):
        enforce(data_file is None,
                "Movielens corpus parsing is not supported in this "
                "environment; omit data_file for the synthetic schema")
        n = synthetic_size or (8192 if mode == "train" else 1024)
        rng = np.random.RandomState(29 if mode == "train" else 31)
        self.users = rng.randint(0, num_users, n).astype(np.int64)
        self.movies = rng.randint(0, num_movies, n).astype(np.int64)
        self.ages = rng.randint(18, 70, n).astype(np.int64)
        self.jobs = rng.randint(0, 21, n).astype(np.int64)
        self.categories = rng.randint(0, 18, n).astype(np.int64)
        # rating = user-bias + movie-bias + noise, clipped to 1..5
        ub = rng.randn(num_users)
        mb = rng.randn(num_movies)
        r = 3 + ub[self.users] + mb[self.movies] + 0.3 * rng.randn(n)
        self.ratings = np.clip(np.round(r), 1, 5).astype(np.float32)

    def __getitem__(self, idx):
        return (self.users[idx], self.ages[idx], self.jobs[idx],
                self.movies[idx], self.categories[idx], self.ratings[idx])

    def __len__(self):
        return len(self.users)
