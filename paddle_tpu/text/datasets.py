"""Text datasets (reference: python/paddle/text/datasets/{imdb,imikolov,
uci_housing,conll05,movielens,wmt14,wmt16}.py).

The reference downloads corpora at construction; this environment has no
egress, so each dataset generates a deterministic synthetic stand-in with
the same item schema — the gating pattern of paddle_tpu.vision.datasets.
MNIST.  ``Imdb`` and ``UCIHousing`` additionally accept an explicit local
``data_file`` (tar / whitespace table); the other corpora's wire formats
are not parsed here — passing ``data_file`` to them raises rather than
silently training on synthetic data.
"""
from __future__ import annotations

import os
import tarfile
from typing import Callable, Optional

import numpy as np

from ..framework.errors import enforce
from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens",
           "MovieInfo", "UserInfo", "WMT14", "WMT16"]


class Imdb(Dataset):
    """Binary sentiment classification; items are (word-id sequence, label)
    (reference text/datasets/imdb.py)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, synthetic_size: Optional[int] = None,
                 vocab_size: int = 5000, seq_len: int = 64):
        enforce(mode in ("train", "test"), "mode must be train|test")
        self.mode = mode
        self.word_idx = {f"w{i}": i for i in range(vocab_size)}
        if data_file is not None:
            enforce(os.path.exists(data_file),
                    f"Imdb data_file {data_file!r} does not exist")
            self.docs, self.labels = self._load_tar(data_file, mode)
            return
        n = synthetic_size or (2048 if mode == "train" else 256)
        rng = np.random.RandomState(3 if mode == "train" else 5)
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        # class-conditional unigram bias makes the task learnable
        self.docs = []
        for y in self.labels:
            lo = 0 if y == 0 else vocab_size // 2
            self.docs.append(rng.randint(
                lo, lo + vocab_size // 2, seq_len).astype(np.int64))

    def _load_tar(self, path: str, mode: str):
        import zlib
        docs, labels = [], []
        vocab = len(self.word_idx)
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                if f"{mode}/pos" in member.name:
                    y = 1
                elif f"{mode}/neg" in member.name:
                    y = 0
                else:
                    continue
                data = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").split()
                # crc32 is stable across processes (builtin hash() is
                # randomized by PYTHONHASHSEED) — reload-safe word ids
                docs.append(np.asarray(
                    [zlib.crc32(w.encode()) % vocab for w in data],
                    np.int64))
                labels.append(y)
        return docs, np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset; items are n-token windows
    (reference text/datasets/imikolov.py)."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50,
                 synthetic_size: Optional[int] = None,
                 vocab_size: int = 2000):
        enforce(data_file is None,
                "Imikolov corpus parsing is not supported in this "
                "environment; omit data_file to use the synthetic stream")
        self.window_size = window_size
        n = synthetic_size or (4096 if mode == "train" else 512)
        rng = np.random.RandomState(11 if mode == "train" else 13)
        # markov-ish stream: next word depends on previous (learnable)
        stream = np.empty(n + window_size, np.int64)
        stream[0] = rng.randint(vocab_size)
        for i in range(1, len(stream)):
            stream[i] = (stream[i - 1] * 31 + 7) % vocab_size \
                if rng.rand() < 0.8 else rng.randint(vocab_size)
        self.windows = np.lib.stride_tricks.sliding_window_view(
            stream, window_size)[:n]

    def __getitem__(self, idx):
        return self.windows[idx]

    def __len__(self):
        return len(self.windows)


class UCIHousing(Dataset):
    """13-feature housing regression (reference text/datasets/
    uci_housing.py); items are (features, price)."""

    FEATURE_DIM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 synthetic_size: Optional[int] = None):
        if data_file is not None:
            enforce(os.path.exists(data_file),
                    f"UCIHousing data_file {data_file!r} does not exist")
            raw = np.loadtxt(data_file).astype(np.float32)
            # canonical 80/20 split by mode — train and test must differ
            cut = int(len(raw) * 0.8)
            raw = raw[:cut] if mode == "train" else raw[cut:]
        else:
            n = synthetic_size or (404 if mode == "train" else 102)
            rng = np.random.RandomState(17 if mode == "train" else 19)
            x = rng.randn(n, self.FEATURE_DIM).astype(np.float32)
            w = np.linspace(-2, 2, self.FEATURE_DIM).astype(np.float32)
            y = x @ w + 0.1 * rng.randn(n).astype(np.float32)
            raw = np.concatenate([x, y[:, None]], axis=1)
        self.features = raw[:, :-1]
        self.prices = raw[:, -1:]

    def __getitem__(self, idx):
        return self.features[idx], self.prices[idx]

    def __len__(self):
        return len(self.features)


class Conll05st(Dataset):
    """SRL sequence-labeling schema: (word_ids, predicate_ids, label_ids)
    (reference text/datasets/conll05.py)."""

    NUM_LABELS = 67

    def __init__(self, data_file: Optional[str] = None,
                 synthetic_size: Optional[int] = None, seq_len: int = 30,
                 vocab_size: int = 5000):
        enforce(data_file is None,
                "Conll05st corpus parsing is not supported in this "
                "environment; omit data_file for the synthetic schema")
        n = synthetic_size or 1024
        rng = np.random.RandomState(23)
        self.words = rng.randint(0, vocab_size,
                                 (n, seq_len)).astype(np.int64)
        self.predicates = rng.randint(0, vocab_size, (n,)).astype(np.int64)
        self.labels = rng.randint(0, self.NUM_LABELS,
                                  (n, seq_len)).astype(np.int64)

    def __getitem__(self, idx):
        return self.words[idx], self.predicates[idx], self.labels[idx]

    def __len__(self):
        return len(self.words)


class Movielens(Dataset):
    """Rating prediction: (user_id, age, job, movie_id, category, rating)
    (reference text/datasets/movielens.py)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 synthetic_size: Optional[int] = None,
                 num_users: int = 943, num_movies: int = 1682):
        enforce(data_file is None,
                "Movielens corpus parsing is not supported in this "
                "environment; omit data_file for the synthetic schema")
        n = synthetic_size or (8192 if mode == "train" else 1024)
        rng = np.random.RandomState(29 if mode == "train" else 31)
        self.users = rng.randint(0, num_users, n).astype(np.int64)
        self.movies = rng.randint(0, num_movies, n).astype(np.int64)
        self.ages = rng.randint(18, 70, n).astype(np.int64)
        self.jobs = rng.randint(0, 21, n).astype(np.int64)
        self.categories = rng.randint(0, 18, n).astype(np.int64)
        # rating = user-bias + movie-bias + noise, clipped to 1..5
        ub = rng.randn(num_users)
        mb = rng.randn(num_movies)
        r = 3 + ub[self.users] + mb[self.movies] + 0.3 * rng.randn(n)
        self.ratings = np.clip(np.round(r), 1, 5).astype(np.float32)

    def __getitem__(self, idx):
        return (self.users[idx], self.ages[idx], self.jobs[idx],
                self.movies[idx], self.categories[idx], self.ratings[idx])

    def __len__(self):
        return len(self.users)


# Movielens record types (reference text/datasets/movielens.py:37,62):
# feature-extraction helpers kept for API parity with scripts that
# introspect the raw corpus records.
_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """Movie id, title and categories (reference movielens.py:37)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]

    def __str__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")

    __repr__ = __str__


class UserInfo:
    """User id, gender, age bucket and job (reference movielens.py:62)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = _AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]

    def __str__(self):
        return (f"<UserInfo id({self.index}), gender({self.is_male}), "
                f"age({self.age}), job({self.job_id})>")

    __repr__ = __str__


class _WMTBase(Dataset):
    """Shared synthetic seq2seq machinery for WMT14/WMT16.

    Items follow the reference schema (wmt14.py:169-171): a tuple of
    (src_ids, trg_ids, trg_ids_next) where trg_ids is <s>-prefixed and
    trg_ids_next is </e>-suffixed.  The synthetic task is learnable:
    the target sequence is the source sequence mapped through a fixed
    random permutation of the dict (a toy "translation"), so a seq2seq
    model can drive the loss to zero.
    """

    START_ID, END_ID, UNK_ID = 0, 1, 2
    _N_SPECIAL = 3

    def _build(self, n: int, seed: int, src_size: int, trg_size: int,
               min_len: int = 4, max_len: int = 16):
        rng = np.random.RandomState(seed)
        content = min(src_size, trg_size) - self._N_SPECIAL
        enforce(content > 0, "dict_size must exceed the 3 special tokens")
        # the "translation" mapping comes from a FIXED seed shared by all
        # splits (the Flowers shared-prototype pattern): train and
        # test/gen must be the same task, only the sampled sequences
        # differ by the split seed
        perm = np.arange(content)
        np.random.RandomState(97 + content).shuffle(perm)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for _ in range(n):
            L = rng.randint(min_len, max_len + 1)
            src = rng.randint(0, content, L)
            trg = perm[src]
            self.src_ids.append((src + self._N_SPECIAL).astype(np.int64))
            self.trg_ids.append(np.concatenate(
                [[self.START_ID], trg + self._N_SPECIAL]).astype(np.int64))
            self.trg_ids_next.append(np.concatenate(
                [trg + self._N_SPECIAL, [self.END_ID]]).astype(np.int64))

    @staticmethod
    def _make_dict(size: int, prefix: str, reverse: bool):
        words = {0: "<s>", 1: "<e>", 2: "<unk>"}
        for i in range(3, size):
            words[i] = f"{prefix}{i}"
        if reverse:
            return words
        return {w: i for i, w in words.items()}

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx],
                self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)


class WMT14(_WMTBase):
    """EN→FR translation token streams (reference text/datasets/wmt14.py:42).

    Zero-egress synthetic stand-in; ``data_file`` parsing of the reference
    tarball format is not supported here and raises.
    """

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 dict_size: int = 30000,
                 synthetic_size: Optional[int] = None):
        enforce(data_file is None,
                "WMT14 corpus parsing is not supported in this "
                "environment; omit data_file for the synthetic schema")
        enforce(mode in ("train", "test", "gen"),
                "mode must be train|test|gen")
        enforce(dict_size > 0, "dict_size should be set as positive number")
        self.mode = mode
        self.dict_size = dict_size
        n = ({"train": 4096, "test": 512, "gen": 128}[mode]
             if synthetic_size is None else synthetic_size)
        self._build(n, {"train": 41, "test": 43, "gen": 47}[mode],
                    dict_size, dict_size)

    def get_dict(self, reverse: bool = False):
        """(src_dict, trg_dict); id→word when reverse (wmt14.py:176)."""
        return (self._make_dict(self.dict_size, "en", reverse),
                self._make_dict(self.dict_size, "fr", reverse))


class WMT16(_WMTBase):
    """EN↔DE translation token streams (reference text/datasets/wmt16.py:43)
    with per-language dict sizes.  Zero-egress synthetic stand-in."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 src_dict_size: int = -1, trg_dict_size: int = -1,
                 lang: str = "en", synthetic_size: Optional[int] = None):
        enforce(data_file is None,
                "WMT16 corpus parsing is not supported in this "
                "environment; omit data_file for the synthetic schema")
        enforce(mode in ("train", "test", "val"),
                "mode must be train|test|val")
        enforce(lang in ("en", "de"), "lang must be en|de")
        enforce(src_dict_size > 0 and trg_dict_size > 0,
                "dict_size should be set as positive number")
        self.mode = mode
        self.lang = lang
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        n = ({"train": 4096, "test": 512, "val": 512}[mode]
             if synthetic_size is None else synthetic_size)
        self._build(n, {"train": 53, "test": 59, "val": 61}[mode],
                    src_dict_size, trg_dict_size)

    def get_dict(self, lang: str, reverse: bool = False):
        """Word dict for ``lang`` ('en'|'de'); id→word when reverse
        (wmt16.py get_dict)."""
        enforce(lang in ("en", "de"), "lang must be en|de")
        size = (self.src_dict_size if lang == self.lang
                else self.trg_dict_size)
        return self._make_dict(size, lang, reverse)
