"""BERT-style WordPiece tokenizer with a native C core.

Reference analog: the reference framework ships tokenization as native
code (PaddleNLP faster_tokenizer); python/paddle itself has none, so the
semantics here are canonical BERT WordPiece — lowercase (optional),
whitespace pre-split, ASCII punctuation isolation, greedy
longest-match-first subwords with ``##`` continuations, whole word →
``[UNK]`` when unsegmentable.

The hot loop is C (text/_native/wordpiece.c, built on first use like the
dataloader shm ring); a pure-python implementation with IDENTICAL
semantics serves as fallback and as the parity test oracle.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework.log import get_logger

__all__ = ["WordPieceTokenizer"]

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_SRC = os.path.join(_DIR, "wordpiece.c")

_lib = None
_lib_lock = threading.Lock()
# the C core's stack buffer bounds subword candidates at 509 bytes;
# max_word_len is clamped to this on BOTH paths so they stay identical
_MAX_WORD_BYTES = 509
_PUNCT = set(chr(c) for c in range(33, 48)) | \
    set(chr(c) for c in range(58, 65)) | \
    set(chr(c) for c in range(91, 97)) | \
    set(chr(c) for c in range(123, 127))


def _load_lib():
    """Build via utils.cpp_extension.load (content-hash cache + atomic
    rename: concurrent first-use must never dlopen a half-written .so).
    ANY failure → python fallback, as the use_native=None contract says."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        try:
            from ..utils.cpp_extension import load as cpp_load
            lib = cpp_load("wordpiece", [_SRC])
            lib.wp_new.restype = ctypes.c_void_p
            lib.wp_new.argtypes = [ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.c_int32, ctypes.c_int64]
            lib.wp_free.argtypes = [ctypes.c_void_p]
            lib.wp_encode.restype = ctypes.c_int64
            lib.wp_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int32, ctypes.c_int32,
                                      ctypes.POINTER(ctypes.c_int32),
                                      ctypes.c_int64]
        except Exception as e:
            get_logger().warning(
                "native wordpiece core unavailable (%s); python fallback",
                e)
            _lib = False
            return None
        _lib = lib
        return lib


class WordPieceTokenizer:
    """``encode(text) -> List[int]`` over a BERT-style vocab.

    ``vocab``: dict token→id or a sequence of tokens (ids = positions).
    ``use_native=None`` tries the C core and falls back silently.
    """

    def __init__(self, vocab, unk_token: str = "[UNK]",
                 lowercase: bool = True, max_word_len: int = 100,
                 use_native: Optional[bool] = None):
        if not isinstance(vocab, dict):
            vocab = {tok: i for i, tok in enumerate(vocab)}
        self.vocab: Dict[str, int] = dict(vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.unk_token = unk_token
        self.unk_id = self.vocab.get(unk_token, 0)
        self.lowercase = lowercase
        self.max_word_len = min(int(max_word_len), _MAX_WORD_BYTES)
        # byte-keyed view for the oracle: greedy matching is BYTE-level
        # exactly like the C core (invalid-utf8 intermediates simply
        # never match, so multibyte chars segment correctly)
        self._bvocab = {t.encode("utf-8"): i for t, i in self.vocab.items()}
        self._handle = None
        self._id_remap = None
        if use_native is not False:
            self._init_native(required=bool(use_native))

    # -- native core --------------------------------------------------------
    def _init_native(self, required: bool):
        lib = _load_lib()
        if lib is None:
            if required:
                raise RuntimeError("native wordpiece core unavailable")
            return
        # the C side needs a SORTED table; remap its indices back to ids
        toks = sorted(self.vocab)
        self._id_remap = np.asarray([self.vocab[t] for t in toks],
                                    np.int32)
        raw = [t.encode("utf-8") for t in toks]
        packed = b"\0".join(raw) + b"\0"
        offsets = np.zeros(len(raw), np.int64)
        off = 0
        for i, r in enumerate(raw):
            offsets[i] = off
            off += len(r) + 1
        handle = lib.wp_new(
            packed, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(raw), len(packed))
        if handle:
            self._handle = handle
            self._lib = lib

    def __del__(self):
        if getattr(self, "_handle", None):
            try:
                self._lib.wp_free(self._handle)
            except Exception:  # noqa: swallow — best-effort finalizer
                pass

    @property
    def uses_native(self) -> bool:
        return self._handle is not None

    # -- encoding -----------------------------------------------------------
    def encode(self, text: str) -> List[int]:
        if self.lowercase:
            text = text.lower()
        if self._handle is not None:
            cap = max(16, 2 * len(text) + 8)
            out = np.empty(cap, np.int32)
            n = self._lib.wp_encode(
                self._handle, text.encode("utf-8"), -1,
                self.max_word_len,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
            ids = out[:min(n, cap)]
            # vectorized remap: a python per-token loop here dominates the
            # whole encode for MB-scale inputs (C core output is sorted-
            # table indices; <0 marks UNK)
            remapped = np.where(ids < 0, np.int32(self.unk_id),
                                self._id_remap[np.clip(ids, 0, None)])
            return remapped.tolist()
        return self._encode_py(text)

    def encode_batch(self, texts: Sequence[str]) -> List[List[int]]:
        return [self.encode(t) for t in texts]

    def decode(self, ids: Sequence[int]) -> str:
        toks = [self.inv_vocab.get(int(i), self.unk_token) for i in ids]
        out = []
        for t in toks:
            if t.startswith("##") and out:
                out[-1] += t[2:]
            else:
                out.append(t)
        return " ".join(out)

    # -- python oracle (identical semantics) --------------------------------
    def _split(self, text: str) -> List[str]:
        words, cur = [], []
        for ch in text:
            if ch in (" ", "\t", "\n", "\r"):
                if cur:
                    words.append("".join(cur))
                    cur = []
            elif ch in _PUNCT:
                if cur:
                    words.append("".join(cur))
                    cur = []
                words.append(ch)
            else:
                cur.append(ch)
        if cur:
            words.append("".join(cur))
        return words

    def _encode_py(self, text: str) -> List[int]:
        ids: List[int] = []
        for word in self._split(text):
            wb = word.encode("utf-8")
            if len(wb) > self.max_word_len:
                ids.append(self.unk_id)
                continue
            start, word_ids = 0, []
            bad = False
            while start < len(wb):
                end = len(wb)
                found = None
                while end > start:
                    sub = wb[start:end]
                    if start > 0:
                        sub = b"##" + sub
                    if sub in self._bvocab:
                        found = self._bvocab[sub]
                        break
                    end -= 1
                if found is None:
                    bad = True
                    break
                word_ids.append(found)
                start = end
            ids.extend([self.unk_id] if bad else word_ids)
        return ids
