"""paddle.text parity (reference python/paddle/text/: datasets + the
viterbi_decode op, SURVEY A14).

``viterbi_decode`` is the real op (phi viterbi_decode kernel): CRF-style
max-sum decoding over a transition matrix, here a ``lax.scan`` dynamic
program that jits/fuses.  The bundled-download dataset zoo is represented
by file-backed classes (this environment has no egress; reference datasets
download then parse local files — the parse half is what lives here)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from . import datasets  # noqa: F401
from .tokenizer import WordPieceTokenizer  # noqa: F401
from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       MovieInfo, UCIHousing, UserInfo, WMT14, WMT16)

__all__ = ["WordPieceTokenizer",
           "viterbi_decode", "ViterbiDecoder", "datasets", "Imdb",
           "Imikolov", "UCIHousing", "Conll05st", "Movielens",
           "MovieInfo", "UserInfo", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition, lengths=None,
                   include_bos_eos_tag: bool = True
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """CRF Viterbi decoding (reference nn.functional viterbi_decode /
    phi viterbi_decode kernel).

    potentials: (B, T, N) emission scores; transition: (N, N) with
    transition[i, j] = score of i→j; lengths: (B,) valid lengths (defaults
    to T).  With include_bos_eos_tag, the last two tags are BOS/EOS
    (reference convention): BOS starts every path, EOS ends it.

    Returns (scores (B,), paths (B, T) int32; positions past a sequence's
    length hold 0).
    """
    potentials = jnp.asarray(potentials, jnp.float32)
    transition = jnp.asarray(transition, jnp.float32)
    B, T, N = potentials.shape
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)

    if include_bos_eos_tag:
        bos, eos = N - 2, N - 1
        init = potentials[:, 0] + transition[bos][None, :]
    else:
        init = potentials[:, 0]

    def step(carry, t):
        alpha, = carry
        # scores[b, i, j] = alpha[b, i] + transition[i, j] + emit[b, t, j]
        scores = alpha[:, :, None] + transition[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)             # (B, N)
        best_score = jnp.max(scores, axis=1) + potentials[:, t]
        # frozen past each sequence's end
        live = (t < lengths)[:, None]
        alpha_new = jnp.where(live, best_score, alpha)
        bp = jnp.where(live, best_prev.astype(jnp.int32), -1)
        return (alpha_new,), bp

    (alpha,), bps = lax.scan(step, (init,), jnp.arange(1, T))
    # bps: (T-1, B, N) backpointers for steps 1..T-1
    if include_bos_eos_tag:
        alpha = alpha + transition[:, eos][None, :]
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # (B,)

    def back(carry, bp_t):
        tag = carry
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # -1 marks frozen (past-end) steps: keep the tag
        new_tag = jnp.where(prev >= 0, prev, tag)
        return new_tag, tag

    tag0, rev_path = lax.scan(back, last_tag, bps, reverse=True)
    # rev_path[i] = tag at step i+1; tag0 = tag at step 0
    paths = jnp.concatenate(
        [tag0[:, None], jnp.transpose(rev_path, (1, 0))],
        axis=1).astype(jnp.int32)                          # (B, T)
    # zero out positions past each length
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    return scores, jnp.where(mask, paths, 0)


class ViterbiDecoder:
    """Layer-style wrapper (reference paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True):
        self.transitions = jnp.asarray(transitions, jnp.float32)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
