/* Native WordPiece tokenizer core.
 *
 * Reference analog: PaddleNLP's faster_tokenizer C++ core (the reference
 * framework ships tokenization as native code; python/paddle has no
 * tokenizer, so this follows the canonical BERT WordPiece semantics:
 * whitespace pre-split, ASCII punctuation isolation, greedy
 * longest-match-first subword segmentation with "##" continuations).
 *
 * Plain C ABI for ctypes (no pybind11 in the image).  The vocabulary is
 * stored as a sorted string table; lookups are binary search (O(log V),
 * V ~ 30k).  UTF-8 multibyte sequences pass through opaquely as word
 * bytes (the python side handles any unicode normalization).
 *
 * API:
 *   wp_new(packed, offsets, n)   -> handle   (packed = NUL-joined vocab,
 *                                             MUST be sorted ascending)
 *   wp_free(handle)
 *   wp_encode(handle, text, unk_id, max_word_len, out, cap) -> n_ids
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
    char *packed;          /* owned copy of the NUL-joined vocab */
    const char **words;    /* sorted pointers into packed */
    int32_t n;
    int32_t maxlen;        /* longest vocab token in bytes (incl. "##") */
} wp_t;

void *wp_new(const char *packed, const int64_t *offsets, int32_t n,
             int64_t packed_len) {
    wp_t *h = (wp_t *)malloc(sizeof(wp_t));
    if (!h) return 0;
    h->packed = (char *)malloc((size_t)packed_len);
    h->words = (const char **)malloc(sizeof(char *) * (size_t)n);
    if (!h->packed || !h->words) { free(h->packed); free(h->words);
                                   free(h); return 0; }
    memcpy(h->packed, packed, (size_t)packed_len);
    h->maxlen = 1;
    for (int32_t i = 0; i < n; i++) {
        h->words[i] = h->packed + offsets[i];
        int32_t l = (int32_t)strlen(h->words[i]);
        if (l > h->maxlen) h->maxlen = l;
    }
    h->n = n;
    return h;
}

void wp_free(void *handle) {
    wp_t *h = (wp_t *)handle;
    if (!h) return;
    free(h->packed);
    free((void *)h->words);
    free(h);
}

/* binary search; returns vocab index or -1 */
static int32_t wp_lookup(const wp_t *h, const char *s, int len) {
    int32_t lo = 0, hi = h->n - 1;
    while (lo <= hi) {
        int32_t mid = lo + (hi - lo) / 2;
        int c = strncmp(h->words[mid], s, (size_t)len);
        if (c == 0 && h->words[mid][len] != '\0') c = 1;
        if (c == 0) return mid;
        if (c < 0) lo = mid + 1; else hi = mid - 1;
    }
    return -1;
}

static int is_ws(unsigned char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

static int is_punct(unsigned char c) {
    /* ASCII punctuation, BERT BasicTokenizer rule */
    return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
           (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

/* greedy wordpiece over one word; returns ids written (or emits unk) */
static int64_t wp_word(const wp_t *h, const char *w, int wlen,
                       int32_t unk_id, int max_word_len,
                       int32_t *out, int64_t cap, int64_t pos) {
    char buf[512];
    if (wlen > max_word_len || wlen + 2 >= (int)sizeof(buf)) {
        if (pos < cap) out[pos] = unk_id;
        return pos + 1;
    }
    int start = 0;
    int64_t first = pos;
    while (start < wlen) {
        /* trials longer than the longest vocab token can never match;
         * with a "##" prefix the budget shrinks by 2 */
        int maxsub = (start > 0) ? h->maxlen - 2 : h->maxlen;
        if (maxsub < 1) maxsub = 1;
        int end = wlen, found = -1;
        if (end > start + maxsub) end = start + maxsub;
        const char *sub = w + start;
        if (start > 0) {
            /* copy once per start (trials only vary the length) — and only
             * the bytes the clamped longest trial can use */
            buf[0] = '#'; buf[1] = '#';
            memcpy(buf + 2, w + start, (size_t)(end - start));
            sub = buf;
        }
        while (end > start) {
            int sublen = end - start + (start > 0 ? 2 : 0);
            found = wp_lookup(h, sub, sublen);
            if (found >= 0) break;
            end--;
        }
        if (found < 0) {           /* unsegmentable -> single unk */
            if (first < cap) out[first] = unk_id;
            return first + 1;
        }
        if (pos < cap) out[pos] = found;
        pos++;
        start = end;
    }
    return pos;
}

int64_t wp_encode(void *handle, const char *text, int32_t unk_id,
                  int32_t max_word_len, int32_t *out, int64_t cap) {
    const wp_t *h = (const wp_t *)handle;
    int64_t pos = 0;
    const char *p = text;
    while (*p) {
        while (*p && is_ws((unsigned char)*p)) p++;
        if (!*p) break;
        if (is_punct((unsigned char)*p)) {       /* punct = own token */
            pos = wp_word(h, p, 1, unk_id, max_word_len, out, cap, pos);
            p++;
            continue;
        }
        const char *start = p;
        while (*p && !is_ws((unsigned char)*p)
               && !is_punct((unsigned char)*p)) p++;
        pos = wp_word(h, start, (int)(p - start), unk_id, max_word_len,
                      out, cap, pos);
    }
    return pos;
}

#ifdef __cplusplus
}  /* extern "C" */
#endif
