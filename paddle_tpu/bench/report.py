"""Static perf dashboard (ISSUE 14): the trend engine, rendered.

``python -m paddle_tpu.bench.report`` turns ``trends.scan_ledger`` into
one **self-contained** HTML file (default ``benchmarks/report.html``):
inline CSS, inline SVG sparklines — no JS, no fonts, no CDN, no network
fetch of any kind, so the artifact is archivable and opens identically
from a laptop, a CI artifact store, or ``file://`` on an air-gapped
machine.

Per scenario/mode: a sparkline per metric axis (step p50, MFU, compile
wall, bytes-on-wire, peak HBM) with detected changepoints marked on the
line, the latest value vs the trailing-window median, and the trend
direction.  Below: the regression table (changepoints + flagged drifts
with sha ranges and dominant phases) and the flakiness ranking the
noise-aware gate calibrates against.
"""
from __future__ import annotations

import argparse
import html
import os
from typing import Any, Dict, List, Optional

from ..utils import fsio
from . import ledger, trends
from .schema import CORE_METRICS, GAP_SINKS

__all__ = ["sparkline_svg", "gap_bar_svg", "comm_bar_svg", "render_html",
           "write_report", "main"]

_METRIC_LABEL = {
    "step_p50": "step p50 (ms)",
    "mfu": "MFU",
    "compile_wall_ms": "compile wall (ms)",
    "bytes_on_wire": "bytes on wire",
    "peak_hbm_bytes": "peak HBM",
    "roofline_coverage": "roofline coverage",
}
_METRIC_LABEL.update({f"gap_{_s}_ms": f"gap:{_s} (ms)"
                      for _s in GAP_SINKS if _s != "mxu"})
_METRIC_LABEL.update({
    "comm_modeled_ms": "comm:modeled (ms)",
    "comm_overlapped_ms": "comm:overlapped (ms)",
    "comm_unattributed_ms": "comm:unattributed (ms)",
})

# stacked-bar palette for the MFU gap budget (ISSUE 19); mxu is the
# useful-work segment, everything else is gap
_SINK_COLOR = {
    "mxu": "#2f855a",
    "memory_bound": "#d69e2e",
    "comm": "#3182ce",
    "host": "#805ad5",
    "padding": "#dd6b20",
    "unknown_device": "#718096",
    "residual": "#c53030",
}

_CSS = """
body { font: 14px/1.45 system-ui, sans-serif; margin: 24px;
       color: #1a202c; background: #fff; }
h1 { font-size: 20px; margin: 0 0 4px; }
h2 { font-size: 16px; margin: 28px 0 8px; }
.meta { color: #718096; margin-bottom: 18px; }
table { border-collapse: collapse; margin: 8px 0 16px; }
th, td { border: 1px solid #e2e8f0; padding: 4px 10px;
         text-align: left; vertical-align: middle; }
th { background: #f7fafc; font-weight: 600; }
td.num { text-align: right; font-variant-numeric: tabular-nums; }
.up { color: #c53030; font-weight: 600; }
.down { color: #2f855a; font-weight: 600; }
.flat { color: #718096; }
.spark { display: block; }
.cards { display: flex; gap: 12px; margin: 12px 0 4px; }
.card { border: 1px solid #e2e8f0; border-radius: 6px;
        padding: 8px 14px; min-width: 110px; }
.card b { display: block; font-size: 18px; }
.ok { color: #2f855a; }
.bad { color: #c53030; }
"""


def sparkline_svg(values: List[float],
                  changepoints: Optional[List[Dict[str, Any]]] = None,
                  width: int = 220, height: int = 44) -> str:
    """One inline SVG sparkline; changepoint indices get a marker dot on
    the line and a vertical rule (red = up/regression, green = down)."""
    n = len(values)
    if n == 0:
        return "<svg class='spark' width='%d' height='%d'></svg>" % (
            width, height)
    pad = 3.0
    lo, hi = min(values), max(values)
    span = (hi - lo) or max(abs(hi), 1e-12) * 0.1 or 1.0

    def x(i: int) -> float:
        return pad + (width - 2 * pad) * (i / max(1, n - 1))

    def y(v: float) -> float:
        return pad + (height - 2 * pad) * (1.0 - (v - lo) / span)

    pts = " ".join(f"{x(i):.1f},{y(v):.1f}" for i, v in enumerate(values))
    parts = [f"<svg class='spark' width='{width}' height='{height}' "
             f"viewBox='0 0 {width} {height}' role='img'>"]
    for cp in changepoints or []:
        i = cp.get("index")
        if not isinstance(i, int) or not (0 <= i < n):
            continue
        color = "#c53030" if cp.get("direction") == "up" else "#2f855a"
        parts.append(f"<line x1='{x(i):.1f}' y1='0' x2='{x(i):.1f}' "
                     f"y2='{height}' stroke='{color}' stroke-width='1' "
                     "stroke-dasharray='3,2'/>")
        parts.append(f"<circle cx='{x(i):.1f}' cy='{y(values[i]):.1f}' "
                     f"r='3' fill='{color}'/>")
    if n == 1:
        parts.append(f"<circle cx='{x(0):.1f}' cy='{y(values[0]):.1f}' "
                     "r='2.5' fill='#3182ce'/>")
    else:
        parts.append(f"<polyline points='{pts}' fill='none' "
                     "stroke='#3182ce' stroke-width='1.5'/>")
        parts.append(f"<circle cx='{x(n - 1):.1f}' "
                     f"cy='{y(values[-1]):.1f}' r='2.5' fill='#3182ce'/>")
    parts.append("</svg>")
    return "".join(parts)


def gap_bar_svg(buckets: Dict[str, float], measured_ms: float,
                width: int = 340, height: int = 18) -> str:
    """One horizontal stacked bar of the MFU-gap budget: a colored
    segment per sink, widths proportional to bucket ms over the measured
    step time (negative buckets — e.g. an over-modeled residual — get
    zero width; their sign still shows in the numbers table)."""
    parts = [f"<svg class='spark' width='{width}' height='{height}' "
             f"viewBox='0 0 {width} {height}' role='img'>"]
    total = max(float(measured_ms), 1e-12)
    x = 0.0
    for s in GAP_SINKS:
        w = width * max(0.0, float(buckets.get(s, 0.0) or 0.0)) / total
        if w < 0.5:
            continue
        parts.append(f"<rect x='{x:.1f}' y='0' width='{w:.1f}' "
                     f"height='{height}' "
                     f"fill='{_SINK_COLOR.get(s, '#a0aec0')}'>"
                     f"<title>{html.escape(s)}</title></rect>")
        x += w
    parts.append("</svg>")
    return "".join(parts)


# per-axis palette for the comm sub-budget bars (ISSUE 20); unmapped
# axes cycle through the fallback list, "(unattributed)" stays grey
_AXIS_COLOR = {
    "dp": "#3182ce", "mp": "#805ad5", "pp": "#dd6b20",
    "ep": "#d69e2e", "sp": "#2f855a",
}
_AXIS_FALLBACK = ("#319795", "#b83280", "#5a67d8", "#975a16")


def comm_bar_svg(entries: List[Dict[str, Any]], bucket_ms: float,
                 width: int = 340, height: int = 18) -> str:
    """One horizontal stacked bar of the comm sub-budget: a colored
    segment per (op, axis) entry, widths proportional to measured ms
    over the comm bucket; ``(unattributed)`` renders grey.  Negative
    entries (over-attribution absorbed by the remainder) get zero
    width; their sign still shows in the numbers column."""
    parts = [f"<svg class='spark' width='{width}' height='{height}' "
             f"viewBox='0 0 {width} {height}' role='img'>"]
    total = max(float(bucket_ms), 1e-12)
    x = 0.0
    fallback = 0
    for e in entries or []:
        op = str(e.get("op") or "?")
        axis = e.get("axis")
        w = width * max(0.0, float(e.get("measured_ms") or 0.0)) / total
        if w < 0.5:
            continue
        if op == "(unattributed)":
            color = "#a0aec0"
        else:
            color = _AXIS_COLOR.get(axis)
            if color is None:
                color = _AXIS_FALLBACK[fallback % len(_AXIS_FALLBACK)]
                fallback += 1
        label = op + (f"[axis={axis}]" if axis else "")
        parts.append(f"<rect x='{x:.1f}' y='0' width='{w:.1f}' "
                     f"height='{height}' fill='{color}'>"
                     f"<title>{html.escape(label)}</title></rect>")
        x += w
    parts.append("</svg>")
    return "".join(parts)


def _esc(v: Any) -> str:
    return html.escape(str(v))


def _short(sha: Optional[str]) -> str:
    return sha[:8] if isinstance(sha, str) else "?"


def _trend_cell(trend: Optional[str]) -> str:
    if trend == "up":
        return "<span class='up'>&#9650; up</span>"
    if trend == "down":
        return "<span class='down'>&#9660; down</span>"
    if trend == "flat":
        return "<span class='flat'>&#8596; flat</span>"
    return "<span class='flat'>—</span>"


def _collect_events(analyses: List[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Changepoints + flagged drifts across all scenarios/metrics, for
    the regression table (step-time upward moves first)."""
    events: List[Dict[str, Any]] = []
    for a in analyses:
        for metric, an in a["metrics"].items():
            for cp in an.get("changepoints") or []:
                events.append({
                    "kind": "changepoint", "scenario": a["scenario"],
                    "mode": a["mode"], "metric": metric,
                    "delta_frac": cp["delta_frac"],
                    "direction": cp["direction"],
                    "sha_range": cp.get("sha_range") or (None, None),
                    "dominant_phase": cp.get("dominant_phase"),
                })
            drift = an.get("drift")
            if drift and drift.get("flagged"):
                events.append({
                    "kind": "drift", "scenario": a["scenario"],
                    "mode": a["mode"], "metric": metric,
                    "delta_frac": drift["total_frac"],
                    "direction": drift["direction"],
                    "sha_range": (None, None), "dominant_phase": None,
                })
    events.sort(key=lambda e: (
        0 if (e["metric"] == "step_p50" and e["direction"] == "up") else 1,
        -abs(e["delta_frac"])))
    return events


def render_html(analyses: List[Dict[str, Any]],
                ledger_path: Optional[str] = None,
                latest_rows: Optional[Dict[str, Dict[str, Any]]] = None
                ) -> str:
    """The whole dashboard as one HTML string (no external assets)."""
    window = trends.trend_window()
    k = trends.trend_k()
    events = _collect_events(analyses)
    n_up = sum(1 for e in events
               if e["metric"] == "step_p50" and e["direction"] == "up")
    flaky = [(a["scenario"], a["mode"], a["flakiness"])
             for a in analyses if a.get("flakiness") is not None]
    worst_flaky = max((f for _, _, f in flaky), default=None)

    out: List[str] = []
    out.append("<!DOCTYPE html><html lang='en'><head>"
               "<meta charset='utf-8'>"
               "<title>paddle_tpu perf trends</title>"
               f"<style>{_CSS}</style></head><body>")
    out.append("<h1>paddle_tpu perf trends</h1>")
    out.append(f"<div class='meta'>ledger: "
               f"{_esc(ledger_path or ledger.default_ledger_path())} "
               f"&middot; trailing window {window} &middot; k={k:g} "
               "&middot; self-contained (no external assets)</div>")

    out.append("<div class='cards'>")
    out.append(f"<div class='card'><b>{len(analyses)}</b>series</div>")
    cls = "bad" if n_up else "ok"
    out.append(f"<div class='card'><b class='{cls}'>{n_up}</b>"
               "step-time regressions</div>")
    out.append(f"<div class='card'><b>{len(events)}</b>"
               "events (all metrics)</div>")
    out.append("<div class='card'><b>"
               + (f"{worst_flaky:.1%}" if worst_flaky is not None else "—")
               + "</b>worst flakiness</div>")
    out.append("</div>")

    # per-scenario sparkline matrix (core axes only — the gap-bucket
    # axes get their own budget section below)
    out.append("<h2>Series</h2><table><tr><th>scenario</th><th>mode</th>"
               "<th>partition</th>"
               + "".join(f"<th>{_esc(_METRIC_LABEL[m])}</th>"
                         for m in CORE_METRICS)
               + "<th>trend</th></tr>")
    for a in analyses:
        out.append(f"<tr><td>{_esc(a['scenario'])}</td>"
                   f"<td>{_esc(a['mode'])}</td>"
                   f"<td>{_esc(a.get('partition') or '—')}</td>")
        for m in CORE_METRICS:
            an = a["metrics"].get(m) or {}
            vals = an.get("values") or []
            if not vals:
                out.append("<td class='flat'>—</td>")
                continue
            spark = sparkline_svg(vals, an.get("changepoints"))
            latest = trends._fmt_metric(m, an.get("latest"))
            med = trends._fmt_metric(m, an.get("median"))
            out.append(f"<td>{spark}<small>{_esc(latest)} "
                       f"(median {_esc(med)}, n={an.get('n')})"
                       "</small></td>")
        step = a["metrics"].get("step_p50") or {}
        out.append(f"<td>{_trend_cell(step.get('trend'))}</td></tr>")
    out.append("</table>")

    # MFU gap budgets (ISSUE 19): roofline attribution of the newest row
    # per scenario — where the gap between achieved and peak went
    out.append("<h2>MFU gap budgets (roofline, newest row)</h2>")
    roof_rows = [(name, row) for name, row in sorted(
                     (latest_rows or {}).items())
                 if isinstance((row.get("roofline") or {})
                               .get("buckets_ms"), dict)]
    if not roof_rows:
        out.append("<p class='flat'>no roofline data yet — rows predate "
                   "schema v2 or the observatory was disabled.</p>")
    else:
        out.append("<table><tr><th>scenario</th><th>budget</th>"
                   "<th>measured</th><th>modeled</th>"
                   "<th>dominant sink</th><th>coverage</th>"
                   "<th>buckets (ms)</th></tr>")
        for name, row in roof_rows:
            roof = row["roofline"]
            buckets = roof.get("buckets_ms") or {}
            measured = float(roof.get("measured_step_ms") or 0.0)
            cov = roof.get("coverage")
            dom = roof.get("dominant_sink")
            nums = ", ".join(
                f"{s}={float(buckets.get(s, 0.0) or 0.0):.2f}"
                for s in GAP_SINKS)
            flags = []
            if roof.get("degraded"):
                flags.append("degraded")
            if roof.get("injected"):
                flags.append("injected")
            dom_cell = _esc(dom or "—") + (
                f" <small class='flat'>[{', '.join(flags)}]</small>"
                if flags else "")
            out.append(
                f"<tr><td>{_esc(name)} ({_esc(row.get('mode'))})</td>"
                f"<td>{gap_bar_svg(buckets, measured)}</td>"
                f"<td class='num'>{measured:.2f}ms</td>"
                f"<td class='num'>"
                f"{float(roof.get('modeled_step_ms') or 0.0):.2f}ms</td>"
                f"<td>{dom_cell}</td>"
                f"<td class='num'>"
                + (f"{float(cov):.1%}" if cov is not None else "—")
                + f"</td><td><small>{_esc(nums)}</small></td></tr>")
        out.append("</table>")
        legend = " &middot; ".join(
            f"<span style='color:{_SINK_COLOR[s]}'>&#9632;</span> "
            f"{_esc(s)}" for s in GAP_SINKS)
        out.append(f"<p class='meta'>{legend}</p>")

    # interconnect comm sub-budgets (ISSUE 20): the roofline's comm
    # bucket split per (op, axis), with efficiency vs the ICI model
    out.append("<h2>Exposed-comm sub-budgets (interconnect, "
               "newest row)</h2>")
    ic_rows = [(name, row) for name, row in sorted(
                   (latest_rows or {}).items())
               if isinstance((row.get("interconnect") or {})
                             .get("entries"), list)]
    if not ic_rows:
        out.append("<p class='flat'>no interconnect data yet — rows "
                   "predate schema v3.</p>")
    else:
        out.append("<table><tr><th>scenario</th><th>sub-budget</th>"
                   "<th>comm bucket</th><th>overlapped (est)</th>"
                   "<th>entries (op[axis] measured / modeled / "
                   "efficiency)</th></tr>")
        for name, row in ic_rows:
            ic = row["interconnect"]
            entries = ic.get("entries") or []
            bucket = float(ic.get("comm_bucket_ms") or 0.0)
            over = ic.get("overlapped_ms")
            cells = []
            for e in entries:
                op = str(e.get("op") or "?")
                if op == "(unattributed)":
                    cells.append(
                        f"(unattributed)="
                        f"{float(e.get('measured_ms') or 0.0):.2f}ms")
                    continue
                label = op + (f"[axis={e['axis']}]"
                              if e.get("axis") else "")
                bit = f"{label}={float(e.get('measured_ms') or 0.0):.2f}ms"
                if isinstance(e.get("modeled_ms"), (int, float)):
                    bit += f" / {e['modeled_ms']:.3f}ms"
                if isinstance(e.get("efficiency"), (int, float)):
                    bit += f" / {e['efficiency']:.0%}"
                cells.append(bit)
            flags = []
            if ic.get("degraded"):
                flags.append("degraded")
            if ic.get("injected"):
                flags.append("injected")
            name_cell = (f"{_esc(name)} ({_esc(row.get('mode'))})"
                         + (f" <small class='flat'>"
                            f"[{', '.join(flags)}]</small>"
                            if flags else ""))
            out.append(
                f"<tr><td>{name_cell}</td>"
                f"<td>{comm_bar_svg(entries, bucket)}</td>"
                f"<td class='num'>{bucket:.2f}ms</td>"
                f"<td class='num'>"
                + (f"{float(over):.2f}ms"
                   if isinstance(over, (int, float)) else "—")
                + f"</td><td><small>{_esc('; '.join(cells) or '—')}"
                "</small></td></tr>")
        out.append("</table>")
        legend = " &middot; ".join(
            f"<span style='color:{c}'>&#9632;</span> axis={_esc(a)}"
            for a, c in _AXIS_COLOR.items()) + \
            " &middot; <span style='color:#a0aec0'>&#9632;</span> " \
            "(unattributed)"
        out.append(f"<p class='meta'>{legend}</p>")

    # regression / event table
    out.append("<h2>Changepoints &amp; drifts</h2>")
    if not events:
        out.append("<p class='ok'>none detected — the ledger looks "
                   "healthy.</p>")
    else:
        out.append("<table><tr><th>kind</th><th>scenario</th>"
                   "<th>metric</th><th>shift</th><th>sha range</th>"
                   "<th>dominant phase</th></tr>")
        for e in events:
            cls = "up" if e["direction"] == "up" else "down"
            before, at = e["sha_range"]
            rng = (f"{_short(before)}..{_short(at)}"
                   if at else "—")
            out.append(
                f"<tr><td>{e['kind']}</td>"
                f"<td>{_esc(e['scenario'])} ({_esc(e['mode'])})</td>"
                f"<td>{_esc(_METRIC_LABEL.get(e['metric'], e['metric']))}"
                f"</td><td class='num {cls}'>{e['delta_frac']:+.1%}</td>"
                f"<td>{_esc(rng)}</td>"
                f"<td>{_esc(e['dominant_phase'] or '—')}</td></tr>")
        out.append("</table>")

    # flakiness ranking
    out.append("<h2>Flakiness (noise sigma / median)</h2>")
    if not flaky:
        out.append("<p class='flat'>no series long enough yet.</p>")
    else:
        out.append("<table><tr><th>scenario</th><th>mode</th>"
                   "<th>flakiness</th></tr>")
        for scenario, mode, f in sorted(flaky, key=lambda r: -r[2]):
            out.append(f"<tr><td>{_esc(scenario)}</td><td>{_esc(mode)}"
                       f"</td><td class='num'>{f:.1%}</td></tr>")
        out.append("</table>")

    out.append("</body></html>")
    return "".join(out)


def default_report_path() -> str:
    return os.path.join(os.path.dirname(ledger.default_ledger_path()),
                        "report.html")


def write_report(path: Optional[str] = None,
                 ledger_path: Optional[str] = None,
                 mode: Optional[str] = None,
                 window: Optional[int] = None,
                 k: Optional[float] = None) -> str:
    """Render the dashboard to ``path`` (atomic write); returns it."""
    rows = ledger.read_ledger(ledger_path)
    if mode is not None:
        rows = [r for r in rows if r.get("mode") == mode]
    analyses = trends.scan_ledger(rows=rows, mode=mode,
                                  window=window, k=k)
    doc = render_html(analyses, ledger_path=ledger_path,
                      latest_rows=ledger.latest_rows(rows))
    path = path or default_report_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.atomic_write_bytes(path, doc.encode("utf-8"))
    return path


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.bench.report",
        description="render the self-contained perf trend dashboard "
                    "(inline SVG, no external assets)")
    ap.add_argument("--ledger", default=None, help="ledger path override")
    ap.add_argument("--out", default=None,
                    help="output path (default benchmarks/report.html)")
    ap.add_argument("--mode", default=None, choices=("smoke", "full"),
                    help="only render rows of this mode")
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--k", type=float, default=None)
    args = ap.parse_args(argv)
    path = write_report(path=args.out, ledger_path=args.ledger,
                        mode=args.mode, window=args.window, k=args.k)
    print(f"perf dashboard -> {path}")  # noqa: print — CLI report
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
