"""The CI perf tier: noise-aware enforcement (ISSUE 13 → 14).

``run_gate`` compares each scenario's **newest** ledger row against the
**trailing-window median** of its own run history (``read_series`` with
sha-dedup off — the gate wants rerun jitter, not one point per commit),
with a threshold of::

    max(golden step_time_regression_frac,  k * 1.4826 * MAD / median)

so a jittery scenario stops false-alarming at a fixed 10% while a quiet
one is enforced tighter than the golden's blanket number would dare.
``PTPU_TREND_WINDOW`` bounds the window, ``PTPU_TREND_K`` scales the
noise term.  Edge cases are deliberate:

- golden missing entirely → rc 0 with an advisory (a fresh tree must
  not fail CI before a baseline exists; run ``--write-golden``);
- scenario in the ledger but not in golden → pass with a note (new
  scenarios enter enforcement only when blessed);
- **fewer than 3 ledger rows for a scenario → rc 0 with an explicit
  "insufficient history" advisory** — never a silent fallback to a raw
  golden comparison (ISSUE 14 fix);
- exactly at the threshold → pass (strict inequality).

``--write-golden`` is unchanged: bless the newest ledger rows (existing
threshold overrides preserved), diff the file in review.
"""
from __future__ import annotations

import argparse
from typing import Any, Dict, List, Optional

from . import diff as perfdiff
from . import ledger
from . import trends

__all__ = ["MIN_HISTORY", "run_gate", "main"]

# below this many rows for a scenario the gate reports "insufficient
# history" as an advisory (rc 0) — a 1-row median is not a baseline
MIN_HISTORY = 3


def _say(msg: str) -> None:
    print(msg)  # noqa: print — the gate IS a CLI report


def run_gate(ledger_path: Optional[str] = None,
             golden_path: Optional[str] = None,
             threshold_frac: Optional[float] = None,
             write_golden: bool = False,
             mode: Optional[str] = None,
             window: Optional[int] = None,
             k: Optional[float] = None) -> int:
    """Returns the process rc: 0 pass, 1 regression, 2 usage error.

    ``threshold_frac`` (or ``--threshold``) overrides the whole
    noise-aware computation — an explicit number is an explicit number.
    """
    if window is None:
        window = trends.trend_window()
    if k is None:
        k = trends.trend_k()
    drops: Dict[str, int] = {}
    rows = ledger.read_ledger(ledger_path, drops=drops)
    if drops.get("torn_lines") or drops.get("unknown_schema"):
        _say(f"perf gate: note — skipped {drops['torn_lines']} torn / "
             f"{drops['unknown_schema']} foreign-schema ledger line(s)")
    latest = ledger.latest_rows(rows, mode=mode)

    if write_golden:
        if not latest:
            _say("perf gate: no ledger rows to bless — run "
                 "`python -m paddle_tpu.bench --all --smoke` first")
            return 2
        prior = ledger.load_golden(golden_path)
        golden = ledger.golden_from_rows(
            latest, thresholds=(prior or {}).get("thresholds"))
        path = ledger.write_golden(golden, golden_path)
        _say(f"perf gate: blessed {len(latest)} scenario row(s) -> {path}")
        return 0

    golden = ledger.load_golden(golden_path)
    if golden is None:
        _say("perf gate: no golden baseline — passing (advisory). "
             "Bless one with: python -m paddle_tpu.bench.gate "
             "--write-golden")
        return 0
    if not latest:
        _say("perf gate: ledger has no rows to check — passing "
             "(advisory); run the matrix first")
        return 0
    golden_frac = ledger.threshold(golden, "step_time_regression_frac")

    failures: List[Dict[str, Any]] = []
    for name in sorted(latest):
        if name not in golden["scenarios"]:
            _say(f"perf gate: {name}: not in golden yet — passing "
                 "(bless with --write-golden to enforce)")
            continue
        cur = latest[name]
        # run-level series (reruns kept, sha-dedup off): the newest
        # point is `cur`, everything before it is the baseline window
        points = ledger.read_series(name, str(cur.get("mode")),
                                    "step_p50", rows=rows,
                                    dedupe_sha=False)
        if len(points) < MIN_HISTORY:
            _say(f"perf gate: {name}: insufficient history "
                 f"({len(points)} row(s), need {MIN_HISTORY}) — "
                 "advisory only, not enforced")
            continue
        prior_pts = points[:-1][-window:]
        prior_vals = [p["value"] for p in prior_pts]
        base_row = trends.median_row([p["row"] for p in prior_pts])
        med = trends.median(prior_vals) or 0.0
        madv = trends.mad(prior_vals) or 0.0
        noise_frac = (k * 1.4826 * madv / med) if med > 0 else 0.0
        thr = (threshold_frac if threshold_frac is not None
               else max(golden_frac, noise_frac))
        report = perfdiff.diff_rows(base_row, cur, thr)
        if report["regression"]:
            failures.append(report)
            _say(perfdiff.render(report))
        else:
            ratio = report.get("ratio")
            _say(f"perf gate: {name}: ok"
                 + (f" ({ratio:.2f}x vs trailing median of "
                    f"{len(prior_pts)}, threshold {thr:.1%}"
                    + (", noise-raised" if thr > golden_frac else "")
                    + ")"
                    if ratio is not None else ""))
    if failures:
        _say(f"perf gate: FAIL — {len(failures)} scenario(s) regressed "
             "beyond their noise-aware threshold vs the trailing median")
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.bench.gate",
        description="perf gate: fail on a step-time regression beyond "
                    "max(golden threshold, k*MAD noise floor) vs the "
                    "trailing-window median")
    ap.add_argument("--ledger", default=None, help="ledger path override")
    ap.add_argument("--golden", default=None, help="golden path override")
    ap.add_argument("--threshold", type=float, default=None,
                    help="explicit regression fraction (disables the "
                         "noise-aware computation)")
    ap.add_argument("--mode", default=None, choices=("smoke", "full"),
                    help="only consider ledger rows of this mode")
    ap.add_argument("--window", type=int, default=None,
                    help="trailing window (default PTPU_TREND_WINDOW)")
    ap.add_argument("--k", type=float, default=None,
                    help="noise multiplier (default PTPU_TREND_K)")
    ap.add_argument("--write-golden", action="store_true",
                    help="bless the newest ledger rows as the golden")
    args = ap.parse_args(argv)
    return run_gate(ledger_path=args.ledger, golden_path=args.golden,
                    threshold_frac=args.threshold,
                    write_golden=args.write_golden, mode=args.mode,
                    window=args.window, k=args.k)


if __name__ == "__main__":
    raise SystemExit(main())
