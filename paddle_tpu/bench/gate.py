"""The CI perf tier (ISSUE 13): enforce the golden baseline.

``run_gate`` reads the newest ledger row per scenario and compares each
against ``benchmarks/golden.json``; a step-time p50 *strictly* more than
``step_time_regression_frac`` (default 10%) above the blessed row fails
rc 1 with the perfdiff attribution report.  Edge cases are deliberate:

- golden missing entirely → rc 0 with an advisory (a fresh tree must
  not fail CI before a baseline exists; run ``--write-golden``);
- scenario in the ledger but not in golden → pass with a note (new
  scenarios enter enforcement only when blessed);
- exactly at the threshold → pass (strict inequality).

``--write-golden`` is the ptlint-baseline-style update workflow: bless
the newest ledger rows as the new golden (existing threshold overrides
are preserved) and diff the file in review like any other change.
"""
from __future__ import annotations

import argparse
import sys
from typing import Any, Dict, List, Optional

from . import diff as perfdiff
from . import ledger

__all__ = ["run_gate", "main"]


def _say(msg: str) -> None:
    print(msg)  # noqa: print — the gate IS a CLI report


def run_gate(ledger_path: Optional[str] = None,
             golden_path: Optional[str] = None,
             threshold_frac: Optional[float] = None,
             write_golden: bool = False,
             mode: Optional[str] = None) -> int:
    """Returns the process rc: 0 pass, 1 regression, 2 usage error."""
    drops: Dict[str, int] = {}
    rows = ledger.read_ledger(ledger_path, drops=drops)
    if drops.get("torn_lines") or drops.get("unknown_schema"):
        _say(f"perf gate: note — skipped {drops['torn_lines']} torn / "
             f"{drops['unknown_schema']} foreign-schema ledger line(s)")
    latest = ledger.latest_rows(rows, mode=mode)

    if write_golden:
        if not latest:
            _say("perf gate: no ledger rows to bless — run "
                 "`python -m paddle_tpu.bench --all --smoke` first")
            return 2
        prior = ledger.load_golden(golden_path)
        golden = ledger.golden_from_rows(
            latest, thresholds=(prior or {}).get("thresholds"))
        path = ledger.write_golden(golden, golden_path)
        _say(f"perf gate: blessed {len(latest)} scenario row(s) -> {path}")
        return 0

    golden = ledger.load_golden(golden_path)
    if golden is None:
        _say("perf gate: no golden baseline — passing (advisory). "
             "Bless one with: python -m paddle_tpu.bench.gate "
             "--write-golden")
        return 0
    if not latest:
        _say("perf gate: ledger has no rows to check — passing "
             "(advisory); run the matrix first")
        return 0
    thr = (threshold_frac if threshold_frac is not None
           else ledger.threshold(golden, "step_time_regression_frac"))

    failures: List[Dict[str, Any]] = []
    for name in sorted(latest):
        if name not in golden["scenarios"]:
            _say(f"perf gate: {name}: not in golden yet — passing "
                 "(bless with --write-golden to enforce)")
            continue
        report = perfdiff.diff_rows(golden["scenarios"][name],
                                    latest[name], thr)
        if report["regression"]:
            failures.append(report)
            _say(perfdiff.render(report))
        else:
            ratio = report.get("ratio")
            _say(f"perf gate: {name}: ok"
                 + (f" ({ratio:.2f}x vs golden)"
                    if ratio is not None else ""))
    if failures:
        _say(f"perf gate: FAIL — {len(failures)} scenario(s) regressed "
             f"more than {thr:.0%} vs golden")
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.bench.gate",
        description="perf gate: fail on >threshold step-time regression "
                    "vs benchmarks/golden.json")
    ap.add_argument("--ledger", default=None, help="ledger path override")
    ap.add_argument("--golden", default=None, help="golden path override")
    ap.add_argument("--threshold", type=float, default=None,
                    help="regression fraction override (e.g. 0.10)")
    ap.add_argument("--mode", default=None, choices=("smoke", "full"),
                    help="only consider ledger rows of this mode")
    ap.add_argument("--write-golden", action="store_true",
                    help="bless the newest ledger rows as the golden")
    args = ap.parse_args(argv)
    return run_gate(ledger_path=args.ledger, golden_path=args.golden,
                    threshold_frac=args.threshold,
                    write_golden=args.write_golden, mode=args.mode)


if __name__ == "__main__":
    raise SystemExit(main())
