"""Scenario registry (ISSUE 13) — the BASELINE.json workload matrix.

Each scenario is one registered function ``fn(mode) -> payload`` where
``mode`` is ``"smoke"`` (CPU-sized, CI) or ``"full"`` (the real
BASELINE shapes).  The payload carries only what the scenario itself
measured — ``runner.run_scenario`` brackets it with the compile window,
bytes-on-wire delta and fingerprint stamping, and assembles the one
schema row.

Matrix (ROADMAP 5b):

==================== =====================================================
gpt_pretrain_fused   GPT causal-LM train step, fused transformer block
gpt_pretrain_unfused same config, fused block off (the PR 7 A/B axes)
moe                  GPT with MoE FFN layers (``distributed/moe.py``)
long_context         Ulysses sequence-parallel GPT over the ``sp`` axis
resnet               ResNet train step (18 smoke / 50 ImageNet-config)
mnist                LeNet MNIST-shape train step
serve                continuous-batching decode through the PR 6 engine
serve_fleet          routed decode over 2 replicas incl. one failover
==================== =====================================================
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List

from . import harness

__all__ = ["register", "get", "names", "SCENARIOS"]

SCENARIOS: Dict[str, Callable[[str], Dict[str, Any]]] = {}


def register(name: str):
    def deco(fn):
        SCENARIOS[name] = fn
        fn.__scenario_name__ = name
        return fn
    return deco


def get(name: str) -> Callable[[str], Dict[str, Any]]:
    if name not in SCENARIOS:
        raise KeyError(f"unknown scenario {name!r}; have "
                       f"{', '.join(sorted(SCENARIOS))}")
    return SCENARIOS[name]


def names() -> List[str]:
    return list(SCENARIOS)


# -- shared GPT train-step scaffolding --------------------------------------
def _gpt_train_payload(cfg, B: int, S: int, steps: int, warmup: int,
                       shard_data: bool = False) -> Dict[str, Any]:
    """Build + measure one GPT causal-LM train step; the common core of
    the gpt/moe/long_context scenarios.  ``shard_data``: route batches
    through ``dist.shard_batch`` (sequence-parallel meshes)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.observability.compilation import track_jit
    from paddle_tpu.observability.mfu import (flops_per_token, mfu,
                                              param_count)

    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    if shard_data:
        from paddle_tpu.distributed.parallel import (
            device_put_sharded_variables)
        device_put_sharded_variables(model)
    params = model.state_dict()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01)
    opt_state = opt.init(params)
    n_params = param_count(params)

    def train_step(p, s, ids, labels, key):
        def loss_fn(q):
            with fw_random.key_scope(key):
                loss, _ = model.apply(q, ids, labels=labels)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(p)
        new_p, new_s = opt.apply_gradients(grads, p, s)
        return loss, new_p, new_s

    jitted = track_jit(jax.jit(train_step, donate_argnums=(0, 1)),
                       name="bench.gpt_step",
                       arg_names=("params", "opt_state", "inputs",
                                  "labels", "key"))
    rng = np.random.RandomState(0)

    def make_batch(i):
        ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
        labels = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
        if shard_data:
            import paddle_tpu.distributed as dist
            return (dist.shard_batch(ids), dist.shard_batch(labels))
        return (jnp.asarray(ids), jnp.asarray(labels))

    # static footprint BEFORE the loop: donated buffers are gone after
    ids0, labels0 = make_batch(0)
    peak = harness.peak_hbm(jitted, params, opt_state, ids0, labels0,
                            jax.random.key(0))

    state = {"p": params, "s": opt_state}

    def step_fn(i, batch):
        ids, labels = batch
        loss, state["p"], state["s"] = jitted(
            state["p"], state["s"], ids, labels,
            jax.random.fold_in(jax.random.key(0), i))
        return loss

    m = harness.measure_steps(step_fn, make_batch, steps, warmup)
    p50 = harness.pct(sorted(m["step_times_ms"]), 50) or 1.0
    tok_s = B * S / (p50 / 1e3)
    flops_tok = flops_per_token(n_params, num_layers=cfg.num_layers,
                                hidden_size=cfg.hidden_size, seq_len=S,
                                causal=True)
    return {
        "config": {"batch": B, "seq_len": S, "steps": steps,
                   "warmup": warmup, "params_m": n_params / 1e6,
                   "num_layers": cfg.num_layers,
                   "hidden_size": cfg.hidden_size},
        "step_times_ms": m["step_times_ms"],
        "phases_ms": m["phases_ms"],
        "collective_by_op": m.get("collective_by_op"),
        "tokens_per_sec": tok_s,
        "mfu": mfu(tok_s, flops_tok),
        "peak_hbm_bytes": peak,
        "extra": {"warmup_s": m["warmup_s"],
                  "final_loss": m["final_value"]},
    }


def _gpt_cfg(mode: str, **kw):
    from paddle_tpu.models import gpt_125m, gpt_tiny
    if mode == "full":
        return gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                        attention_dropout=0.0, use_pallas_attention=True,
                        max_position_embeddings=2048, **kw)
    return gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0, **kw)


def _gpt_shape(mode: str):
    return ((8, 2048, 10, 3) if mode == "full" else (2, 128, 4, 1))


@register("gpt_pretrain_fused")
def gpt_pretrain_fused(mode: str) -> Dict[str, Any]:
    B, S, steps, warmup = _gpt_shape(mode)
    return _gpt_train_payload(_gpt_cfg(mode, use_fused_block=True),
                              B, S, steps, warmup)


@register("gpt_pretrain_unfused")
def gpt_pretrain_unfused(mode: str) -> Dict[str, Any]:
    B, S, steps, warmup = _gpt_shape(mode)
    return _gpt_train_payload(_gpt_cfg(mode, use_fused_block=False),
                              B, S, steps, warmup)


@register("moe")
def moe(mode: str) -> Dict[str, Any]:
    """GPT with MoE FFN layers (every other layer; gshard top-2).  On
    one device the dispatch/combine runs unsharded — the capacity math
    and aux loss are identical, which is what the row tracks."""
    from paddle_tpu.models import gpt_125m, gpt_tiny
    if mode == "full":
        cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                       attention_dropout=0.0, use_pallas_attention=True,
                       max_position_embeddings=2048,
                       moe_num_experts=8, moe_every=2)
        B, S, steps, warmup = 8, 2048, 10, 3
    else:
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                       moe_num_experts=4, moe_every=2)
        B, S, steps, warmup = 2, 128, 4, 1
    payload = _gpt_train_payload(cfg, B, S, steps, warmup)
    payload["config"]["moe_num_experts"] = cfg.moe_num_experts
    return payload


@register("long_context")
def long_context(mode: str) -> Dict[str, Any]:
    """Ulysses sequence-parallel GPT: activations seq-sharded over the
    ``sp`` axis, heads all-to-all'd inside attention
    (``distributed/sequence_parallel.py``).  Needs ≥4 devices for the
    sp axis — the virtual CPU mesh provides them in smoke mode."""
    import jax

    import paddle_tpu.distributed as dist
    from paddle_tpu.models import gpt_tiny

    sp = 4
    if jax.device_count() < 2 * sp:
        raise RuntimeError(
            f"long_context needs a {2 * sp}-device mesh for the dp×sp "
            f"axes (have {jax.device_count()})")
    if mode == "full":
        cfg = gpt_tiny(hidden_size=512, num_layers=8, num_heads=8,
                       vocab_size=32768, max_position_embeddings=8192,
                       hidden_dropout=0.0, attention_dropout=0.0,
                       sequence_parallel=True)
        B, S, steps, warmup = 2, 8192, 6, 2
    else:
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                       max_position_embeddings=512,
                       sequence_parallel=True)
        B, S, steps, warmup = 2, 512, 4, 1
    topo = dist.CommunicateTopology(["data", "sequence", "model"],
                                    [2, sp, 1])
    dist.set_hybrid_communicate_group(dist.HybridCommunicateGroup(topo))
    try:
        payload = _gpt_train_payload(cfg, B, S, steps, warmup,
                                     shard_data=True)
    finally:
        dist.set_hybrid_communicate_group(None)
    payload["config"]["sp_degree"] = sp
    return payload


def _vision_train_payload(model, B: int, hw: int, steps: int, warmup: int,
                          num_classes: int, channels: int = 3,
                          flops_per_img: float = 0.0) -> Dict[str, Any]:
    import numpy as np
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.observability.compilation import track_jit
    from paddle_tpu.observability.mfu import param_count, peak_flops_per_sec

    pt.seed(0)
    model.train()
    trainable = model.trainable_variables()
    rest = {k: v for k, v in model.state_dict().items()
            if k not in trainable}
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                weight_decay=1e-4)
    opt_state = opt.init(trainable)

    def train_step(params, s, x, y, key):
        def loss_fn(tp):
            with fw_random.key_scope(key):
                logits, newv = model.apply({**rest, **tp}, x, mutable=True)
            loss = F.cross_entropy(logits.astype(jnp.float32), y)
            return loss, newv
        (loss, _newv), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_p, new_s = opt.apply_gradients(grads, params, s)
        return loss, new_p, new_s

    jitted = track_jit(jax.jit(train_step, donate_argnums=(0, 1)),
                       name="bench.vision_step",
                       arg_names=("params", "opt_state", "inputs",
                                  "labels", "key"))
    rng = np.random.RandomState(0)

    def make_batch(i):
        x = (rng.randn(B, channels, hw, hw) * 0.5).astype(np.float32)
        y = rng.randint(0, num_classes, (B,)).astype(np.int32)
        return (jnp.asarray(x), jnp.asarray(y))

    x0, y0 = make_batch(0)
    peak = harness.peak_hbm(jitted, trainable, opt_state, x0, y0,
                            jax.random.key(0))
    state = {"p": trainable, "s": opt_state}

    def step_fn(i, batch):
        x, y = batch
        loss, state["p"], state["s"] = jitted(
            state["p"], state["s"], x, y,
            jax.random.fold_in(jax.random.key(0), i))
        return loss

    m = harness.measure_steps(step_fn, make_batch, steps, warmup)
    p50 = harness.pct(sorted(m["step_times_ms"]), 50) or 1.0
    img_s = B / (p50 / 1e3)
    # vision rows keep tokens_per_sec null; img/s lives in extra and the
    # MFU (when a per-image FLOPs figure exists for the config) uses the
    # shared peak definition
    mfu_val = (img_s * 3.0 * flops_per_img / peak_flops_per_sec()
               if flops_per_img else None)
    return {
        "config": {"batch": B, "hw": hw, "steps": steps,
                   "warmup": warmup,
                   "params_m": param_count(trainable) / 1e6},
        "step_times_ms": m["step_times_ms"],
        "phases_ms": m["phases_ms"],
        "collective_by_op": m.get("collective_by_op"),
        "tokens_per_sec": None,
        "mfu": mfu_val,
        "peak_hbm_bytes": peak,
        "extra": {"images_per_sec": img_s, "warmup_s": m["warmup_s"],
                  "final_loss": m["final_value"]},
    }


@register("resnet")
def resnet(mode: str) -> Dict[str, Any]:
    """BASELINE row #2: ResNet ImageNet-config train step — ResNet-50 at
    224² in full mode (MFU against the 4.089 GFLOPs/img forward cost),
    ResNet-18 at 32² as the CPU smoke."""
    from paddle_tpu.vision.models import resnet18, resnet50
    if mode == "full":
        payload = _vision_train_payload(resnet50(), B=128, hw=224,
                                        steps=10, warmup=3,
                                        num_classes=1000,
                                        flops_per_img=4.089e9)
        payload["config"]["depth"] = 50
    else:
        payload = _vision_train_payload(resnet18(), B=2, hw=32,
                                        steps=3, warmup=1,
                                        num_classes=1000)
        payload["config"]["depth"] = 18
    return payload


@register("mnist")
def mnist(mode: str) -> Dict[str, Any]:
    """LeNet on MNIST-shaped batches — the smallest vision row, mostly a
    canary for per-step host overheads (data/readback dominate)."""
    from paddle_tpu.vision.models import LeNet
    B = 64 if mode == "full" else 16
    steps, warmup = (10, 3) if mode == "full" else (4, 1)
    return _vision_train_payload(LeNet(), B=B, hw=28, steps=steps,
                                 warmup=warmup, num_classes=10,
                                 channels=1)


@register("serve")
def serve(mode: str) -> Dict[str, Any]:
    """Continuous-batching decode through the PR 6 ServingEngine: N
    ragged streams, one interleaved loop.  A bench "step" is one engine
    step (one prefill or one decode batch); TTFT/TPOT percentiles and
    serve-mode (fwd-only) MFU ride in ``extra``."""
    import time as _time

    import numpy as np
    import jax

    import paddle_tpu as pt
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability.mfu import (flops_per_token, mfu,
                                              param_count)
    from paddle_tpu.observability.registry import MetricsRegistry

    n_streams = 8 if mode == "full" else 4
    max_new = 48 if mode == "full" else 12
    cfg = GPTConfig(vocab_size=512,
                    hidden_size=128 if mode == "full" else 64,
                    num_layers=2, num_heads=4,
                    ffn_hidden_size=256 if mode == "full" else 128,
                    max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    reg = MetricsRegistry()
    engine = ServingEngine(model, max_seqs=n_streams, kv_block_size=4,
                           registry=reg)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, cfg.vocab_size,
                           rng.randint(3, 8)).tolist()
               for _ in range(n_streams)]
    # warm the prefill/decode compile caches outside the timed window
    engine.generate([p[:3] for p in prompts[:2]], max_new_tokens=2)
    t_warm = _time.perf_counter()
    rids = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
    warm_s = _time.perf_counter() - t_warm

    step_ms: List[float] = []
    t0 = _time.perf_counter()
    while engine.has_work() and len(step_ms) < 4096:
        ta = _time.perf_counter()
        engine.step()
        step_ms.append((_time.perf_counter() - ta) * 1e3)
    elapsed = _time.perf_counter() - t0
    results = [engine.collect(r) for r in rids]
    generated = sum(len(r["tokens"]) for r in results)
    tok_s = generated / max(1e-9, elapsed)
    snap = reg.snapshot()

    def hpct(name, p):
        m = snap.get(name)
        return None if not isinstance(m, dict) else m.get(p)

    n_params = param_count(model.trainable_variables())
    flops_tok = flops_per_token(n_params, num_layers=cfg.num_layers,
                                hidden_size=cfg.hidden_size,
                                seq_len=cfg.max_position_embeddings,
                                fwd_only=True)

    def p50(series):
        return harness.pct(sorted(series), 50) or 0.0

    return {
        "config": {"n_streams": n_streams, "max_new_tokens": max_new,
                   "steps": len(step_ms),
                   "params_m": n_params / 1e6,
                   "kv_block_size": engine.cache.block_size},
        "step_times_ms": step_ms,
        # an engine step is dispatch+sample+bookkeeping in one host
        # call; the whole step is the compute phase (sampling syncs
        # internally, so there is no separate readback to time)
        "phases_ms": {"data": 0.0, "compute": p50(step_ms),
                      "readback": 0.0, "collective": 0.0},
        "tokens_per_sec": tok_s,
        "mfu": mfu(tok_s, flops_tok),
        "peak_hbm_bytes": harness.peak_hbm(),
        "extra": {"generated_tokens": generated,
                  "engine_steps": len(step_ms),
                  "warmup_s": warm_s,
                  "ttft_ms_p50": hpct("serve.ttft_ms", "p50"),
                  "ttft_ms_p99": hpct("serve.ttft_ms", "p99"),
                  "tpot_ms_p50": hpct("serve.tpot_ms", "p50"),
                  "tpot_ms_p99": hpct("serve.tpot_ms", "p99"),
                  "preemptions": engine.sched.preemptions,
                  # real-vs-padded token slots (ISSUE 19): pow2 prefill
                  # buckets + fixed decode batch; feeds the roofline
                  # padding sink so pad rows stop inflating serve MFU
                  "padding_frac": round(engine.padding_frac(), 6)},
    }


@register("serve_fleet")
def serve_fleet(mode: str) -> Dict[str, Any]:
    """Routed decode through the ISSUE 16 fleet: two in-process
    replicas behind the Router, one mid-run failover.  A bench "step"
    is one router pump (poll + step every live replica); the timed
    window includes journal replay of the failed-over streams, so the
    figure prices what resilience costs, not just the happy path.

    Runs twice (ISSUE 18): once with request tracing OFF (the parity
    baseline) and once ON (the reported pass).  ``extra`` carries the
    assembled trace coverage, per-component breakdown medians, and
    ``trace_overhead_frac`` — the typical (p50) pump's span-emission
    cost as a fraction of step p50, which CI asserts stays under 1%.
    One-off emission bursts (prefill fan-out, failover re-dispatch)
    stay visible in the reported per-pump mean.  The cost
    is measured directly (``requesttrace.emission_cost`` meters the
    emit hot path) rather than by differencing the two passes: at
    millisecond-scale CPU steps, run-to-run jitter swamps a 1% budget,
    while direct accounting resolves microseconds.  The off-pass p50
    is still reported so gross regressions stay visible."""
    import os as _os
    import time as _time

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.inference.fleet import LocalReplica, Router
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import requesttrace
    from paddle_tpu.observability.mfu import (flops_per_token, mfu,
                                              param_count)
    from paddle_tpu.observability.registry import MetricsRegistry

    n_streams = 8 if mode == "full" else 4
    max_new = 48 if mode == "full" else 24
    cfg = GPTConfig(vocab_size=512,
                    hidden_size=128 if mode == "full" else 64,
                    num_layers=2, num_heads=4,
                    ffn_hidden_size=256 if mode == "full" else 128,
                    max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0)

    def build_engine(reg, i):
        pt.seed(0)                    # identical weights per replica
        model = GPTForCausalLM(cfg)
        model.eval()
        return model, ServingEngine(model, max_seqs=n_streams,
                                    kv_block_size=4, registry=reg,
                                    replica_id=i)

    class _ListSink:                  # in-memory trace record capture
        def __init__(self):
            self.records: List[Dict[str, Any]] = []

        def write(self, rec):
            self.records.append(rec)

        def flush(self):
            pass

        def close(self):
            pass

    def run_pass(traced: bool) -> Dict[str, Any]:
        prev = _os.environ.get(requesttrace.TRACE_REQUESTS_ENV)
        _os.environ[requesttrace.TRACE_REQUESTS_ENV] = \
            "1" if traced else "0"
        try:
            reg = MetricsRegistry()
            sink = _ListSink()
            if traced:
                reg.add_sink(sink)
            models, replicas = [], []
            for i in range(2):
                model, eng = build_engine(reg, i)
                models.append(model)
                replicas.append(LocalReplica(eng, replica_id=i))
            router = Router(replicas, registry=reg)
            rng = np.random.RandomState(7)
            prompts = [rng.randint(1, cfg.vocab_size,
                                   rng.randint(3, 8)).tolist()
                       for _ in range(n_streams)]
            # warm both replicas' compile caches outside the timed
            # window — untraced, so assembled traces == client streams
            _os.environ[requesttrace.TRACE_REQUESTS_ENV] = "0"
            for r in replicas:
                r.engine.generate([prompts[0][:3]], max_new_tokens=2)
            _os.environ[requesttrace.TRACE_REQUESTS_ENV] = \
                "1" if traced else "0"
            rids = [router.submit(p, max_new_tokens=max_new)
                    for p in prompts]

            kill_after = 3            # pumps before the failover drill
            step_ms: List[float] = []
            emit_ms: List[float] = []   # per-pump metered emit cost
            cost = requesttrace.emission_cost
            if traced:                # meter emit cost over the timed
                cost.start()          # window only
            t0 = _time.perf_counter()
            while len(step_ms) < 4096:
                es0 = cost.seconds
                ta = _time.perf_counter()
                live = router.pump()
                step_ms.append((_time.perf_counter() - ta) * 1e3)
                emit_ms.append((cost.seconds - es0) * 1e3)
                if len(step_ms) == kill_after:
                    victim = next((j.replica_id
                                   for j in router.journals.values()
                                   if not j.finished
                                   and j.replica_id is not None), None)
                    if victim is not None:
                        replicas[victim].engine._state = "stopped"
                if live == 0:
                    break
            elapsed = _time.perf_counter() - t0
            emit_n = cost.count
            cost.stop()
            results = [router.collect(r, timeout=60) for r in rids]
            return {"step_ms": step_ms, "elapsed": elapsed,
                    "generated": sum(len(r["tokens"]) for r in results),
                    "records": sink.records, "router": router,
                    "models": models, "n_requests": len(rids),
                    "engines": [r.engine for r in replicas],
                    "emit_ms": emit_ms, "emit_count": emit_n}
        finally:
            if prev is None:
                _os.environ.pop(requesttrace.TRACE_REQUESTS_ENV, None)
            else:
                _os.environ[requesttrace.TRACE_REQUESTS_ENV] = prev

    def p50(series):
        return harness.pct(sorted(series), 50) or 0.0

    base = run_pass(traced=False)     # parity baseline: same token
    run = run_pass(traced=True)       # count, untraced step p50
    step_ms = run["step_ms"]
    generated = run["generated"]
    tok_s = generated / max(1e-9, run["elapsed"])
    p50_off, p50_on = p50(base["step_ms"]), p50(step_ms)
    # overhead = the typical pump's metered emission cost over the
    # typical pump's duration — p50 against p50, so one-off bursts
    # (prefill fan-out, failover re-dispatch) land in the mean, which
    # is still reported, not in the gate (direct measurement; see the
    # docstring for why not pass differencing)
    emit_p50 = p50(run["emit_ms"])
    emit_mean = sum(run["emit_ms"]) / max(1, len(run["emit_ms"]))
    overhead = emit_p50 / p50_on if p50_on > 0 else 0.0

    asm = requesttrace.TraceAssembler().from_records(run["records"])
    traces = asm["traces"]
    coverages = sorted(t["coverage"] for t in traces)
    comps = sorted({c for t in traces for c in t["components"]})
    comp_medians = {
        c: round(harness.pct(sorted(t["components"].get(c, 0.0)
                                    for t in traces), 50) or 0.0, 3)
        for c in comps}
    attrib = requesttrace.tail_latency_attribution(traces)

    n_params = param_count(run["models"][0].trainable_variables())
    flops_tok = flops_per_token(n_params, num_layers=cfg.num_layers,
                                hidden_size=cfg.hidden_size,
                                seq_len=cfg.max_position_embeddings,
                                fwd_only=True)
    router = run["router"]
    # fleet-wide padding: pooled real/slot counts across both replicas
    pad_real = sum(e._pad_real_tokens for e in run["engines"])
    pad_slots = sum(e._pad_slot_tokens for e in run["engines"])
    padding_frac = (1.0 - pad_real / pad_slots) if pad_slots else 0.0

    return {
        "config": {"n_streams": n_streams, "max_new_tokens": max_new,
                   "replicas": 2, "steps": len(step_ms),
                   "params_m": n_params / 1e6},
        "step_times_ms": step_ms,
        # a pump is poll+step+journal in one host call — all compute
        # phase (no separate data/readback to time at this layer)
        "phases_ms": {"data": 0.0, "compute": p50(step_ms),
                      "readback": 0.0, "collective": 0.0},
        "tokens_per_sec": tok_s,
        "mfu": mfu(tok_s, flops_tok),
        "peak_hbm_bytes": harness.peak_hbm(),
        "extra": {"generated_tokens": generated,
                  "router_pumps": len(step_ms),
                  "failovers": router.failovers,
                  "dispatches": run["n_requests"] + router.failovers,
                  "trace_overhead_frac": round(overhead, 6),
                  "trace_emit_p50_ms": round(emit_p50, 5),
                  "trace_emit_ms_per_pump": round(emit_mean, 5),
                  "trace_emit_records": run["emit_count"],
                  "trace_step_p50_off_ms": round(p50_off, 3),
                  "trace_step_p50_on_ms": round(p50_on, 3),
                  "traces_assembled": len(traces),
                  "traces_complete": asm["complete"],
                  "trace_orphan_spans": len(asm["orphan_spans"]),
                  "trace_coverage_p50": round(
                      harness.pct(coverages, 50) or 0.0, 4),
                  "trace_coverage_min": round(
                      coverages[0] if coverages else 0.0, 4),
                  "trace_component_median_ms": comp_medians,
                  "tail_dominant": (attrib or {}).get("dominant"),
                  "padding_frac": round(padding_frac, 6)},
    }
