"""Shared scenario harness (ISSUE 13).

One measurement discipline for every scenario, so rows are comparable:

- :func:`measure_steps` — the timed loop.  Each step is decomposed into
  the ledger's phase axes: **data** (host batch production), **compute**
  (the dispatch call), **readback** (the host readback of the loss — on
  tunneled TPU platforms ``block_until_ready`` returns at dispatch, so
  the readback is the only true sync; see bench.py's module note).  The
  **collective** phase comes from the ``collective.<op>.ms`` histogram
  deltas the comm layer records across the timed window.
- :class:`CompileWindow` — brackets a scenario with a compile-tracker
  reset and registry-counter baselines, yielding the row's ``compile``
  stats (wall, traces, retraces, in-process cache hits, persistent
  disk-cache hits/requests from ``observability/compilecache``).
- :func:`peak_hbm` — PJRT ``memory_stats()`` peak when the backend
  exposes it, else the compiled program's memory analysis
  (temp+argument+output bytes), the platform-independent proxy bench.py
  has always used.
- :func:`tpu_reachable` — the subprocess device probe (moved out of
  bench.py's monolith; a dead TPU tunnel hangs ``jax.devices()``
  indefinitely, which must never take the bench down with it).
"""
from __future__ import annotations

import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability.registry import split_labels

__all__ = ["measure_steps", "CompileWindow", "RooflineWindow", "peak_hbm",
           "xla_memory", "bytes_on_wire", "tpu_reachable", "pct"]


def pct(sorted_vals: List[float], p: float) -> Optional[float]:
    """The percentile definition shared with ``aggregate._pct``."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _collective_ms_total(registry) -> float:
    """Sum of all ``collective.<op>.ms`` histogram totals right now —
    labeled (``[axis=..,n=..]``) and legacy-unlabeled families both
    count, each exactly once."""
    total = 0.0
    for name, snap in registry.snapshot().items():
        base, _labels = split_labels(name)
        if (base.startswith("collective.") and base.endswith(".ms")
                and snap.get("type") == "histogram"):
            total += float(snap.get("sum") or 0.0)
    return total


def _collective_by_key(registry) -> Dict[Tuple[str, Optional[str], int],
                                         Dict[str, float]]:
    """Per-(op, axis, participants) totals of the collective instrument
    families right now: ``{"ms": histogram sum, "calls": counter,
    "bytes": counter}``.  Unlabeled legacy names land under
    ``axis=None, participants=0`` — one bucket, never double-counted
    against their labeled siblings (distinct instrument names)."""
    out: Dict[Tuple[str, Optional[str], int], Dict[str, float]] = {}
    for name, snap in registry.snapshot().items():
        base, labels = split_labels(name)
        if not base.startswith("collective."):
            continue
        parts = base.split(".")
        if len(parts) != 3 or parts[2] not in ("ms", "calls", "bytes"):
            continue
        op, field = parts[1], parts[2]
        try:
            n = int(labels.get("n", "0"))
        except ValueError:
            n = 0
        key = (op, labels.get("axis"), n)
        rec = out.setdefault(key, {"ms": 0.0, "calls": 0.0, "bytes": 0.0})
        if field == "ms":
            if snap.get("type") == "histogram":
                rec["ms"] += float(snap.get("sum") or 0.0)
        elif snap.get("type") == "counter":
            rec[field] += float(snap.get("value") or 0.0)
    return out


def measure_steps(step_fn: Callable[[int, Any], Any],
                  make_batch: Callable[[int], Any],
                  steps: int, warmup: int,
                  registry=None) -> Dict[str, Any]:
    """Run ``warmup + steps`` iterations; time the last ``steps`` with a
    per-phase breakdown.

    ``make_batch(i)`` produces one host-side batch (its wall time is the
    **data** phase); ``step_fn(i, batch)`` dispatches one step, keeping
    any state (params/opt) internal, and returns the scalar to read back
    (**compute** = the dispatch call, **readback** = ``float(...)`` on
    the result).  Returns per-step series plus phase p50s shaped for
    ``schema.new_row``.
    """
    if registry is None:
        from ..observability import get_registry
        registry = get_registry()
    t0 = time.perf_counter()
    out = None
    for i in range(warmup):
        out = step_fn(i, make_batch(i))
    if out is not None:
        float(out)                      # true sync before the timed window
    warm_s = time.perf_counter() - t0

    total_ms: List[float] = []
    data_ms: List[float] = []
    compute_ms: List[float] = []
    readback_ms: List[float] = []
    coll_by0 = _collective_by_key(registry)
    last = None
    for i in range(steps):
        ta = time.perf_counter()
        batch = make_batch(warmup + i)
        tb = time.perf_counter()
        out = step_fn(warmup + i, batch)
        tc = time.perf_counter()
        last = float(out) if out is not None else None
        td = time.perf_counter()
        data_ms.append((tb - ta) * 1e3)
        compute_ms.append((tc - tb) * 1e3)
        readback_ms.append((td - tc) * 1e3)
        total_ms.append((td - ta) * 1e3)
    coll_by1 = _collective_by_key(registry)
    collective_by_op: List[Dict[str, Any]] = []
    coll_total = 0.0
    for key in sorted(coll_by1, key=lambda k: (k[0], str(k[1]), k[2])):
        rec = coll_by1[key]
        base0 = coll_by0.get(key, {"ms": 0.0, "calls": 0.0, "bytes": 0.0})
        d_ms = max(0.0, rec["ms"] - base0["ms"])
        d_calls = max(0.0, rec["calls"] - base0["calls"])
        d_bytes = max(0.0, rec["bytes"] - base0["bytes"])
        coll_total += d_ms
        if d_ms <= 0.0 and d_calls <= 0.0 and d_bytes <= 0.0:
            continue
        op, axis, n = key
        collective_by_op.append({
            "op": op, "axis": axis, "participants": n or None,
            "calls": d_calls / max(1, steps),
            "ms": d_ms / max(1, steps),
            "payload_bytes": d_bytes / max(1, steps),
        })
    collective_per_step = coll_total / max(1, steps)

    def p50(series: List[float]) -> float:
        return pct(sorted(series), 50) or 0.0

    return {
        "step_times_ms": total_ms,
        "phases_ms": {"data": p50(data_ms), "compute": p50(compute_ms),
                      "readback": p50(readback_ms),
                      "collective": collective_per_step},
        "collective_by_op": collective_by_op,
        "warmup_s": warm_s,
        "final_value": last,
    }


class CompileWindow:
    """Bracket one scenario: tracker reset on entry, compile stats for
    the row on :meth:`stats`.

    Wall time is the delta of the ``compile.wall_ms[fn=...]`` histogram
    totals (the registry is process-global and scenarios run back to
    back); trace/retrace/hit counts come from the tracker, which IS
    reset per scenario; persistent-cache hits/requests are the
    ``observability/compilecache`` counter deltas.
    """

    def __init__(self, registry=None):
        if registry is None:
            from ..observability import get_registry
            registry = get_registry()
        self._registry = registry

    def __enter__(self) -> "CompileWindow":
        from ..observability.compilation import reset_tracker
        reset_tracker()
        self._wall0 = self._compile_wall_total()
        self._pc0 = self._persistent_counts()
        return self

    def __exit__(self, *exc) -> None:
        return None

    def _compile_wall_total(self) -> float:
        total = 0.0
        for name, snap in self._registry.snapshot().items():
            if (name.startswith("compile.wall_ms[")
                    and snap.get("type") == "histogram"):
                total += float(snap.get("sum") or 0.0)
        return total

    def _persistent_counts(self) -> Tuple[float, float]:
        reg = self._registry
        return (reg.counter("compile.persistent_cache_hits").value,
                reg.counter("compile.persistent_cache_requests").value)

    def stats(self) -> Dict[str, Any]:
        from ..observability.compilation import get_tracker
        tr = get_tracker()
        traces = retraces = calls = storms = 0
        for fn in tr.functions():
            st = tr.stats(fn)
            calls += st["calls"]
            traces += st["traces"]
            retraces += st["retraces"]
            storms += st["storms"]
        hits, reqs = self._persistent_counts()
        return {
            "wall_ms": max(0.0, self._compile_wall_total() - self._wall0),
            "traces": traces,
            "retraces": retraces,
            "storms": storms,
            "cache_hits": max(0, calls - traces),
            "persistent_hits": int(hits - self._pc0[0]),
            "persistent_requests": int(reqs - self._pc0[1]),
        }


class RooflineWindow:
    """Bracket one scenario with the MFU-microscope capture (ISSUE 19):
    on entry the roofline observatory starts recording the abstract
    signatures ``track_jit`` sees; :meth:`block` lowers + compiles each
    captured program (outside any timed region) and returns the row's
    ``roofline`` gap-budget block.  Never raises — a failed capture
    degrades to the phase-only block so the row still validates.
    """

    def __enter__(self) -> "RooflineWindow":
        from ..observability import roofline
        self._win = roofline.capture_window()
        self._win.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._win.__exit__(*exc)

    def block(self, step_times_ms: List[float],
              phases_ms: Dict[str, float], *,
              padding_frac: float = 0.0) -> Dict[str, Any]:
        p50 = pct(sorted(float(t) for t in step_times_ms), 50) or 0.0
        try:
            return self._win.build_block(p50, phases_ms,
                                         padding_frac=padding_frac)
        except Exception as e:
            from ..observability import roofline
            return roofline.degraded_block(
                p50, phases_ms, padding_frac=padding_frac,
                reason=f"capture failed: {e!r}")


def xla_memory(jitted, *args) -> Optional[Dict[str, int]]:
    """Compiled-program memory analysis (temp/argument/output bytes) —
    None when the backend doesn't expose it."""
    try:
        fn = getattr(jitted, "__wrapped_fn__", jitted)
        mem = fn.lower(*args).compile().memory_analysis()
        return {"temp_bytes": int(mem.temp_size_in_bytes),
                "argument_bytes": int(mem.argument_size_in_bytes),
                "output_bytes": int(mem.output_size_in_bytes)}
    except Exception:
        return None


def peak_hbm(jitted=None, *args) -> Optional[int]:
    """Peak device memory for the row: the live PJRT watermark when the
    backend reports one, else the compiled program's static footprint."""
    import jax
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("peak_bytes_in_use"):
        return int(stats["peak_bytes_in_use"])
    if jitted is not None:
        mem = xla_memory(jitted, *args)
        if mem:
            return (mem["temp_bytes"] + mem["argument_bytes"]
                    + mem["output_bytes"])
    return None


def _counter_family_total(registry, base: str) -> float:
    """Sum of one counter family — the unlabeled ``base`` plus every
    ``base[...]`` labeled variant (each a distinct instrument)."""
    total = 0.0
    for name, snap in registry.snapshot().items():
        b, _labels = split_labels(name)
        if b == base and snap.get("type") == "counter":
            total += float(snap.get("value") or 0.0)
    return total


class BytesOnWire:
    """Delta reader over the comm package's trace-time byte accounting
    (PR 8): ``comm.compressed_bytes`` is what the run ships,
    ``comm.bytes`` the exact-schedule equivalent.  Both are summed as
    metric *families* — since ISSUE 20 the counters carry
    ``[axis=..,leg=..]`` labels."""

    def __init__(self, registry=None):
        if registry is None:
            from ..observability import get_registry
            registry = get_registry()
        self._registry = registry
        self._raw0 = _counter_family_total(registry, "comm.bytes")
        self._wire0 = _counter_family_total(registry,
                                            "comm.compressed_bytes")

    def delta(self) -> int:
        reg = self._registry
        wire = (_counter_family_total(reg, "comm.compressed_bytes")
                - self._wire0)
        raw = _counter_family_total(reg, "comm.bytes") - self._raw0
        return int(wire if wire > 0 else raw)


def bytes_on_wire(registry=None) -> BytesOnWire:
    return BytesOnWire(registry)


def tpu_reachable(timeout_s: int = 420) -> bool:
    """Probe device init in a subprocess: a dead TPU tunnel makes
    ``jax.devices()`` hang indefinitely, which must not take the bench
    (and the driver's BENCH json) down with it."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return out.returncode == 0 and "tpu" in out.stdout
