"""Append-only perf ledger + checked-in golden (ISSUE 13).

``benchmarks/ledger.jsonl`` is the observatory's history: one line per
scenario run, append-only through ``utils/fsio.append_bytes`` (fsync'd;
a mid-append death costs one torn line, never the file).  The reader
carries the exact torn-tail semantics of
``observability.aggregate.read_worker_stream``: unparseable lines and
foreign ``schema_version`` rows are skipped with drop accounting, so a
ledger written by a newer tree stays readable by older tooling.

``benchmarks/golden.json`` is the enforcement baseline: the blessed row
per scenario plus the ``thresholds`` table the CI gate (and the ci.sh
A/B smokes) read — updated only through the explicit ``--write-golden``
workflow, mirroring ptlint's baseline file.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..utils import fsio
from .schema import (KNOWN_SCHEMA_VERSIONS, SCHEMA_VERSION, fingerprint_key,
                     metric_value, validate_row)

__all__ = ["default_ledger_path", "default_golden_path", "append_row",
           "read_ledger", "latest_rows", "read_series", "compact_ledger",
           "load_golden", "write_golden", "golden_from_rows",
           "DEFAULT_THRESHOLDS", "DEFAULT_LEDGER_KEEP"]

# --compact bound: newest rows kept per (scenario, mode) partition
# (override with PTPU_LEDGER_KEEP)
DEFAULT_LEDGER_KEEP = 256

# regression/quality thresholds the gate and the ci.sh smokes enforce.
# These are the previously hard-coded ci.sh constants, moved behind the
# golden so a recalibration is a --write-golden diff, not a script edit.
DEFAULT_THRESHOLDS: Dict[str, float] = {
    # >10% step-time p50 growth vs golden fails the perf tier (strictly
    # greater: exactly 10% passes)
    "step_time_regression_frac": 0.10,
    # fused-block A/B: fused leg must not be slower than unfused
    "fused_block_min_speedup": 1.0,
    # comm A/B: int8+EF wire compression and loss-fidelity bounds,
    # ZeRO-1 loss bound and per-replica state shrink factor
    "comm_min_compress_ratio": 3.0,
    "comm_int8_max_loss_rel": 0.01,
    "comm_zero1_max_loss_rel": 1e-4,
    "comm_zero1_min_state_shrink": 4.0,
    # MFU microscope (ISSUE 19): the modeled-vs-measured reconciliation
    # bound — |roofline residual| must stay under this fraction of the
    # measured step p50 on every smoke row (enforced by
    # `python -m paddle_tpu.observability.roofline` in the perf tier)
    "roofline_max_residual_frac": 0.35,
    # Interconnect microscope (ISSUE 20): bound on the |(unattributed)|
    # share of a nonzero comm bucket (enforced by
    # `python -m paddle_tpu.observability.interconnect`); 1.0 = advisory
    # only by default — trace-time collective observation legitimately
    # attributes ~nothing on jitted CPU smokes, so tightening this is a
    # per-deployment golden decision, not a universal one
    "interconnect_max_unattributed_frac": 1.0,
}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_ledger_path() -> str:
    return os.path.join(_repo_root(), "benchmarks", "ledger.jsonl")


def default_golden_path() -> str:
    return os.path.join(_repo_root(), "benchmarks", "golden.json")


def append_row(row: Dict[str, Any],
               path: Optional[str] = None) -> str:
    """Validate + append one row; returns the ledger path.

    Raises ``ValueError`` on a schema violation — an invalid row must
    fail the producer, never poison the history.
    """
    errors = validate_row(row)
    if errors:
        raise ValueError(f"invalid ledger row for scenario "
                         f"{row.get('scenario') if isinstance(row, dict) else row!r}: "
                         + "; ".join(errors))
    path = path or default_ledger_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.append_bytes(path, (json.dumps(row, sort_keys=False)
                             + "\n").encode("utf-8"))
    return path


def read_ledger(path: Optional[str] = None,
                drops: Optional[Dict[str, int]] = None
                ) -> List[Dict[str, Any]]:
    """All readable rows, oldest first, with
    ``read_worker_stream``-style torn-line / foreign-schema tolerance
    (``drops`` accumulates ``torn_lines`` / ``unknown_schema``)."""
    if drops is None:
        drops = {}
    drops.setdefault("torn_lines", 0)
    drops.setdefault("unknown_schema", 0)
    path = path or default_ledger_path()
    try:
        raw = fsio.read_bytes(path)
    except OSError:
        return []
    rows: List[Dict[str, Any]] = []
    for line in raw.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            drops["torn_lines"] += 1
            continue  # torn tail from a mid-append death
        if not isinstance(rec, dict):
            drops["torn_lines"] += 1
            continue
        if rec.get("schema_version",
                   SCHEMA_VERSION) not in KNOWN_SCHEMA_VERSIONS:
            drops["unknown_schema"] += 1
            continue
        rows.append(rec)
    return rows


def latest_rows(rows: List[Dict[str, Any]],
                mode: Optional[str] = None
                ) -> Dict[str, Dict[str, Any]]:
    """Newest row per scenario (ledger order; optionally one mode)."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        if mode is not None and r.get("mode") != mode:
            continue
        name = r.get("scenario")
        if isinstance(name, str):
            out[name] = r
    return out


def read_series(scenario: str, mode: str, metric: str = "step_p50", *,
                path: Optional[str] = None,
                rows: Optional[List[Dict[str, Any]]] = None,
                partition: Optional[str] = None,
                dedupe_sha: bool = True) -> List[Dict[str, Any]]:
    """The trend engine's series view of the ledger (ISSUE 14): one
    scenario/mode/metric as an oldest-first list of points
    ``{"sha", "ts", "value", "row"}``.

    - **fingerprint-partitioned**: only rows whose
      :func:`schema.fingerprint_key` matches ``partition`` (default: the
      partition of the scenario's newest row) enter the series — a
      CPU-smoke point never mixes into a TPU series;
    - **sha-deduped** (``dedupe_sha=True``): when one commit produced
      several rows (CI reruns), the newest row wins — the series is
      indexed by commit, which is what changepoint → sha-range
      attribution needs.  Rows without a ``git_sha`` are kept as-is.
      Pass ``dedupe_sha=False`` for run-level statistics (the
      noise-aware gate wants rerun jitter, not one point per commit);
    - rows whose ``metric`` field is absent/null are skipped.
    """
    if rows is None:
        rows = read_ledger(path)
    cand = [r for r in rows
            if r.get("scenario") == scenario and r.get("mode") == mode]
    cand.sort(key=lambda r: (r.get("ts") or 0.0))
    if not cand:
        return []
    if partition is None:
        partition = fingerprint_key(cand[-1])
    cand = [r for r in cand if fingerprint_key(r) == partition]
    if dedupe_sha:
        newest_at: Dict[str, int] = {}
        for i, r in enumerate(cand):
            sha = r.get("git_sha")
            if isinstance(sha, str):
                newest_at[sha] = i          # later index = newer row wins
        cand = [r for i, r in enumerate(cand)
                if not isinstance(r.get("git_sha"), str)
                or newest_at[r["git_sha"]] == i]
    points = []
    for r in cand:
        v = metric_value(r, metric)
        if v is None:
            continue
        points.append({"sha": r.get("git_sha"), "ts": r.get("ts"),
                       "value": v, "row": r})
    return points


def compact_ledger(path: Optional[str] = None,
                   keep: Optional[int] = None) -> Tuple[int, int]:
    """Bound per-(scenario, mode) history to the newest ``keep`` rows
    (default ``PTPU_LEDGER_KEEP``, else :data:`DEFAULT_LEDGER_KEEP`),
    rewriting the ledger atomically in original order.  Torn/foreign
    lines are dropped by the rewrite (they were invisible to readers
    anyway).  Returns ``(kept, dropped)`` row counts."""
    if keep is None:
        keep = int(os.environ.get("PTPU_LEDGER_KEEP", DEFAULT_LEDGER_KEEP))
    if keep < 1:
        raise ValueError(f"PTPU_LEDGER_KEEP must be >= 1, got {keep}")
    path = path or default_ledger_path()
    rows = read_ledger(path)
    per_key: Dict[Tuple[str, str], int] = {}
    for r in rows:
        k = (str(r.get("scenario")), str(r.get("mode")))
        per_key[k] = per_key.get(k, 0) + 1
    seen: Dict[Tuple[str, str], int] = {}
    kept: List[Dict[str, Any]] = []
    for r in rows:                      # ledger order ≈ oldest first
        k = (str(r.get("scenario")), str(r.get("mode")))
        seen[k] = seen.get(k, 0) + 1
        if per_key[k] - seen[k] < keep:     # one of the newest `keep`
            kept.append(r)
    if len(kept) != len(rows):
        payload = "".join(json.dumps(r, sort_keys=False) + "\n"
                          for r in kept)
        fsio.atomic_write_bytes(path, payload.encode("utf-8"))
    return len(kept), len(rows) - len(kept)


def load_golden(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The checked-in baseline, or None when absent/unreadable."""
    path = path or default_golden_path()
    try:
        payload = json.loads(fsio.read_bytes(path))
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or "scenarios" not in payload:
        return None
    payload.setdefault("thresholds", {})
    return payload


def golden_from_rows(rows_by_scenario: Dict[str, Dict[str, Any]],
                     thresholds: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """Assemble a golden payload from the blessed rows."""
    thr = dict(DEFAULT_THRESHOLDS)
    thr.update(thresholds or {})
    return {
        "schema_version": SCHEMA_VERSION,
        "thresholds": thr,
        "scenarios": {name: row for name, row
                      in sorted(rows_by_scenario.items())},
    }


def write_golden(golden: Dict[str, Any],
                 path: Optional[str] = None) -> str:
    path = path or default_golden_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.atomic_write_bytes(
        path, json.dumps(golden, indent=1, sort_keys=False,
                         default=str).encode("utf-8"))
    return path


def threshold(golden: Optional[Dict[str, Any]], name: str) -> float:
    """One threshold, golden override first, defaults second."""
    thr = (golden or {}).get("thresholds") or {}
    v = thr.get(name, DEFAULT_THRESHOLDS.get(name))
    if v is None:
        raise KeyError(f"unknown threshold {name!r}")
    return float(v)


__all__.append("threshold")


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m paddle_tpu.bench.ledger`` — inspect / compact the
    ledger.  ``--compact`` bounds per-(scenario, mode) history to the
    newest ``--keep`` (default ``PTPU_LEDGER_KEEP``) rows."""
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.bench.ledger",
        description="perf ledger maintenance: summarize row counts, "
                    "or --compact to bound per-scenario history")
    ap.add_argument("--ledger", default=None, help="ledger path override")
    ap.add_argument("--compact", action="store_true",
                    help="rewrite the ledger keeping only the newest "
                         "--keep rows per (scenario, mode)")
    ap.add_argument("--keep", type=int, default=None,
                    help="history bound (default PTPU_LEDGER_KEEP, "
                         f"else {DEFAULT_LEDGER_KEEP})")
    args = ap.parse_args(argv)
    path = args.ledger or default_ledger_path()
    if args.compact:
        kept, dropped = compact_ledger(path, keep=args.keep)
        print(f"ledger: kept {kept} row(s), dropped {dropped} -> {path}")  # noqa: print — CLI report
        return 0
    drops: Dict[str, int] = {}
    rows = read_ledger(path, drops=drops)
    per_key: Dict[Tuple[str, str], int] = {}
    for r in rows:
        k = (str(r.get("scenario")), str(r.get("mode")))
        per_key[k] = per_key.get(k, 0) + 1
    print(f"ledger: {len(rows)} row(s) at {path} "  # noqa: print — CLI report
          f"(skipped {drops['torn_lines']} torn / "
          f"{drops['unknown_schema']} foreign-schema)")
    for (scenario, mode), n in sorted(per_key.items()):
        print(f"  {scenario:<22} {mode:<6} {n:4d} row(s)")  # noqa: print — CLI report
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
