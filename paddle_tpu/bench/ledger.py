"""Append-only perf ledger + checked-in golden (ISSUE 13).

``benchmarks/ledger.jsonl`` is the observatory's history: one line per
scenario run, append-only through ``utils/fsio.append_bytes`` (fsync'd;
a mid-append death costs one torn line, never the file).  The reader
carries the exact torn-tail semantics of
``observability.aggregate.read_worker_stream``: unparseable lines and
foreign ``schema_version`` rows are skipped with drop accounting, so a
ledger written by a newer tree stays readable by older tooling.

``benchmarks/golden.json`` is the enforcement baseline: the blessed row
per scenario plus the ``thresholds`` table the CI gate (and the ci.sh
A/B smokes) read — updated only through the explicit ``--write-golden``
workflow, mirroring ptlint's baseline file.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..utils import fsio
from .schema import KNOWN_SCHEMA_VERSIONS, SCHEMA_VERSION, validate_row

__all__ = ["default_ledger_path", "default_golden_path", "append_row",
           "read_ledger", "latest_rows", "load_golden", "write_golden",
           "golden_from_rows", "DEFAULT_THRESHOLDS"]

# regression/quality thresholds the gate and the ci.sh smokes enforce.
# These are the previously hard-coded ci.sh constants, moved behind the
# golden so a recalibration is a --write-golden diff, not a script edit.
DEFAULT_THRESHOLDS: Dict[str, float] = {
    # >10% step-time p50 growth vs golden fails the perf tier (strictly
    # greater: exactly 10% passes)
    "step_time_regression_frac": 0.10,
    # fused-block A/B: fused leg must not be slower than unfused
    "fused_block_min_speedup": 1.0,
    # comm A/B: int8+EF wire compression and loss-fidelity bounds,
    # ZeRO-1 loss bound and per-replica state shrink factor
    "comm_min_compress_ratio": 3.0,
    "comm_int8_max_loss_rel": 0.01,
    "comm_zero1_max_loss_rel": 1e-4,
    "comm_zero1_min_state_shrink": 4.0,
}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_ledger_path() -> str:
    return os.path.join(_repo_root(), "benchmarks", "ledger.jsonl")


def default_golden_path() -> str:
    return os.path.join(_repo_root(), "benchmarks", "golden.json")


def append_row(row: Dict[str, Any],
               path: Optional[str] = None) -> str:
    """Validate + append one row; returns the ledger path.

    Raises ``ValueError`` on a schema violation — an invalid row must
    fail the producer, never poison the history.
    """
    errors = validate_row(row)
    if errors:
        raise ValueError(f"invalid ledger row for scenario "
                         f"{row.get('scenario') if isinstance(row, dict) else row!r}: "
                         + "; ".join(errors))
    path = path or default_ledger_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.append_bytes(path, (json.dumps(row, sort_keys=False)
                             + "\n").encode("utf-8"))
    return path


def read_ledger(path: Optional[str] = None,
                drops: Optional[Dict[str, int]] = None
                ) -> List[Dict[str, Any]]:
    """All readable rows, oldest first, with
    ``read_worker_stream``-style torn-line / foreign-schema tolerance
    (``drops`` accumulates ``torn_lines`` / ``unknown_schema``)."""
    if drops is None:
        drops = {}
    drops.setdefault("torn_lines", 0)
    drops.setdefault("unknown_schema", 0)
    path = path or default_ledger_path()
    try:
        raw = fsio.read_bytes(path)
    except OSError:
        return []
    rows: List[Dict[str, Any]] = []
    for line in raw.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            drops["torn_lines"] += 1
            continue  # torn tail from a mid-append death
        if not isinstance(rec, dict):
            drops["torn_lines"] += 1
            continue
        if rec.get("schema_version",
                   SCHEMA_VERSION) not in KNOWN_SCHEMA_VERSIONS:
            drops["unknown_schema"] += 1
            continue
        rows.append(rec)
    return rows


def latest_rows(rows: List[Dict[str, Any]],
                mode: Optional[str] = None
                ) -> Dict[str, Dict[str, Any]]:
    """Newest row per scenario (ledger order; optionally one mode)."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        if mode is not None and r.get("mode") != mode:
            continue
        name = r.get("scenario")
        if isinstance(name, str):
            out[name] = r
    return out


def load_golden(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The checked-in baseline, or None when absent/unreadable."""
    path = path or default_golden_path()
    try:
        payload = json.loads(fsio.read_bytes(path))
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or "scenarios" not in payload:
        return None
    payload.setdefault("thresholds", {})
    return payload


def golden_from_rows(rows_by_scenario: Dict[str, Dict[str, Any]],
                     thresholds: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """Assemble a golden payload from the blessed rows."""
    thr = dict(DEFAULT_THRESHOLDS)
    thr.update(thresholds or {})
    return {
        "schema_version": SCHEMA_VERSION,
        "thresholds": thr,
        "scenarios": {name: row for name, row
                      in sorted(rows_by_scenario.items())},
    }


def write_golden(golden: Dict[str, Any],
                 path: Optional[str] = None) -> str:
    path = path or default_golden_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.atomic_write_bytes(
        path, json.dumps(golden, indent=1, sort_keys=False,
                         default=str).encode("utf-8"))
    return path


def threshold(golden: Optional[Dict[str, Any]], name: str) -> float:
    """One threshold, golden override first, defaults second."""
    thr = (golden or {}).get("thresholds") or {}
    v = thr.get(name, DEFAULT_THRESHOLDS.get(name))
    if v is None:
        raise KeyError(f"unknown threshold {name!r}")
    return float(v)


__all__.append("threshold")
