"""``python -m paddle_tpu.bench`` — run the scenario matrix.

Each selected scenario emits one validated row: appended to the ledger
(unless ``--no-append``) and printed to stdout as JSONL (stdout carries
only rows; diagnostics go to stderr, same contract as bench.py).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import scenarios
from .runner import run_scenarios


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.bench",
        description="performance observatory: run the scenario matrix "
                    "and append one ledger row per scenario")
    ap.add_argument("--all", action="store_true",
                    help="run every registered scenario")
    ap.add_argument("--scenario", action="append", default=[],
                    metavar="NAME", help="run one scenario (repeatable)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-sized smoke shapes (default)")
    ap.add_argument("--full", action="store_true",
                    help="the real BASELINE shapes (TPU-sized)")
    ap.add_argument("--ledger", default=None,
                    help="ledger path override")
    ap.add_argument("--no-append", action="store_true",
                    help="print rows without touching the ledger")
    ap.add_argument("--list", action="store_true",
                    help="list registered scenarios and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name in scenarios.names():
            doc = (scenarios.get(name).__doc__ or "").strip()
            print(f"{name:<22} {doc.splitlines()[0] if doc else ''}")  # noqa: print
        return 0
    names = list(args.scenario) if args.scenario else None
    if not args.all and not names:
        ap.error("pick --all or at least one --scenario NAME "
                 "(see --list)")
    mode = "full" if args.full else "smoke"
    rows = run_scenarios(names, mode=mode, ledger_path=args.ledger,
                         append=not args.no_append)
    for row in rows:
        sys.stdout.write(json.dumps(row) + "\n")
    sys.stdout.flush()
    want = len(names) if names else len(scenarios.names())
    return 0 if len(rows) == want else 1


if __name__ == "__main__":
    raise SystemExit(main())
