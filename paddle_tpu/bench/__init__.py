"""Performance observatory (ISSUE 13; ROADMAP 5b).

The scenario matrix that replaces bench.py's monolith: every workload
family the repo claims speed on (GPT pretrain fused/unfused, MoE,
long-context sequence-parallel, ResNet/MNIST vision, serve-mode decode)
runs under one measurement discipline and emits ONE schema-versioned
row into the append-only ``benchmarks/ledger.jsonl``.

Layout::

    schema.py     row schema v1: fingerprint, phase breakdown, validate
    ledger.py     append-only ledger + golden + series view + --compact
    harness.py    phase-timed step loop, compile window, bytes-on-wire
    scenarios.py  the registered workload matrix
    runner.py     scenario → row assembly → ledger append
    diff.py       perfdiff: row-vs-row / golden / trailing-median
    gate.py       the CI perf tier (noise-aware; --write-golden)
    trends.py     series model: noise floors, changepoints, drift (14)
    report.py     self-contained HTML dashboard (inline SVG) (14)

Entry points::

    python -m paddle_tpu.bench --all --smoke     # run matrix, append rows
    python -m paddle_tpu.bench.diff              # attribute a regression
    python -m paddle_tpu.bench.gate              # enforce, noise-aware
    python -m paddle_tpu.bench.trends            # series report
    python -m paddle_tpu.bench.report            # HTML dashboard
    python -m paddle_tpu.bench.ledger --compact  # bound history
"""
from __future__ import annotations

from . import harness, ledger, schema
from .ledger import (DEFAULT_LEDGER_KEEP, DEFAULT_THRESHOLDS, append_row,
                     compact_ledger, default_golden_path,
                     default_ledger_path, latest_rows, load_golden,
                     read_ledger, read_series, threshold, write_golden)
from .schema import (KNOWN_SCHEMA_VERSIONS, METRICS, PHASES,
                     SCHEMA_VERSION, fingerprint_key, metric_value,
                     new_row, validate_row)

__all__ = [
    "schema", "ledger", "harness",
    "SCHEMA_VERSION", "KNOWN_SCHEMA_VERSIONS", "PHASES", "METRICS",
    "new_row", "validate_row", "fingerprint_key", "metric_value",
    "append_row", "read_ledger", "latest_rows", "read_series",
    "compact_ledger", "load_golden",
    "write_golden", "threshold", "default_ledger_path",
    "default_golden_path", "DEFAULT_THRESHOLDS", "DEFAULT_LEDGER_KEEP",
    "run_scenarios",
]


def run_scenarios(*args, **kwargs):
    """Lazy forward to :func:`runner.run_scenarios` (the runner imports
    jax-heavy scenario code; keep ``import paddle_tpu.bench`` light for
    tooling that only reads the ledger)."""
    from .runner import run_scenarios as _run
    return _run(*args, **kwargs)
