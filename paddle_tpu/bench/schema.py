"""Ledger row schema (ISSUE 13).

Every scenario the observatory runs emits exactly ONE row shaped like
this, so rows from different scenarios, machines, and months are
comparable by construction:

- ``schema_version`` — bumped on any incompatible shape change; the
  reader drops foreign versions with accounting instead of mis-parsing
  them (the same doctrine as ``observability/aggregate.py``);
- ``fingerprint`` + ``git_sha`` — where the number came from: device
  kind/count, jax/python versions, the commit that produced it;
- ``device_kind`` / ``fallback_reason`` — the row is self-describing
  about *what hardware actually ran* (a TPU-unreachable CPU fallback is
  a field, not a stderr note);
- ``step_time_ms`` p50/p99 plus the ``phases_ms`` breakdown
  (data / compute / readback / collective) — the axes perfdiff
  attributes a regression to;
- ``compile`` — wall + trace counts from the PR 4 tracker and
  persistent-cache hit/miss from ``observability/compilecache``;
- ``tokens_per_sec`` / ``mfu`` — through the shared
  ``observability/mfu`` definitions (never a per-scenario formula);
- ``bytes_on_wire`` — the comm package's trace-time accounting (PR 8);
- ``extra`` — scenario-specific figures (img/s, TTFT/TPOT, ...) that
  must not leak into the comparable core.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

__all__ = ["SCHEMA_VERSION", "KNOWN_SCHEMA_VERSIONS", "PHASES", "METRICS",
           "CORE_METRICS", "GAP_SINKS", "GAP_METRICS", "COMM_METRICS",
           "fingerprint", "fingerprint_key", "metric_value", "new_row",
           "validate_row"]

# v2 (ISSUE 19): every row carries a ``roofline`` MFU-gap budget block
# whose buckets (with residual) sum to the measured step p50; v1 rows
# remain readable — gap axes are simply None on them.
# v3 (ISSUE 20): every row additionally carries an ``interconnect``
# per-collective sub-budget whose entries (with the signed
# "(unattributed)" remainder) sum to the roofline ``comm`` bucket
# exactly; v1/v2 rows remain readable — comm axes are None on them.
SCHEMA_VERSION = 3
KNOWN_SCHEMA_VERSIONS = (1, 2, 3)

# the step-time decomposition perfdiff attributes regressions to; every
# row carries all four (0.0 when a scenario has no such phase)
PHASES = ("data", "compute", "readback", "collective")

# the MFU-gap sink taxonomy (ISSUE 19) — a literal mirror of
# ``observability.roofline.SINKS`` (pinned equal by a test) so this
# module never imports the roofline at module scope
GAP_SINKS = ("mxu", "memory_bound", "comm", "host", "padding",
             "unknown_device", "residual")

# the original five metric axes — what the report's sparkline table
# shows; the gap axes below join them in the full trendable set
CORE_METRICS = ("step_p50", "mfu", "compile_wall_ms", "bytes_on_wire",
                "peak_hbm_bytes")

# per-sink gap axes (mxu excluded — it is the useful part, not a gap)
# plus the attribution-honesty coverage gauge
GAP_METRICS = tuple("gap_%s_ms" % s for s in GAP_SINKS if s != "mxu") \
    + ("roofline_coverage",)

# per-collective comm axes (ISSUE 20): the modeled wire time of the
# attributed entries, the XLA-overlap estimate, and the honesty gauge —
# how much of the comm bucket no (op, axis) claims
COMM_METRICS = ("comm_modeled_ms", "comm_overlapped_ms",
                "comm_unattributed_ms")

# the metric axes the trend engine models as per-scenario series
# (ISSUE 14); each maps to one numeric field of the row via
# :func:`metric_value`
METRICS = CORE_METRICS + GAP_METRICS + COMM_METRICS

_MODES = ("smoke", "full")


def metric_value(row: Dict[str, Any], metric: str) -> Optional[float]:
    """One :data:`METRICS` axis out of a row (None when the row doesn't
    carry it — e.g. ``mfu`` on a vision scenario)."""
    if metric == "step_p50":
        v = (row.get("step_time_ms") or {}).get("p50")
    elif metric == "mfu":
        v = row.get("mfu")
    elif metric == "compile_wall_ms":
        v = (row.get("compile") or {}).get("wall_ms")
    elif metric == "bytes_on_wire":
        v = row.get("bytes_on_wire")
    elif metric == "peak_hbm_bytes":
        v = row.get("peak_hbm_bytes")
    elif metric == "roofline_coverage":
        v = (row.get("roofline") or {}).get("coverage")
    elif metric == "comm_modeled_ms":
        v = (row.get("interconnect") or {}).get("modeled_ms_total")
    elif metric == "comm_overlapped_ms":
        v = (row.get("interconnect") or {}).get("overlapped_ms")
    elif metric == "comm_unattributed_ms":
        v = (row.get("interconnect") or {}).get("unattributed_ms")
    elif metric.startswith("gap_") and metric.endswith("_ms"):
        sink = metric[len("gap_"):-len("_ms")]
        if sink not in GAP_SINKS:
            raise KeyError(f"unknown metric {metric!r}; have {METRICS}")
        v = ((row.get("roofline") or {}).get("buckets_ms") or {}).get(sink)
    else:
        raise KeyError(f"unknown metric {metric!r}; have {METRICS}")
    return float(v) if isinstance(v, (int, float)) else None


def fingerprint_key(row: Dict[str, Any]) -> str:
    """The series-partition key (ISSUE 14): rows from different hardware
    or device counts never mix into one trend series — a CPU-smoke point
    in a TPU series would read as a catastrophic changepoint."""
    fp = row.get("fingerprint") or {}
    return "%s/%s/x%s" % (fp.get("platform", "?"),
                          fp.get("device_kind", row.get("device_kind", "?")),
                          fp.get("device_count", "?"))


def _git_sha() -> Optional[str]:
    """Commit of the tree that produced the row (None outside a repo)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def fingerprint() -> Dict[str, Any]:
    """Device / software environment stamp for one row."""
    import jax
    dev = jax.devices()[0]
    return {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "device_count": jax.device_count(),
        "jax": jax.__version__,
        "python": "%d.%d.%d" % sys.version_info[:3],
    }


def new_row(scenario: str, mode: str, *,
            step_times_ms: List[float],
            phases_ms: Dict[str, float],
            config: Optional[Dict[str, Any]] = None,
            tokens_per_sec: Optional[float] = None,
            mfu: Optional[float] = None,
            compile_stats: Optional[Dict[str, Any]] = None,
            bytes_on_wire: int = 0,
            peak_hbm_bytes: Optional[int] = None,
            fallback_reason: Optional[str] = None,
            roofline: Optional[Dict[str, Any]] = None,
            interconnect: Optional[Dict[str, Any]] = None,
            extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble one schema-v3 row from a scenario's measurements.

    ``step_times_ms`` is the raw per-step series (percentiles are
    computed here so every scenario uses the same definition);
    ``phases_ms`` maps each :data:`PHASES` entry to its per-step p50.
    ``roofline`` is the MFU-gap budget block from a capture window; when
    omitted, a degraded phase-only block is synthesized so every row
    still carries buckets that sum to the measured step time.
    ``interconnect`` is the per-collective sub-budget of the roofline's
    ``comm`` bucket; when omitted, a degraded all-unattributed block is
    synthesized so the v3 sum invariant holds for every producer.
    """
    times = sorted(float(t) for t in step_times_ms)

    def pct(p: float) -> Optional[float]:
        if not times:
            return None
        idx = min(len(times) - 1,
                  max(0, int(round(p / 100.0 * (len(times) - 1)))))
        return times[idx]

    fp = fingerprint()
    if roofline is None:
        from ..observability.roofline import degraded_block
        roofline = degraded_block(
            pct(50) or 0.0,
            {p: float(phases_ms.get(p, 0.0) or 0.0) for p in PHASES},
            padding_frac=float((extra or {}).get("padding_frac") or 0.0),
            reason="producer passed no roofline block")
    if interconnect is None:
        from ..observability import interconnect as ic
        interconnect = ic.degraded_block(
            float(((roofline or {}).get("buckets_ms") or {}).get("comm")
                  or 0.0),
            reason="producer passed no interconnect block")
    row: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "scenario": str(scenario),
        "mode": str(mode),
        "ts": time.time(),
        "git_sha": _git_sha(),
        "device_kind": fp["device_kind"],
        "fallback_reason": fallback_reason,
        "fingerprint": fp,
        "config": dict(config or {}),
        "steps": len(times),
        "step_time_ms": {"p50": pct(50), "p99": pct(99),
                         "mean": (sum(times) / len(times)) if times
                         else None,
                         "min": (times[0] if times else None)},
        "phases_ms": {p: float(phases_ms.get(p, 0.0) or 0.0)
                      for p in PHASES},
        "tokens_per_sec": tokens_per_sec,
        "mfu": mfu,
        "compile": dict(compile_stats or {}),
        "bytes_on_wire": int(bytes_on_wire),
        "peak_hbm_bytes": (None if peak_hbm_bytes is None
                           else int(peak_hbm_bytes)),
        "roofline": roofline,
        "interconnect": interconnect,
        "extra": dict(extra or {}),
    }
    return row


def validate_row(row: Any) -> List[str]:
    """Schema check; returns the list of violations (empty = valid).

    Mirrors the reader-side doctrine: a row that fails here must never
    reach the ledger, so every row IN the ledger is loadable by tooling
    of the same schema generation.
    """
    errors: List[str] = []
    if not isinstance(row, dict):
        return ["row is not an object"]
    if row.get("schema_version") not in KNOWN_SCHEMA_VERSIONS:
        errors.append(f"unknown schema_version "
                      f"{row.get('schema_version')!r}")
    if not row.get("scenario") or not isinstance(row.get("scenario"), str):
        errors.append("missing/invalid scenario")
    if row.get("mode") not in _MODES:
        errors.append(f"mode must be one of {_MODES}, "
                      f"got {row.get('mode')!r}")
    if not isinstance(row.get("ts"), (int, float)):
        errors.append("missing/invalid ts")
    if not isinstance(row.get("device_kind"), str):
        errors.append("missing/invalid device_kind")
    fr = row.get("fallback_reason")
    if fr is not None and not isinstance(fr, str):
        errors.append("fallback_reason must be null or a string")
    fp = row.get("fingerprint")
    if not isinstance(fp, dict):
        errors.append("missing fingerprint")
    else:
        for k in ("platform", "device_count", "jax"):
            if k not in fp:
                errors.append(f"fingerprint missing {k!r}")
    st = row.get("step_time_ms")
    if not isinstance(st, dict) or not isinstance(
            st.get("p50"), (int, float)):
        errors.append("step_time_ms.p50 missing (no timed steps?)")
    elif not isinstance(st.get("p99"), (int, float)):
        errors.append("step_time_ms.p99 missing")
    ph = row.get("phases_ms")
    if not isinstance(ph, dict):
        errors.append("missing phases_ms")
    else:
        for p in PHASES:
            if not isinstance(ph.get(p), (int, float)):
                errors.append(f"phases_ms.{p} missing/invalid")
    comp = row.get("compile")
    if not isinstance(comp, dict):
        errors.append("missing compile stats")
    if not isinstance(row.get("bytes_on_wire"), int):
        errors.append("bytes_on_wire must be an int")
    for opt_num in ("tokens_per_sec", "mfu"):
        v = row.get(opt_num)
        if v is not None and not isinstance(v, (int, float)):
            errors.append(f"{opt_num} must be null or a number")
    if not isinstance(row.get("extra", {}), dict):
        errors.append("extra must be an object")
    if row.get("schema_version") in (2, 3):
        errors.extend(_validate_roofline(row))
    if row.get("schema_version") == 3:
        errors.extend(_validate_interconnect(row))
    return errors


def _validate_roofline(row: Dict[str, Any]) -> List[str]:
    """The v2 contract: a complete gap-bucket set whose values (with
    residual) sum to the block's measured step time — a roofline block
    that doesn't reconcile with itself must never reach the ledger."""
    errors: List[str] = []
    rl = row.get("roofline")
    if not isinstance(rl, dict):
        return ["schema v2 row missing roofline block"]
    measured = rl.get("measured_step_ms")
    if not isinstance(measured, (int, float)):
        errors.append("roofline.measured_step_ms missing/invalid")
        measured = None
    buckets = rl.get("buckets_ms")
    if not isinstance(buckets, dict):
        errors.append("roofline.buckets_ms missing")
    else:
        total = 0.0
        complete = True
        for s in GAP_SINKS:
            v = buckets.get(s)
            if not isinstance(v, (int, float)):
                errors.append(f"roofline.buckets_ms.{s} missing/invalid")
                complete = False
            else:
                total += float(v)
        if complete and measured is not None:
            tol = max(0.01, 0.005 * abs(float(measured)))
            if abs(total - float(measured)) > tol:
                errors.append(
                    "roofline buckets sum %.4fms != measured %.4fms"
                    % (total, float(measured)))
    cov = rl.get("coverage")
    if not isinstance(cov, (int, float)) or not (0.0 <= cov <= 1.0):
        errors.append("roofline.coverage must be in [0, 1]")
    if rl.get("dominant_sink") not in GAP_SINKS:
        errors.append("roofline.dominant_sink must be one of GAP_SINKS")
    dev = rl.get("device")
    if not isinstance(dev, dict) or not isinstance(
            dev.get("known"), bool):
        errors.append("roofline.device.known missing/invalid")
    return errors


def _validate_interconnect(row: Dict[str, Any]) -> List[str]:
    """The v3 contract: a per-collective entry list (with the signed
    ``"(unattributed)"`` remainder) that sums to the block's comm
    bucket, which in turn equals the roofline ``comm`` bucket — a
    sub-budget that doesn't reconcile with its parent must never reach
    the ledger."""
    errors: List[str] = []
    ic = row.get("interconnect")
    if not isinstance(ic, dict):
        return ["schema v3 row missing interconnect block"]
    bucket = ic.get("comm_bucket_ms")
    if not isinstance(bucket, (int, float)):
        errors.append("interconnect.comm_bucket_ms missing/invalid")
        bucket = None
    entries = ic.get("entries")
    if not isinstance(entries, list) or not entries:
        errors.append("interconnect.entries missing/empty")
    else:
        total = 0.0
        complete = True
        for i, e in enumerate(entries):
            if not isinstance(e, dict) or not isinstance(
                    e.get("measured_ms"), (int, float)):
                errors.append(
                    f"interconnect.entries[{i}].measured_ms "
                    f"missing/invalid")
                complete = False
                continue
            total += float(e["measured_ms"])
        if complete and bucket is not None:
            tol = max(0.01, 0.005 * abs(float(bucket)))
            if abs(total - float(bucket)) > tol:
                errors.append(
                    "interconnect entries sum %.4fms != comm bucket "
                    "%.4fms" % (total, float(bucket)))
    rl_comm = ((row.get("roofline") or {}).get("buckets_ms")
               or {}).get("comm")
    if (bucket is not None and isinstance(rl_comm, (int, float))
            and abs(float(bucket) - float(rl_comm))
            > max(0.01, 0.005 * abs(float(rl_comm)))):
        errors.append(
            "interconnect.comm_bucket_ms %.4fms != roofline comm "
            "bucket %.4fms" % (float(bucket), float(rl_comm)))
    dev = ic.get("device")
    if not isinstance(dev, dict) or not isinstance(
            dev.get("known"), bool):
        errors.append("interconnect.device.known missing/invalid")
    return errors
