"""perfdiff (ISSUE 13): compare two ledger rows and say *why* they
differ, not just that they do.

A step-time regression is only actionable once it is attributed to the
phase that moved — compile wall (one-time, its own axis), data wait,
compute, collective, or readback.  ``attribute`` computes per-phase
deltas from the rows' ``phases_ms`` breakdown and ranks the movers;
``render`` prints the doctor-style report the CI gate shows on failure.

CLI::

    python -m paddle_tpu.bench.diff ROW_A.json ROW_B.json
    python -m paddle_tpu.bench.diff --golden [--scenario gpt_pretrain_fused]
    python -m paddle_tpu.bench.diff --baseline median:8   # vs trailing median

``--baseline median:N`` (ISSUE 14) compares each scenario's newest
ledger row against the **median pseudo-row of its trailing N prior
rows** instead of a single (possibly noisy) golden or prior row — the
same baseline the noise-aware gate enforces against.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, List, Optional

from ..utils import fsio
from . import ledger
from .schema import GAP_SINKS, PHASES

__all__ = ["attribute", "diff_rows", "render", "main"]

# human phrasing per phase for the report's remedy line
_PHASE_HINTS = {
    "data": "host input pipeline (batch production) slowed — check "
            "tokenizer/augment work and PTPU_DATA_* staging",
    "compute": "on-device step math slowed — check fusion flags, dtype, "
               "and recent kernel changes",
    "readback": "device→host sync slowed — check what the step returns "
                "and tunnel latency",
    "collective": "cross-device traffic slowed — check compression tier "
                  "and topology (comm package)",
}


def _p50(row: Dict[str, Any]) -> Optional[float]:
    st = row.get("step_time_ms") or {}
    v = st.get("p50")
    return float(v) if isinstance(v, (int, float)) else None


def attribute(base: Dict[str, Any],
              cur: Dict[str, Any]) -> Dict[str, Any]:
    """Per-phase movement between two rows of the same scenario.

    Returns ``movers`` ranked by signed per-step delta (worst first),
    the ``dominant`` phase (largest positive delta, None when nothing
    grew), the ``unattributed`` remainder of the p50 delta the phase
    breakdown doesn't explain, and the compile-wall delta on its own
    axis (one-time cost, never part of the steady-state step).
    """
    base_ph = base.get("phases_ms") or {}
    cur_ph = cur.get("phases_ms") or {}
    movers: List[Dict[str, Any]] = []
    for p in PHASES:
        b = float(base_ph.get(p, 0.0) or 0.0)
        c = float(cur_ph.get(p, 0.0) or 0.0)
        movers.append({"phase": p, "base_ms": b, "cur_ms": c,
                       "delta_ms": c - b,
                       "ratio": (c / b) if b > 0 else None})
    movers.sort(key=lambda m: -m["delta_ms"])
    dominant = (movers[0]["phase"]
                if movers and movers[0]["delta_ms"] > 0 else None)
    b50, c50 = _p50(base), _p50(cur)
    total_delta = ((c50 - b50) if (b50 is not None and c50 is not None)
                   else None)
    explained = sum(m["delta_ms"] for m in movers)
    comp_b = float((base.get("compile") or {}).get("wall_ms", 0.0) or 0.0)
    comp_c = float((cur.get("compile") or {}).get("wall_ms", 0.0) or 0.0)
    out = {
        "movers": movers,
        "dominant": dominant,
        "step_p50_delta_ms": total_delta,
        "unattributed_ms": (None if total_delta is None
                            else total_delta - explained),
        "compile_wall_delta_ms": comp_c - comp_b,
    }
    # MFU-gap movers (ISSUE 19): only when *both* rows carry a roofline
    # block — doctor's regression check builds row-alikes without one,
    # and v1 rows predate the block entirely.
    base_gb = (base.get("roofline") or {}).get("buckets_ms")
    cur_gb = (cur.get("roofline") or {}).get("buckets_ms")
    if isinstance(base_gb, dict) and isinstance(cur_gb, dict):
        gap_movers: List[Dict[str, Any]] = []
        for s in GAP_SINKS:
            if s == "mxu":   # useful-work bucket, not a gap sink
                continue
            b = float(base_gb.get(s, 0.0) or 0.0)
            c = float(cur_gb.get(s, 0.0) or 0.0)
            gap_movers.append({"sink": s, "base_ms": b, "cur_ms": c,
                               "delta_ms": c - b,
                               "ratio": (c / b) if b > 0 else None})
        gap_movers.sort(key=lambda m: -m["delta_ms"])
        out["gap_movers"] = gap_movers
        out["gap_dominant"] = (gap_movers[0]["sink"]
                               if gap_movers and gap_movers[0]["delta_ms"] > 0
                               else None)
    # comm movers (ISSUE 20): per-(op, axis) exposed-comm deltas — only
    # when *both* rows carry interconnect entries (v3 rows); row-alikes
    # and v1/v2 rows skip the axis entirely.
    base_ic = (base.get("interconnect") or {}).get("entries")
    cur_ic = (cur.get("interconnect") or {}).get("entries")
    if isinstance(base_ic, list) and isinstance(cur_ic, list):
        def by_key(entries):
            keyed: Dict[tuple, float] = {}
            for e in entries:
                if isinstance(e, dict) and e.get("op"):
                    k = (str(e["op"]), e.get("axis"))
                    keyed[k] = keyed.get(k, 0.0) + float(
                        e.get("measured_ms") or 0.0)
            return keyed
        b_keyed, c_keyed = by_key(base_ic), by_key(cur_ic)
        comm_movers: List[Dict[str, Any]] = []
        for k in sorted(set(b_keyed) | set(c_keyed),
                        key=lambda k: (k[0], k[1] or "")):
            b, c = b_keyed.get(k, 0.0), c_keyed.get(k, 0.0)
            comm_movers.append({"op": k[0], "axis": k[1],
                                "base_ms": b, "cur_ms": c,
                                "delta_ms": c - b,
                                "ratio": (c / b) if b > 0 else None})
        comm_movers.sort(key=lambda m: -m["delta_ms"])
        out["comm_movers"] = comm_movers
        out["comm_dominant"] = (
            {"op": comm_movers[0]["op"], "axis": comm_movers[0]["axis"]}
            if comm_movers and comm_movers[0]["delta_ms"] > 0 else None)
    return out


def diff_rows(base: Dict[str, Any], cur: Dict[str, Any],
              threshold_frac: float = None) -> Dict[str, Any]:
    """Full comparison of two rows; ``regression`` is True when the
    current p50 is *strictly* above ``(1 + threshold) × base`` (exactly
    at the threshold passes — the gate's edge-case contract)."""
    if threshold_frac is None:
        threshold_frac = ledger.DEFAULT_THRESHOLDS[
            "step_time_regression_frac"]
    b50, c50 = _p50(base), _p50(cur)
    ratio = (c50 / b50) if (b50 and c50 is not None) else None
    regression = (b50 is not None and c50 is not None
                  and c50 > (1.0 + threshold_frac) * b50)
    return {
        "scenario": cur.get("scenario") or base.get("scenario"),
        "mode": cur.get("mode"),
        "base_p50_ms": b50,
        "cur_p50_ms": c50,
        "ratio": ratio,
        "threshold_frac": threshold_frac,
        "regression": regression,
        "attribution": attribute(base, cur),
        "base_sha": base.get("git_sha"),
        "cur_sha": cur.get("git_sha"),
        "base_device": base.get("device_kind"),
        "cur_device": cur.get("device_kind"),
    }


def _fmt_ms(v: Optional[float]) -> str:
    return "—" if v is None else f"{v:.2f}ms"


def render(report: Dict[str, Any]) -> str:
    """Doctor-style text: verdict line, ranked movers, remedy hint."""
    att = report["attribution"]
    lines: List[str] = []
    verdict = ("REGRESSION" if report["regression"] else "ok")
    ratio = report.get("ratio")
    lines.append(
        f"[{verdict}] {report['scenario']}: step p50 "
        f"{_fmt_ms(report['base_p50_ms'])} -> "
        f"{_fmt_ms(report['cur_p50_ms'])}"
        + (f"  ({ratio:.2f}x, threshold "
           f"{1.0 + report['threshold_frac']:.2f}x)"
           if ratio is not None else ""))
    if (report.get("base_device") and report.get("cur_device")
            and report["base_device"] != report["cur_device"]):
        lines.append(f"  ! devices differ: {report['base_device']} vs "
                     f"{report['cur_device']} — not comparable")
    lines.append("  movers (per-step phase delta, worst first):")
    for m in att["movers"]:
        mark = " <-- dominant" if m["phase"] == att["dominant"] else ""
        lines.append(
            f"    {m['phase']:<10} {_fmt_ms(m['base_ms'])} -> "
            f"{_fmt_ms(m['cur_ms'])}  ({m['delta_ms']:+.2f}ms){mark}")
    ua = att.get("unattributed_ms")
    if ua is not None:
        lines.append(f"    {'unattributed':<10} {ua:+.2f}ms "
                     "(p50 delta not explained by phases)")
    if att.get("gap_movers"):
        lines.append("  MFU-gap sinks (roofline bucket delta, worst "
                     "first):")
        for m in att["gap_movers"]:
            mark = (" <-- dominant"
                    if m["sink"] == att.get("gap_dominant") else "")
            lines.append(
                f"    {m['sink']:<14} {_fmt_ms(m['base_ms'])} -> "
                f"{_fmt_ms(m['cur_ms'])}  ({m['delta_ms']:+.2f}ms){mark}")
    if att.get("comm_movers"):
        lines.append("  exposed-comm collectives (per-(op, axis) delta, "
                     "worst first):")
        dom = att.get("comm_dominant") or {}
        for m in att["comm_movers"]:
            label = m["op"] + (f"[axis={m['axis']}]" if m["axis"] else "")
            mark = (" <-- dominant"
                    if (m["op"] == dom.get("op")
                        and m["axis"] == dom.get("axis")) else "")
            lines.append(
                f"    {label:<24} {_fmt_ms(m['base_ms'])} -> "
                f"{_fmt_ms(m['cur_ms'])}  ({m['delta_ms']:+.2f}ms){mark}")
    cw = att.get("compile_wall_delta_ms") or 0.0
    if abs(cw) > 1.0:
        lines.append(f"  compile wall moved {cw:+.0f}ms (one-time cost, "
                     "outside the step budget)")
    if report["regression"] and att["dominant"]:
        lines.append(f"  likely cause: "
                     f"{_PHASE_HINTS.get(att['dominant'], att['dominant'])}")
    if report.get("base_sha") or report.get("cur_sha"):
        lines.append(f"  base sha {report.get('base_sha') or '?'}  "
                     f"cur sha {report.get('cur_sha') or '?'}")
    return "\n".join(lines)


def _load_row_file(path: str) -> Dict[str, Any]:
    payload = json.loads(fsio.read_bytes(path))
    if not isinstance(payload, dict):
        raise ValueError(f"{path}: not a row object")
    return payload


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.bench.diff",
        description="perfdiff: attribute the difference between two "
                    "ledger rows (or latest ledger vs golden)")
    ap.add_argument("rows", nargs="*",
                    help="two row JSON files (base, then current)")
    ap.add_argument("--golden", action="store_true",
                    help="compare the newest ledger row per scenario "
                         "against benchmarks/golden.json")
    ap.add_argument("--ledger", default=None, help="ledger path override")
    ap.add_argument("--golden-path", default=None,
                    help="golden path override")
    ap.add_argument("--scenario", default=None,
                    help="restrict --golden/--baseline mode to one "
                         "scenario")
    ap.add_argument("--baseline", default=None, metavar="median:N",
                    help="compare each newest ledger row against the "
                         "median pseudo-row of its trailing N prior "
                         "rows instead of the golden")
    ap.add_argument("--json", action="store_true",
                    help="emit the report(s) as JSON")
    args = ap.parse_args(argv)

    reports: List[Dict[str, Any]] = []
    if args.baseline is not None:
        from . import trends
        m = re.fullmatch(r"median:(\d+)", args.baseline)
        if not m or int(m.group(1)) < 1:
            ap.error("--baseline must look like median:N with N >= 1")
        n = int(m.group(1))
        rows = ledger.read_ledger(args.ledger)
        latest = ledger.latest_rows(rows)
        names = ([args.scenario] if args.scenario else sorted(latest))
        thr = ledger.threshold(ledger.load_golden(args.golden_path),
                               "step_time_regression_frac")
        for name in names:
            cur = latest.get(name)
            if cur is None:
                sys.stderr.write(f"perfdiff: {name}: not in ledger, "
                                 "skipped\n")
                continue
            pts = ledger.read_series(name, str(cur.get("mode")),
                                     rows=rows, dedupe_sha=False)
            if len(pts) < 2:
                sys.stderr.write(f"perfdiff: {name}: fewer than 2 rows "
                                 "— no trailing median to compare "
                                 "against, skipped\n")
                continue
            base = trends.median_row([p["row"] for p in pts[:-1][-n:]])
            reports.append(diff_rows(base, cur, thr))
    elif args.golden or not args.rows:
        golden = ledger.load_golden(args.golden_path)
        if golden is None:
            sys.stderr.write("perfdiff: no golden baseline "
                             "(run the gate with --write-golden)\n")
            return 2
        thr = ledger.threshold(golden, "step_time_regression_frac")
        latest = ledger.latest_rows(ledger.read_ledger(args.ledger))
        names = ([args.scenario] if args.scenario
                 else sorted(set(latest) & set(golden["scenarios"])))
        for name in names:
            if name not in latest or name not in golden["scenarios"]:
                sys.stderr.write(f"perfdiff: {name}: missing from "
                                 "ledger or golden, skipped\n")
                continue
            reports.append(diff_rows(golden["scenarios"][name],
                                     latest[name], thr))
    elif len(args.rows) == 2:
        reports.append(diff_rows(_load_row_file(args.rows[0]),
                                 _load_row_file(args.rows[1])))
    else:
        ap.error("pass exactly two row files, or --golden")

    if args.json:
        print(json.dumps(reports, indent=1))  # noqa: print
    else:
        for rep in reports:
            print(render(rep))  # noqa: print
    return 1 if any(r["regression"] for r in reports) else 0


if __name__ == "__main__":
    raise SystemExit(main())
