"""Perf trend engine (ISSUE 14): the ledger as a *series*, not pairs.

perfdiff (ISSUE 13) compares two rows; this module models the whole
ledger per scenario/mode/metric — sha-deduped, fingerprint-partitioned
series (``ledger.read_series``) for step p50, MFU, compile wall,
bytes-on-wire and peak HBM — and answers the questions a pair can't:

- **noise floor** — median/MAD over a trailing window
  (``PTPU_TREND_WINDOW``), so "how jittery is this scenario" is a
  number, not folklore.  The robust per-point noise sigma comes from
  first differences (MAD of diffs / sqrt(2)), which a single mean shift
  cannot inflate the way a whole-series stddev can;
- **changepoints** — robust mean-shift detection (binary segmentation;
  a split fires only when the between-segment median gap clears
  ``max(k * sigma, 5%)``, ``k`` = ``PTPU_TREND_K``), each attributed to
  the **git-sha range** it landed in and — for step time — the
  **dominant phase** via perfdiff's attribution, so a slow multi-commit
  regression that pairwise perfdiff is blind to by construction gets a
  name;
- **drift** — a Theil–Sen slope whose cumulative movement is tested
  against the noise of its own residuals, catching the creep that never
  jumps;
- **flakiness** — per-scenario noise-sigma / median, the score the
  noise-aware gate (``bench.gate``) calibrates its threshold with.

CLI::

    python -m paddle_tpu.bench.trends [--mode smoke] [--scenario moe]
                                      [--window N] [--k K] [--json]
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import ledger
from .schema import GAP_SINKS, METRICS, PHASES

__all__ = ["DEFAULT_WINDOW", "DEFAULT_K", "MIN_SHIFT_FRAC",
           "SMALL_SERIES_FLOOR", "trend_window", "trend_k", "median",
           "mad", "sigma_from_diffs", "noise_floor", "detect_changepoints",
           "theil_sen", "median_row", "analyze_series", "scan_ledger",
           "render_report", "main"]

# trailing-window length for the noise floor / gate baseline
# (override with PTPU_TREND_WINDOW)
DEFAULT_WINDOW = 16
# noise multiplier: a shift / threshold is k robust-sigmas
# (override with PTPU_TREND_K)
DEFAULT_K = 3.0
# no shift smaller than this fraction is ever a changepoint, however
# quiet the series (measurement resolution floor)
MIN_SHIFT_FRAC = 0.05
# when a segment is too short to estimate sigma from its diffs, demand a
# shift this large instead (tiny series: evidence must be loud)
SMALL_SERIES_FLOOR = 0.12
# MAD → sigma for normal noise; diffs of iid noise carry sqrt(2) sigma
_MAD_SCALE = 1.4826
_EPS = 1e-12


def trend_window() -> int:
    return max(2, int(os.environ.get("PTPU_TREND_WINDOW", DEFAULT_WINDOW)))


def trend_k() -> float:
    return float(os.environ.get("PTPU_TREND_K", DEFAULT_K))


# -- robust statistics ------------------------------------------------------
def median(vals: Sequence[float]) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def mad(vals: Sequence[float]) -> Optional[float]:
    """Raw median absolute deviation (unscaled)."""
    m = median(vals)
    if m is None:
        return None
    return median([abs(v - m) for v in vals])


def sigma_from_diffs(values: Sequence[float],
                     exclude: Optional[int] = None) -> Optional[float]:
    """Robust per-point noise sigma from first differences.

    A single mean shift contaminates exactly one diff, which the MAD
    shrugs off (and ``exclude`` drops a candidate changepoint's own
    jump before estimating).  Returns None below 3 usable diffs — too
    little data to call anything noise.
    """
    diffs = [values[i + 1] - values[i] for i in range(len(values) - 1)]
    if exclude is not None and 0 <= exclude < len(diffs):
        diffs = diffs[:exclude] + diffs[exclude + 1:]
    if len(diffs) < 3:
        return None
    m = mad(diffs)
    return _MAD_SCALE * m / math.sqrt(2.0) if m is not None else None


def noise_floor(values: Sequence[float],
                window: Optional[int] = None
                ) -> Tuple[Optional[float], Optional[float]]:
    """(median, MAD) over the trailing ``window`` points — the gate's
    baseline and its noise calibration."""
    if window is None:
        window = trend_window()
    win = list(values)[-window:]
    return median(win), mad(win)


def theil_sen(values: Sequence[float]) -> float:
    """Median of pairwise slopes — a robust per-point drift rate."""
    n = len(values)
    slopes = [(values[j] - values[i]) / (j - i)
              for i in range(n) for j in range(i + 1, n)]
    return median(slopes) or 0.0


# -- changepoints -----------------------------------------------------------
def detect_changepoints(values: Sequence[float],
                        k: Optional[float] = None,
                        min_frac: float = MIN_SHIFT_FRAC
                        ) -> List[Dict[str, Any]]:
    """Mean-shift changepoints by robust binary segmentation.

    A split at ``t`` (the first index of the new regime) fires when the
    gap between segment medians exceeds ``max(k * sigma, min_frac *
    level)`` — sigma from the segment's first differences with the
    candidate jump excluded, so the shift can't hide itself in its own
    noise estimate.  Segments too short for a sigma estimate fall back
    to the louder :data:`SMALL_SERIES_FLOOR`.  Pure noise produces no
    changepoints at any window length; recursion finds multiple shifts.
    """
    if k is None:
        k = trend_k()
    values = [float(v) for v in values]
    found: List[Dict[str, Any]] = []

    def sad(vals: Sequence[float]) -> float:
        m = median(vals)
        return sum(abs(v - m) for v in vals)

    def scan(lo: int, hi: int) -> None:
        if hi - lo < 3:
            return
        seg = values[lo:hi]
        base_cost = sad(seg)
        # best split = largest reduction in within-segment spread — the
        # classic binseg objective, which lands on the regime boundary
        # instead of whichever noise excursion has the loudest median gap
        best: Optional[Tuple[float, int]] = None
        for t in range(lo + 1, hi):
            gain = base_cost - (sad(values[lo:t]) + sad(values[t:hi]))
            if best is None or gain > best[0]:
                best = (gain, t)
        if best is None:
            return
        t = best[1]
        ml = median(values[lo:t])
        mr = median(values[t:hi])
        level = max(abs(ml), _EPS)
        rel = abs(mr - ml) / level
        sigma = sigma_from_diffs(seg, exclude=t - 1 - lo)
        if sigma is not None:
            # the gap of two segment *medians* is much tighter than one
            # point (std of a median shrinks with sqrt(n)); the sqrt(ln)
            # factor pays for testing the *best* of ~n candidate splits
            # instead of one chosen a priori.  The overall constant is
            # Monte-Carlo calibrated: ~4% false positives on pure +-8%
            # jitter at k=3, <10% misses on a 20% shift under the same.
            gap_sigma = (sigma
                         * math.sqrt(1.0 / (t - lo) + 1.0 / (hi - t))
                         * math.sqrt(max(1.0, math.log(hi - lo))))
            thr = max(k * gap_sigma / level, min_frac)
        else:
            thr = max(min_frac, SMALL_SERIES_FLOOR)
        if rel <= thr:
            return
        found.append({
            "index": t,
            "before_median": ml,
            "after_median": mr,
            "delta_frac": (mr - ml) / max(abs(ml), _EPS),
            "direction": "up" if mr > ml else "down",
        })
        scan(lo, t)
        scan(t, hi)

    scan(0, len(values))
    found.sort(key=lambda c: c["index"])
    return found


# -- series → analysis ------------------------------------------------------
def median_row(rows: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """A pseudo-row of per-field medians over ``rows`` — the
    trailing-window baseline the noise-aware gate and
    ``perfdiff --baseline median:N`` compare against.  Carries every
    field perfdiff's attribution reads."""
    if not rows:
        raise ValueError("median_row of an empty window")

    def med_of(get) -> Optional[float]:
        vals = [v for v in (get(r) for r in rows)
                if isinstance(v, (int, float))]
        return median(vals)

    newest = rows[-1]
    return {
        "scenario": newest.get("scenario"),
        "mode": newest.get("mode"),
        "git_sha": f"median:{len(rows)}",
        "device_kind": newest.get("device_kind"),
        "fingerprint": newest.get("fingerprint"),
        "step_time_ms": {
            "p50": med_of(lambda r: (r.get("step_time_ms") or {}).get("p50")),
            "p99": med_of(lambda r: (r.get("step_time_ms") or {}).get("p99")),
        },
        "phases_ms": {p: (med_of(lambda r, _p=p:
                                 (r.get("phases_ms") or {}).get(_p)) or 0.0)
                      for p in PHASES},
        "compile": {"wall_ms": med_of(
            lambda r: (r.get("compile") or {}).get("wall_ms"))},
        "mfu": med_of(lambda r: r.get("mfu")),
        "bytes_on_wire": med_of(lambda r: r.get("bytes_on_wire")),
        "peak_hbm_bytes": med_of(lambda r: r.get("peak_hbm_bytes")),
        # ISSUE 19: per-sink medians so perfdiff's gap attribution works
        # against a median baseline too (only when any window row has a
        # roofline block — v1-only windows stay block-free)
        "roofline": _median_roofline(rows, med_of),
    }


def _median_roofline(rows: Sequence[Dict[str, Any]],
                     med_of) -> Optional[Dict[str, Any]]:
    if not any(isinstance((r.get("roofline") or {}).get("buckets_ms"),
                          dict) for r in rows):
        return None
    return {
        "buckets_ms": {s: (med_of(lambda r, _s=s:
                                  ((r.get("roofline") or {})
                                   .get("buckets_ms") or {}).get(_s)) or 0.0)
                       for s in GAP_SINKS},
        "coverage": med_of(lambda r:
                           (r.get("roofline") or {}).get("coverage")),
        "measured_step_ms": med_of(
            lambda r: (r.get("roofline") or {}).get("measured_step_ms")),
    }


def _short(sha: Optional[str]) -> str:
    return sha[:8] if isinstance(sha, str) else "?"


def analyze_series(points: List[Dict[str, Any]],
                   window: Optional[int] = None,
                   k: Optional[float] = None) -> Dict[str, Any]:
    """Full trend analysis of one ``read_series`` result: trailing
    noise floor, trend direction of the newest point, changepoints with
    sha-range attribution, Theil–Sen drift, flakiness."""
    if window is None:
        window = trend_window()
    if k is None:
        k = trend_k()
    values = [p["value"] for p in points]
    n = len(values)
    out: Dict[str, Any] = {"n": n, "values": values,
                           "shas": [p.get("sha") for p in points],
                           "window": window, "k": k}
    if n == 0:
        out.update({"median": None, "mad": None, "noise_frac": None,
                    "flakiness": None, "latest": None, "trend": None,
                    "changepoints": [], "drift": None})
        return out
    med, madv = noise_floor(values, window)
    level = max(abs(med), _EPS)
    noise_frac = (_MAD_SCALE * madv / level) if madv is not None else None
    sigma = sigma_from_diffs(values)
    flakiness = (sigma / level) if sigma is not None else noise_frac
    out.update({"median": med, "mad": madv, "noise_frac": noise_frac,
                "flakiness": flakiness, "latest": values[-1]})

    # trend direction of the newest point vs the trailing median of what
    # came before it, with a noise-calibrated dead band
    trend = None
    if n >= 2:
        prior_med, prior_mad = noise_floor(values[:-1], window)
        base = max(abs(prior_med), _EPS)
        band = max(0.02, k * _MAD_SCALE * (prior_mad or 0.0) / base)
        rel = (values[-1] - prior_med) / base
        trend = "up" if rel > band else ("down" if rel < -band else "flat")
        out["trend_rel"] = rel
    out["trend"] = trend

    cps = detect_changepoints(values, k=k)
    for cp in cps:
        i = cp["index"]
        cp["sha_range"] = (points[i - 1].get("sha") if i > 0 else None,
                           points[i].get("sha"))
        cp["ts"] = points[i].get("ts")
    out["changepoints"] = cps

    drift = None
    if n >= 5:
        slope = theil_sen(values)
        resid = [v - slope * i for i, v in enumerate(values)]
        resid_mad = mad(resid) or 0.0
        sigma_r = _MAD_SCALE * resid_mad
        total = slope * (n - 1)
        total_frac = total / level
        drift = {
            "slope_per_point": slope,
            "total_frac": total_frac,
            "residual_sigma_frac": sigma_r / level,
            "flagged": abs(total_frac) > max(MIN_SHIFT_FRAC,
                                             k * sigma_r / level),
            "direction": "up" if slope > 0 else "down",
        }
    out["drift"] = drift
    return out


def scan_ledger(path: Optional[str] = None,
                rows: Optional[List[Dict[str, Any]]] = None,
                mode: Optional[str] = None,
                scenario_names: Optional[List[str]] = None,
                window: Optional[int] = None,
                k: Optional[float] = None,
                metrics: Sequence[str] = METRICS) -> List[Dict[str, Any]]:
    """Analyze every (scenario, mode) series in the ledger.  Returns one
    entry per scenario/mode with a per-metric analysis; step-time
    changepoints are additionally attributed to their dominant phase
    via perfdiff over the segment medians."""
    from . import diff as perfdiff
    if rows is None:
        rows = ledger.read_ledger(path)
    keys = sorted({(str(r.get("scenario")), str(r.get("mode")))
                   for r in rows
                   if isinstance(r.get("scenario"), str)})
    analyses: List[Dict[str, Any]] = []
    for scenario, m in keys:
        if mode is not None and m != mode:
            continue
        if scenario_names and scenario not in scenario_names:
            continue
        per_metric: Dict[str, Dict[str, Any]] = {}
        step_points: List[Dict[str, Any]] = []
        for metric in metrics:
            points = ledger.read_series(scenario, m, metric, rows=rows)
            if metric == "step_p50":
                step_points = points
            per_metric[metric] = analyze_series(points, window=window, k=k)
        # dominant-phase attribution for step-time changepoints: compare
        # the median pseudo-rows of the segments either side of the shift
        step = per_metric.get("step_p50") or {}
        cps = step.get("changepoints") or []
        bounds = [0] + [cp["index"] for cp in cps] + [len(step_points)]
        for ci, cp in enumerate(cps):
            before = [p["row"] for p in step_points[bounds[ci]:cp["index"]]]
            after = [p["row"]
                     for p in step_points[cp["index"]:bounds[ci + 2]]]
            if before and after:
                att = perfdiff.attribute(median_row(before),
                                         median_row(after))
                cp["dominant_phase"] = att["dominant"]
                cp["movers"] = att["movers"]
        entry = {
            "scenario": scenario,
            "mode": m,
            "partition": (step_points and
                          _partition_of(step_points[-1]["row"])) or None,
            "metrics": per_metric,
            "flakiness": step.get("flakiness"),
            "trend": step.get("trend"),
            "last_changepoint": (cps[-1] if cps else None),
        }
        analyses.append(entry)
    return analyses


def _partition_of(row: Dict[str, Any]) -> str:
    from .schema import fingerprint_key
    return fingerprint_key(row)


# -- report -----------------------------------------------------------------
_METRIC_FMT = {
    "step_p50": ("step p50", lambda v: f"{v:.2f}ms"),
    "mfu": ("MFU", lambda v: f"{v:.4%}"),
    "compile_wall_ms": ("compile wall", lambda v: f"{v:.0f}ms"),
    "bytes_on_wire": ("bytes on wire", lambda v: f"{v:,.0f}B"),
    "peak_hbm_bytes": ("peak HBM", lambda v: f"{v / (1 << 20):.1f}MiB"),
    "roofline_coverage": ("roofline coverage", lambda v: f"{v:.1%}"),
}
# gap-bucket axes (ISSUE 19): one trendable series per non-mxu sink
_METRIC_FMT.update({
    f"gap_{_s}_ms": (f"gap:{_s}", lambda v: f"{v:.2f}ms")
    for _s in GAP_SINKS if _s != "mxu"
})
# interconnect axes (ISSUE 20): the comm sub-budget's headline figures
_METRIC_FMT.update({
    "comm_modeled_ms": ("comm:modeled", lambda v: f"{v:.3f}ms"),
    "comm_overlapped_ms": ("comm:overlapped", lambda v: f"{v:.2f}ms"),
    "comm_unattributed_ms": ("comm:unattributed", lambda v: f"{v:.2f}ms"),
})


def _fmt_metric(metric: str, v: Optional[float]) -> str:
    if v is None:
        return "—"
    return _METRIC_FMT.get(metric, (metric, lambda x: f"{x:.3g}"))[1](v)


def render_report(analyses: List[Dict[str, Any]]) -> str:
    """The doctor-style text report of ``python -m
    paddle_tpu.bench.trends``."""
    lines: List[str] = []
    if not analyses:
        return ("perf trends: no ledger series yet — run "
                "`python -m paddle_tpu.bench --all --smoke` first")
    regressions = 0
    for a in analyses:
        step = a["metrics"].get("step_p50") or {}
        n = step.get("n", 0)
        head = (f"{a['scenario']} ({a['mode']}"
                + (f", {a['partition']}" if a.get("partition") else "")
                + f"): {n} point(s)")
        if n == 0:
            lines.append(head)
            continue
        noise = step.get("noise_frac")
        flaky = step.get("flakiness")
        head += (f", step p50 {_fmt_metric('step_p50', step.get('latest'))}"
                 f" vs trailing median "
                 f"{_fmt_metric('step_p50', step.get('median'))}"
                 + (f", noise floor ±{noise:.1%}" if noise is not None
                    else "")
                 + (f", flakiness {flaky:.1%}" if flaky is not None else "")
                 + (f", trend {step.get('trend')}" if step.get("trend")
                    else ""))
        lines.append(head)
        for metric, an in a["metrics"].items():
            for cp in an.get("changepoints") or []:
                label = _METRIC_FMT.get(metric, (metric, None))[0]
                before, at = cp["sha_range"]
                seg = (f"  changepoint in {label}: sha range "
                       f"{_short(before)}..{_short(at)} "
                       f"(point {cp['index'] + 1}/{an['n']}): "
                       f"{_fmt_metric(metric, cp['before_median'])} -> "
                       f"{_fmt_metric(metric, cp['after_median'])} "
                       f"({cp['delta_frac']:+.1%})")
                if cp.get("dominant_phase"):
                    seg += f", dominant phase: {cp['dominant_phase']}"
                lines.append(seg)
                if metric == "step_p50" and cp["direction"] == "up":
                    regressions += 1
            drift = an.get("drift")
            if drift and drift.get("flagged"):
                label = _METRIC_FMT.get(metric, (metric, None))[0]
                lines.append(
                    f"  drift in {label}: {drift['total_frac']:+.1%} over "
                    f"{an['n']} points "
                    f"({drift['slope_per_point']:+.3g}/point, residual "
                    f"noise ±{drift['residual_sigma_frac']:.1%})")
                if metric == "step_p50" and drift["direction"] == "up":
                    regressions += 1
    flaky_rows = [(a["scenario"], a["mode"], a.get("flakiness"))
                  for a in analyses if a.get("flakiness") is not None]
    if flaky_rows:
        lines.append("scenario flakiness (noise sigma / median, "
                     "worst first):")
        for scenario, m, f in sorted(flaky_rows, key=lambda r: -r[2]):
            lines.append(f"  {scenario:<22} {m:<6} {f:6.1%}")
    lines.append(f"{regressions} upward step-time shift(s)/drift(s) "
                 "across the ledger"
                 if regressions else
                 "no upward step-time shifts or drifts — the ledger "
                 "looks healthy")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.bench.trends",
        description="perf trend engine: noise floors, changepoints with "
                    "sha-range + phase attribution, drift, flakiness")
    ap.add_argument("--ledger", default=None, help="ledger path override")
    ap.add_argument("--mode", default=None, choices=("smoke", "full"),
                    help="only analyze rows of this mode")
    ap.add_argument("--scenario", action="append", default=[],
                    metavar="NAME", help="restrict to one scenario "
                                         "(repeatable)")
    ap.add_argument("--window", type=int, default=None,
                    help="trailing window (default PTPU_TREND_WINDOW, "
                         f"else {DEFAULT_WINDOW})")
    ap.add_argument("--k", type=float, default=None,
                    help="noise multiplier (default PTPU_TREND_K, "
                         f"else {DEFAULT_K})")
    ap.add_argument("--json", action="store_true",
                    help="emit the analyses as JSON")
    args = ap.parse_args(argv)
    analyses = scan_ledger(path=args.ledger, mode=args.mode,
                           scenario_names=args.scenario or None,
                           window=args.window, k=args.k)
    if args.json:
        slim = []
        for a in analyses:
            slim.append({**a, "metrics": {
                m: {k2: v for k2, v in an.items() if k2 != "values"}
                for m, an in a["metrics"].items()}})
        print(json.dumps(slim, indent=1, default=str))  # noqa: print — CLI report
    else:
        print(render_report(analyses))  # noqa: print — CLI report
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
