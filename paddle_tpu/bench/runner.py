"""Scenario runner (ISSUE 13): registry entry → one validated ledger row.

``run_scenario`` is the assembly point — it brackets the scenario with
the compile window and bytes-on-wire baselines, stamps device/fallback
provenance, and validates + appends the row.  Scenario code never
touches the ledger; the runner never touches model code.
"""
from __future__ import annotations

import os
import sys
import traceback
from typing import Any, Dict, List, Optional, Tuple

from . import harness, ledger, scenarios, schema

__all__ = ["run_scenario", "run_scenarios", "ensure_devices"]


def _emit_diag(msg: str) -> None:
    sys.stderr.write(msg + "\n")
    sys.stderr.flush()


def ensure_devices() -> Tuple[str, Optional[str]]:
    """Decide what the matrix runs on; returns ``(platform,
    fallback_reason)`` for the rows' provenance fields.

    Mirrors bench.py's doctrine — ``BENCH_CPU=1`` opts into the virtual
    CPU mesh outright; otherwise a dead TPU tunnel is detected by the
    subprocess probe and the run degrades to the CPU smoke *as data*
    (``fallback_reason="tpu_unreachable"``), never as a stderr-only
    note.  The CPU mesh is 8-wide so the meshed scenarios
    (long_context's dp×sp axes) have devices to shard over.
    """
    from ..framework.vmesh import force_virtual_cpu_mesh

    n_cpu = int(os.environ.get("BENCH_CPU_DEVICES", "8"))
    if os.environ.get("BENCH_CPU") == "1":
        force_virtual_cpu_mesh(n_cpu)
        return "cpu", None
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        force_virtual_cpu_mesh(n_cpu)
        return "cpu", None
    if harness.tpu_reachable():
        return "tpu", None
    _emit_diag("[bench] tpu unreachable after probe timeout — running "
               "the CPU smoke; rows carry fallback_reason=tpu_unreachable")
    force_virtual_cpu_mesh(n_cpu)
    return "cpu", "tpu_unreachable"


def run_scenario(name: str, mode: str = "smoke",
                 fallback_reason: Optional[str] = None,
                 registry=None) -> Dict[str, Any]:
    """Run one registered scenario and assemble its schema row."""
    from ..observability import get_registry
    from ..observability.compilecache import maybe_enable_persistent_cache

    registry = registry or get_registry()
    maybe_enable_persistent_cache(registry=registry)
    fn = scenarios.get(name)
    wire = harness.bytes_on_wire(registry)
    with harness.CompileWindow(registry) as cw, \
            harness.RooflineWindow() as rw:
        payload = fn(mode)
    phases = payload.get("phases_ms") or {}
    padding_frac = float(
        (payload.get("extra") or {}).get("padding_frac") or 0.0)
    roof = rw.block(payload["step_times_ms"], phases,
                    padding_frac=padding_frac)
    from ..observability import interconnect as ic_mod
    comm_bucket = float(((roof or {}).get("buckets_ms") or {})
                        .get("comm") or 0.0)
    try:
        import jax
        default_n = jax.device_count()
    except Exception:
        default_n = None
    per_op = payload.get("collective_by_op")
    if per_op is None:
        ic = ic_mod.degraded_block(
            comm_bucket, reason="scenario reports no per-collective "
                                "deltas")
    else:
        ic = ic_mod.build_block(comm_bucket, per_op,
                                hlo_comm=roof.get("comm_ops"),
                                default_participants=default_n)
    row = schema.new_row(
        name, mode,
        step_times_ms=payload["step_times_ms"],
        phases_ms=phases,
        config=payload.get("config"),
        tokens_per_sec=payload.get("tokens_per_sec"),
        mfu=payload.get("mfu"),
        compile_stats=cw.stats(),
        bytes_on_wire=wire.delta(),
        peak_hbm_bytes=payload.get("peak_hbm_bytes"),
        fallback_reason=fallback_reason,
        roofline=roof,
        interconnect=ic,
        extra=payload.get("extra"),
    )
    # mirror the headline figures into the live registry so /statusz and
    # the doctor see the freshest matrix without re-reading the ledger
    p50 = row["step_time_ms"]["p50"]
    if p50 is not None:
        registry.gauge(f"perf.step_time_ms[scenario={name}]").set(p50)
    if row["tokens_per_sec"] is not None:
        registry.gauge(
            f"perf.tokens_per_sec[scenario={name}]").set(
                row["tokens_per_sec"])
    for phase, ms in row["phases_ms"].items():
        registry.gauge(
            f"perf.phase_ms[scenario={name},phase={phase}]").set(ms)
    rl = row.get("roofline") or {}
    for sink, ms in (rl.get("buckets_ms") or {}).items():
        registry.gauge(
            f"roofline.bucket_ms[scenario={name},sink={sink}]").set(ms)
    if isinstance(rl.get("coverage"), (int, float)):
        registry.gauge(
            f"roofline.coverage[scenario={name}]").set(rl["coverage"])
    if isinstance(rl.get("modeled_step_ms"), (int, float)):
        registry.gauge(
            f"roofline.modeled_step_ms[scenario={name}]").set(
                rl["modeled_step_ms"])
    ic_blk = row.get("interconnect") or {}
    registry.gauge(
        f"interconnect.comm_bucket_ms[scenario={name}]").set(
            float(ic_blk.get("comm_bucket_ms") or 0.0))
    if isinstance(ic_blk.get("overlapped_ms"), (int, float)):
        registry.gauge(
            f"interconnect.overlapped_ms[scenario={name}]").set(
                ic_blk["overlapped_ms"])
    for e in (ic_blk.get("entries") or []):
        if e.get("op") == ic_mod.UNATTRIBUTED:
            registry.gauge(
                f"interconnect.unattributed_ms[scenario={name}]").set(
                    float(e.get("measured_ms") or 0.0))
            continue
        axis = e.get("axis") or "none"
        registry.gauge(
            f"interconnect.entry_ms[scenario={name},op={e['op']},"
            f"axis={axis}]").set(float(e.get("measured_ms") or 0.0))
        if isinstance(e.get("efficiency"), (int, float)):
            registry.gauge(
                f"interconnect.efficiency[scenario={name},op={e['op']},"
                f"axis={axis}]").set(e["efficiency"])
    registry.emit("bench.row", scenario=name, mode=mode,
                  step_time_p50_ms=p50, phases_ms=row["phases_ms"],
                  compile_wall_ms=row["compile"].get("wall_ms"),
                  device_kind=row["device_kind"],
                  fallback_reason=fallback_reason,
                  mfu=row["mfu"],
                  roofline={
                      "dominant_sink": rl.get("dominant_sink"),
                      "coverage": rl.get("coverage"),
                      "measured_step_ms": rl.get("measured_step_ms"),
                      "modeled_step_ms": rl.get("modeled_step_ms"),
                      "buckets_ms": rl.get("buckets_ms"),
                      "injected": bool(rl.get("injected")),
                      "device_known": (rl.get("device") or {}).get("known"),
                  },
                  interconnect={
                      "comm_bucket_ms": ic_blk.get("comm_bucket_ms"),
                      "unattributed_ms": ic_blk.get("unattributed_ms"),
                      "overlapped_ms": ic_blk.get("overlapped_ms"),
                      "entries": [
                          {"op": e.get("op"), "axis": e.get("axis"),
                           "participants": e.get("participants"),
                           "measured_ms": e.get("measured_ms"),
                           "modeled_ms": e.get("modeled_ms"),
                           "efficiency": e.get("efficiency")}
                          for e in (ic_blk.get("entries") or [])],
                      "injected": ic_blk.get("injected"),
                      "degraded": bool(ic_blk.get("degraded")),
                  })
    return row


def run_scenarios(names: Optional[List[str]] = None, mode: str = "smoke",
                  ledger_path: Optional[str] = None,
                  append: bool = True) -> List[Dict[str, Any]]:
    """Run the matrix; each scenario's row is validated and appended as
    it lands (a later scenario crashing never loses earlier rows).
    Scenario failures are reported and skipped, not fatal — the matrix
    must degrade scenario-by-scenario, like the doctor's checks.
    """
    _platform, fallback = ensure_devices()
    rows: List[Dict[str, Any]] = []
    for name in (names or scenarios.names()):
        _emit_diag(f"[bench] {name} ({mode}) ...")
        try:
            row = run_scenario(name, mode, fallback_reason=fallback)
        except Exception:
            _emit_diag(f"[bench] scenario {name!r} failed:\n"
                       + traceback.format_exc())
            continue
        if append:
            ledger.append_row(row, path=ledger_path)
        rows.append(row)
        _emit_diag(f"[bench] {name}: p50={row['step_time_ms']['p50']:.2f}ms"
                   f" compile={row['compile'].get('wall_ms', 0):.0f}ms"
                   f" device={row['device_kind']}")
    return rows
