"""paddle.utils analog (reference: python/paddle/utils — deprecated.py,
lazy_import.py try_import, install_check.py run_check, unique_name from
fluid, cpp_extension/).
"""
from __future__ import annotations

import functools
import importlib
import warnings

from . import cpp_extension  # noqa: F401
from . import download  # noqa: F401
from . import fsio  # noqa: F401
from . import retry  # noqa: F401
from . import unique_name  # noqa: F401

__all__ = ["deprecated", "try_import", "run_check", "cpp_extension",
           "unique_name", "download", "retry", "fsio"]


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Decorator emitting a DeprecationWarning on call
    (reference utils/deprecated.py)."""
    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return inner
    return wrap


def try_import(module_name: str, err_msg: str = ""):
    """Import or raise a readable error (reference utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed "
                       f"({e}); this environment has no package installs — "
                       f"gate the feature instead") from e


def run_check() -> bool:
    """Install sanity check (reference utils/install_check.py run_check):
    one matmul on the default device, one jitted step, report and return
    success."""
    import jax
    import jax.numpy as jnp

    from ..framework.errors import enforce

    dev = jax.devices()[0]
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    enforce(float(y[0, 0]) == 128.0, "matmul sanity check failed")
    jitted = jax.jit(lambda a: (a @ a).sum())
    enforce(float(jitted(x)) == 128.0 * 128 * 128,
            "jitted matmul sanity check failed")
    print(f"paddle_tpu is installed successfully on {dev.platform} "  # noqa: print
          f"({getattr(dev, 'device_kind', 'cpu')})")
    return True


def require_version(min_version: str, max_version=None):
    """Version gate (reference utils.require_version): checks this
    framework's version string against [min_version, max_version]."""
    from .. import __version__

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3])

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True
