"""Generic retry with exponential backoff (resilience layer, ISSUE 1).

The north-star workload runs on preemptible capacity against shared
filesystems: transient ``OSError``s on checkpoint writes, manifest reads
and host-side batch fetch are expected operating conditions, not bugs.
One policy object covers all of them:

- exponential backoff with full jitter (delay_k = base * mult^k, then a
  uniform draw in [delay*(1-jitter), delay] so a fleet of hosts retrying
  the same flaky NFS server doesn't stampede in lockstep);
- a wall-clock ``deadline`` so a SIGTERM grace window is never spent
  sleeping (the elastic flush path uses a tight deadline);
- a ``retryable`` exception filter — anything else propagates on the
  first raise (a corrupt checkpoint must NOT be retried into).

``sleep`` is injectable for tests (and for the fault harness, which
verifies attempt counts without paying real backoff time).
"""
from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["RetryPolicy", "retry_call", "retryable", "RetriesExhausted"]


class RetriesExhausted(OSError):
    """Raised when every attempt failed; ``__cause__`` is the last error."""


class RetryPolicy:
    """Immutable description of a retry schedule.

    >>> policy = RetryPolicy(max_attempts=4, base_delay=0.05)
    >>> retry_call(flaky_write, path, data, policy=policy)
    """

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 jitter: float = 0.5, deadline: Optional[float] = None,
                 retryable: Tuple[Type[BaseException], ...] = (OSError,),
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.retryable = tuple(retryable)
        self.sleep = sleep

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            d *= 1.0 - self.jitter * random.random()
        return d


#: Conservative default for small-file checkpoint I/O: up to 4 attempts
#: (absorbs 3 consecutive transient errors), ~0.35s worst-case backoff.
DEFAULT_IO_POLICY = RetryPolicy()


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying per ``policy``.

    Non-retryable exceptions propagate immediately.  When attempts (or the
    deadline) run out, raises :class:`RetriesExhausted` chained to the
    last underlying error so callers still see the root cause.
    """
    policy = policy or DEFAULT_IO_POLICY
    start = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retryable as e:
            last = e
            if attempt == policy.max_attempts:
                break
            d = policy.delay(attempt)
            if (policy.deadline is not None
                    and time.monotonic() - start + d > policy.deadline):
                break
            policy.sleep(d)
    raise RetriesExhausted(
        f"{getattr(fn, '__name__', fn)!s} failed after "
        f"{policy.max_attempts} attempts: {last}") from last


def retryable(policy: Optional[RetryPolicy] = None):
    """Decorator form of :func:`retry_call`."""
    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, **kwargs)
        return inner
    return wrap
