"""Durable small-file I/O — the single seam every checkpoint byte passes
through.

All checkpoint writers (``distributed/checkpoint.py`` shards + manifests,
``framework/io.py`` pickles, the elastic COMMITTED marker) call
:func:`write_bytes` / :func:`atomic_write_bytes` instead of opening files
directly.  That buys three things at once:

- **durability**: every write is flushed AND fsync'd before it counts —
  an ``os.replace`` over a non-fsync'd file can still surface as a torn
  file after power loss;
- **atomicity**: ``atomic_write_bytes`` stages through ``path + ".tmp"``
  and ``os.replace``s into place, so readers only ever see absent or
  complete files;
- **injectability**: the fault harness (``paddle_tpu.testing.faults``)
  monkeypatches ``fsio.write_bytes`` to deliver truncations, bit flips,
  transient ``OSError``s and SIGTERM mid-save to EVERY durable write in
  the stack from one place.
"""
from __future__ import annotations

import os

__all__ = ["write_bytes", "atomic_write_bytes", "append_bytes",
           "read_bytes", "fsync_dir"]


def write_bytes(path: str, payload: bytes) -> None:
    """Write ``payload`` to ``path`` and fsync it (durable, NOT atomic)."""
    with open(path, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())


def append_bytes(path: str, payload: bytes) -> None:
    """Append ``payload`` to ``path`` and fsync it — the JSONL-stream
    variant of :func:`write_bytes` (observability metric streams).  Same
    injectability contract: the fault harness patches this to tear/fail
    telemetry appends."""
    with open(path, "ab") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())


def atomic_write_bytes(path: str, payload: bytes) -> None:
    """Durably write ``payload`` so ``path`` is only ever absent or
    complete: stage into ``path + ".tmp"``, fsync, ``os.replace``."""
    tmp = path + ".tmp"
    write_bytes(tmp, payload)
    os.replace(tmp, path)


def read_bytes(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def fsync_dir(path: str) -> None:
    """fsync a directory so renames/creates inside it are durable (no-op
    on platforms whose dirfds reject fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems (and non-POSIX hosts) reject dirfd fsync
    finally:
        os.close(fd)
