"""Custom C++ op loading (reference: utils/cpp_extension/ — CppExtension /
load building a shared lib from sources; framework/custom_operator.cc
registration, E9).

TPU-first shape of the feature: device kernels belong in Pallas (the E9
custom-kernel mechanism); what C++ is for here is HOST-side ops — IO,
tokenization, CPU-heavy pre/post-processing.  ``load()`` compiles sources
with g++ into a .so exposed via ctypes (no pybind11 in this image), and
``custom_op()`` wraps an exported symbol as a jax-callable that works
INSIDE jit via ``jax.pure_callback`` — the analog of the reference's
custom-op-in-graph registration.

C ABI contract for custom_op: ``void f(const float* in, float* out,
int64_t n)`` — elementwise/same-shape ops; richer signatures can be
wrapped manually from the ctypes handle returned by ``load``.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.errors import enforce

__all__ = ["load", "custom_op", "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str, sources: Sequence[str], extra_cxx_cflags=(),
         extra_ldflags=(), verbose: bool = False) -> ctypes.CDLL:
    """Compile ``sources`` (.cc/.cpp paths) into <build_dir>/<name>-<hash>.so
    and return the loaded ctypes handle.  Recompiles only when sources
    change (content-hash keyed), mirroring the reference's build cache."""
    enforce(len(sources) > 0, "cpp_extension.load needs at least one source")
    h = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join([*extra_cxx_cflags, *extra_ldflags]).encode())
    so_path = os.path.join(get_build_directory(),
                           f"{name}-{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        # build to a private temp path and rename atomically: a concurrent
        # load() must never dlopen a half-written .so
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *extra_cxx_cflags, *sources, "-o", tmp_path, *extra_ldflags]
        if verbose:
            print("compiling:", " ".join(cmd))  # noqa: print
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
            enforce(proc.returncode == 0,
                    f"cpp_extension build failed:\n{proc.stderr}")
            os.rename(tmp_path, so_path)
        finally:
            if os.path.exists(tmp_path):   # failed build: no orphan files
                os.unlink(tmp_path)
    return ctypes.CDLL(so_path)


_CTYPES = {
    np.float32: ctypes.c_float,
    np.float64: ctypes.c_double,
    np.int32: ctypes.c_int32,
    np.int64: ctypes.c_int64,
}


def custom_op(lib: ctypes.CDLL, symbol: str, dtype=np.float32) -> Callable:
    """Wrap an exported ``void f(const T* in, T* out, int64_t n)`` symbol
    as a jax-callable usable under jit (host callback; the graph sees a
    same-shape op).  Gradients are not defined — wrap with
    ``paddle_tpu.autograd.PyLayer``/``jax.custom_vjp`` if needed."""
    fn = getattr(lib, symbol)
    ct = _CTYPES[np.dtype(dtype).type]
    fn.argtypes = [ctypes.POINTER(ct), ctypes.POINTER(ct), ctypes.c_int64]
    fn.restype = None

    def host(x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, dtype=dtype)
        out = np.empty_like(x)
        fn(x.ravel().ctypes.data_as(ctypes.POINTER(ct)),
           out.ctypes.data_as(ctypes.POINTER(ct)),
           ctypes.c_int64(x.size))
        return out

    def op(x):
        x = jnp.asarray(x)
        return jax.pure_callback(
            host, jax.ShapeDtypeStruct(x.shape, np.dtype(dtype)), x,
            vmap_method="sequential")

    op.__name__ = symbol
    return op


def CppExtension(sources, **kwargs):
    """setuptools Extension factory (reference cpp_extension.CppExtension):
    the ahead-of-time build path next to the JIT ``load``.  Extension
    options go by keyword (include_dirs=..., extra_compile_args=...)."""
    from setuptools import Extension
    name = kwargs.pop("name", "paddle_tpu_ext")
    kwargs.setdefault("language", "c++")
    return Extension(name, sources=list(sources), **kwargs)


def CUDAExtension(sources, **kwargs):
    """Reference CUDAExtension: CUDA does not exist on this stack — the
    host-side C++ parts still build (CppExtension); .cu sources raise
    with the Pallas recipe (docs/MIGRATION.md: custom ops)."""
    cu = [s for s in sources if str(s).endswith((".cu", ".cuh"))]
    if cu:
        raise RuntimeError(
            f"CUDA sources {cu} cannot build here: device kernels are "
            "Pallas on TPU (docs/MIGRATION.md 'custom ops'); host-side "
            "C++ goes through CppExtension/load")
    return CppExtension(sources, **kwargs)


def setup(**attrs):
    """Reference cpp_extension.setup: setuptools.setup preconfigured for
    the extension build (the AOT twin of ``load``)."""
    import setuptools
    attrs.setdefault("ext_modules", [])
    return setuptools.setup(**attrs)


__all__ += ["CppExtension", "CUDAExtension", "setup"]
