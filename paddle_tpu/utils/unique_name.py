"""paddle.utils.unique_name analog (reference: fluid/unique_name.py —
generate/guard/switch; used for auto-naming parameters and ops)."""
from __future__ import annotations

import contextlib
import threading
from collections import defaultdict

__all__ = ["generate", "guard", "switch"]

_state = threading.local()


def _gen() -> dict:
    g = getattr(_state, "generator", None)
    if g is None:
        g = defaultdict(int)
        _state.generator = g
    return g


def generate(key: str) -> str:
    g = _gen()
    name = f"{key}_{g[key]}"
    g[key] += 1
    return name


def switch(new_generator=None):
    old = _gen()
    _state.generator = new_generator if new_generator is not None \
        else defaultdict(int)
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        _state.generator = old
