"""paddle.utils.download analog (reference utils/download.py).

Zero-egress environment: URLs are served from the local cache only
(the paddle_tpu.hub gating pattern) — a cached file is returned, a
missing one raises with the provenance recipe instead of silently
fetching."""
from __future__ import annotations

import hashlib
import os

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def _md5check(path: str, md5sum: str) -> bool:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def get_weights_path_from_url(url: str, md5sum: str = None) -> str:
    """Resolve a weights URL to a local path (reference
    get_weights_path_from_url).  Looks up the basename under
    WEIGHTS_HOME; this environment has no egress, so an uncached file is
    an error pointing at the cache location rather than a download."""
    fname = os.path.basename(url)
    path = os.path.join(WEIGHTS_HOME, fname)
    if os.path.exists(path):
        if md5sum and not _md5check(path, md5sum):
            raise RuntimeError(
                f"cached weights {path} fail the md5 check ({md5sum}); "
                "remove the file and re-provision it")
        return path
    raise RuntimeError(
        f"no network egress in this environment: provision {fname} "
        f"under {WEIGHTS_HOME} (from {url}) before calling "
        "get_weights_path_from_url")
