"""Divergence guard: rolling loss / grad-norm statistics with a
skip → lower-LR → rollback escalation ladder (ISSUE 2).

Generalizes hapi's ``nonfinite_skip_budget`` (PR 1), which could only
"skip the batch": a batch is *bad* when its loss (or grad global norm)
is non-finite OR spikes by ``spike_factor``× over the rolling median of
the recent healthy window.  Consecutive bad batches climb the ladder:

    1..skip_budget               SKIP       drop the update, keep going
    next max_lr_backoffs times   LOWER_LR   also multiply LR by
                                            ``lr_backoff`` (sticky until
                                            explicitly restored)
    after that                   ROLLBACK   restore last-good checkpoint

A healthy batch resets the consecutive counter (one cosmic-ray batch
costs one update, not an escalation), but the *lifetime* bad count and
the lowered LR persist — a run that keeps spiking is drifting, not
unlucky.

AMP-awareness: while dynamic loss scaling is active, overflow steps are
an expected part of the scale search — the first ``amp_grace``
non-finite observations are skipped WITHOUT climbing the ladder, exactly
mirroring GradScaler's own "shrink the scale and retry" contract.
"""
from __future__ import annotations

from collections import deque
from statistics import median
from typing import Optional

from ..framework.log import vlog

__all__ = ["GuardAction", "DivergenceGuard"]


def _finite(x: Optional[float]) -> bool:
    return x is not None and x == x and abs(x) != float("inf")


class GuardAction:
    OK = "ok"
    SKIP = "skip"
    LOWER_LR = "lower-lr"
    ROLLBACK = "rollback"


class DivergenceGuard:
    """Feed it every step's host-side loss (and optionally the grad
    global norm); it answers what the training loop should do.

    >>> guard = DivergenceGuard(skip_budget=2)
    >>> guard.observe(step, loss, grad_norm)   # → a GuardAction value
    """

    def __init__(self, window: int = 32, spike_factor: float = 10.0,
                 skip_budget: int = 2, lr_backoff: float = 0.5,
                 max_lr_backoffs: int = 1, amp_grace: int = 3,
                 min_history: int = 4, report=None):
        self.window = deque(maxlen=int(window))
        self.norm_window = deque(maxlen=int(window))
        self.spike_factor = float(spike_factor)
        self.skip_budget = int(skip_budget)
        self.lr_backoff = float(lr_backoff)
        self.max_lr_backoffs = int(max_lr_backoffs)
        self.amp_grace = int(amp_grace)
        self.min_history = int(min_history)
        self.report = report
        self.lr_scale = 1.0
        self.consecutive_bad = 0
        self.total_bad = 0
        self.lr_backoffs = 0
        self.amp_overflows = 0

    # -- classification ----------------------------------------------------
    def _spiking(self, value: Optional[float], history: deque) -> bool:
        if value is None or len(history) < self.min_history:
            return False
        base = abs(median(history))
        return abs(value) > self.spike_factor * max(base, 1e-12)

    def observe(self, step: int, loss: float,
                grad_norm: Optional[float] = None,
                amp_active: bool = False) -> str:
        loss = None if loss is None else float(loss)
        grad_norm = None if grad_norm is None else float(grad_norm)
        nonfinite = not _finite(loss) or (grad_norm is not None
                                          and not _finite(grad_norm))
        if nonfinite and amp_active and self.amp_overflows < self.amp_grace:
            # loss-scale search overflow: skip the update, don't escalate
            self.amp_overflows += 1
            self._event("amp_overflow_skip", step=step, loss=loss,
                        grad_norm=grad_norm)
            return GuardAction.SKIP
        bad = (nonfinite or self._spiking(loss, self.window)
               or self._spiking(grad_norm, self.norm_window))
        if not bad:
            self.consecutive_bad = 0
            if loss is not None:
                self.window.append(loss)
            if grad_norm is not None:
                self.norm_window.append(grad_norm)
            return GuardAction.OK
        self.consecutive_bad += 1
        self.total_bad += 1
        reason = "nonfinite" if nonfinite else "spike"
        if self.consecutive_bad <= self.skip_budget:
            self._event("divergence_skip", step=step, loss=loss,
                        grad_norm=grad_norm, reason=reason,
                        consecutive=self.consecutive_bad)
            return GuardAction.SKIP
        if self.lr_backoffs < self.max_lr_backoffs:
            self.lr_backoffs += 1
            self.lr_scale *= self.lr_backoff
            self._event("lr_backoff", step=step, loss=loss, reason=reason,
                        lr_scale=self.lr_scale)
            return GuardAction.LOWER_LR
        self._event("divergence_rollback", step=step, loss=loss,
                    grad_norm=grad_norm, reason=reason,
                    consecutive=self.consecutive_bad)
        return GuardAction.ROLLBACK

    # -- lifecycle ---------------------------------------------------------
    def reset_after_rollback(self) -> None:
        """Restored state invalidates the rolling statistics; the lowered
        LR persists — whatever diverged once will diverge again at the
        old rate."""
        self.window.clear()
        self.norm_window.clear()
        self.consecutive_bad = 0

    def restore_lr(self) -> None:
        self.lr_scale = 1.0
        self.lr_backoffs = 0

    def _event(self, kind: str, **fields) -> None:
        vlog(0, "guard: %s %s", kind, fields)
        if self.report is not None:
            self.report.record(kind, **fields)
