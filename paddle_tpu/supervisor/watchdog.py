"""Step watchdog: a deadline armed around each train step / blocking
collective (ISSUE 2).

A hung collective on a preemptible pod does not crash — it sits at 100%
idle forever while the job bill runs.  The watchdog turns "forever" into
a bounded event: a monitor thread tracks every armed section, and when a
deadline expires it (1) dumps the stacks of every live thread (the
post-mortem a hang otherwise destroys), (2) records a
``watchdog_timeout`` event, and (3) raises :class:`StepTimeout` inside
the armed thread (``PyThreadState_SetAsyncExc``) so the supervised loop
regains control and can skip / roll back instead of hanging.

The async raise lands at the next Python bytecode boundary — it
interrupts host-side loops, sleeps between slices, and retry backoff,
which covers every injectable hang the fault harness produces.  A thread
truly wedged inside a C extension can't be interrupted from userspace;
for that case the stack dump + report event still fire, which is what a
supervising launcher needs to kill and reschedule the worker.

Env knob: ``PTPU_WATCHDOG_SECS`` (default 300) seeds the default
deadline; each ``armed()`` call may override it.
"""
from __future__ import annotations

import contextlib
import ctypes
import os
import sys
import threading
import traceback
from typing import List, Optional

from ..framework.log import vlog

__all__ = ["StepTimeout", "Watchdog", "install_global", "global_watchdog",
           "guarded", "dump_all_stacks"]

DEFAULT_TIMEOUT_ENV = "PTPU_WATCHDOG_SECS"


class StepTimeout(RuntimeError):
    """An armed section outlived its watchdog deadline."""


def default_timeout() -> float:
    return float(os.environ.get(DEFAULT_TIMEOUT_ENV, "300"))


def _async_raise(thread_id: int, exc_type) -> bool:
    """Raise ``exc_type`` asynchronously in the thread with ``thread_id``;
    True when the interpreter accepted exactly one target."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(exc_type))
    if res > 1:  # "we broke more than one thread" — undo, never deliver
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_id), None)
        return False
    return res == 1


def dump_all_stacks(limit: int = 16, first: Optional[int] = None) -> str:
    """Stack of every live thread, hung ones included (the forensic core
    of the timeout path).  ``first`` puts that thread id at the top —
    the report clips long dumps, and in a thread-heavy process (serving
    callbacks, io workers, peer watchdogs) the hung thread's stack must
    survive the clip."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for tid, frame in sys._current_frames().items():
        header = f"--- thread {names.get(tid, '?')} ({tid}) ---"
        chunk = (header + "\n"
                 + "".join(traceback.format_stack(frame, limit=limit)))
        if tid == first:
            chunks.insert(0, chunk)
        else:
            chunks.append(chunk)
    return "\n".join(chunks)


class _Armed:
    __slots__ = ("label", "timeout", "deadline", "thread_id", "expired",
                 "delivered")

    def __init__(self, label: str, timeout: float, deadline: float,
                 thread_id: int):
        self.label = label
        self.timeout = timeout
        self.deadline = deadline
        self.thread_id = thread_id
        self.expired = False
        self.delivered = False


class Watchdog:
    """Deadline monitor for blocking sections.

    >>> wd = Watchdog(timeout=30.0)
    >>> with wd.armed("train_batch"):
    ...     loss = train_step(...)        # StepTimeout if it stalls

    One daemon monitor thread serves all armed sections (multiple threads
    may arm concurrently — e.g. the train loop and an async checkpoint
    committer).  ``clock`` is injectable for tests.
    """

    def __init__(self, timeout: Optional[float] = None, report=None,
                 on_timeout=None, clock=None):
        import time as _time
        self.timeout = default_timeout() if timeout is None else float(timeout)
        self.report = report
        self.on_timeout = on_timeout
        self._clock = clock or _time.monotonic
        self._cond = threading.Condition()
        self._entries: List[_Armed] = []
        self._monitor: Optional[threading.Thread] = None
        self._closed = False
        self.timeouts = 0

    # -- arming ------------------------------------------------------------
    @contextlib.contextmanager
    def armed(self, label: str = "step", timeout: Optional[float] = None):
        t = self.timeout if timeout is None else float(timeout)
        entry = _Armed(label, t, self._clock() + t, threading.get_ident())
        with self._cond:
            if self._closed:
                raise RuntimeError("watchdog is closed")
            self._entries.append(entry)
            self._ensure_monitor()
            self._cond.notify_all()
        try:
            yield entry
        finally:
            with self._cond:
                if entry in self._entries:
                    self._entries.remove(entry)
                self._cond.notify_all()
                # backstop: deadline passed but the async exception was
                # not (or could not be) delivered — surface it here so an
                # expiry is never silent
                if entry.expired and not entry.delivered:
                    entry.delivered = True
                    raise StepTimeout(
                        f"{entry.label!r} exceeded the {t:.3g}s watchdog "
                        "deadline")

    # -- monitor -----------------------------------------------------------
    def _ensure_monitor(self) -> None:
        if self._monitor is None or not self._monitor.is_alive():
            self._monitor = threading.Thread(
                target=self._run, name="ptpu-watchdog", daemon=True)
            self._monitor.start()

    def _run(self) -> None:
        with self._cond:
            while not self._closed:
                live = [e for e in self._entries if not e.expired]
                if not live:
                    self._cond.wait()
                    continue
                now = self._clock()
                nxt = min(e.deadline for e in live)
                if nxt > now:
                    self._cond.wait(timeout=min(nxt - now, 1.0))
                    continue
                for entry in [e for e in live if e.deadline <= now]:
                    self._fire(entry)

    def _fire(self, entry: _Armed) -> None:
        """Called with the condition held: expire one armed section."""
        entry.expired = True
        self.timeouts += 1
        stacks = dump_all_stacks(first=entry.thread_id)
        vlog(0, "watchdog: %r missed its deadline — thread stacks:\n%s",
             entry.label, stacks)
        if self.report is not None:
            self.report.record(
                "watchdog_timeout", label=entry.label,
                timeout_secs=entry.timeout, thread_id=entry.thread_id,
                stacks=stacks[:4000])
        entry.delivered = _async_raise(entry.thread_id, StepTimeout)
        if self.on_timeout is not None:
            try:
                self.on_timeout(entry.label)
            except Exception as e:
                vlog(0, "watchdog: on_timeout callback failed: %s", e)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- process-global registry (collective barriers arm through this) --------
_GLOBAL: Optional[Watchdog] = None
_GLOBAL_LOCK = threading.Lock()


def install_global(watchdog: Optional[Watchdog]) -> Optional[Watchdog]:
    """Register ``watchdog`` as the process-wide one (None uninstalls);
    returns the previous registration so callers can restore it."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        prev, _GLOBAL = _GLOBAL, watchdog
    return prev


def global_watchdog() -> Optional[Watchdog]:
    return _GLOBAL


def guarded(label: str, timeout: Optional[float] = None):
    """Arm the global watchdog (if any) around a blocking call site —
    a no-op context manager when no supervisor is active."""
    wd = global_watchdog()
    if wd is None:
        return contextlib.nullcontext()
    return wd.armed(label, timeout=timeout)
