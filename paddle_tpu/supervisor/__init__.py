"""Run supervisor — the run-level half of the resilience story (ISSUE 2).

PR 1 made checkpoint *storage* fault-tolerant; this package supervises
the *run* built on top of it.  Four cooperating pieces:

- :mod:`watchdog` — a deadline armed around every train step / blocking
  collective; a hang becomes a stack-dumped, reported ``StepTimeout``.
- :mod:`heartbeat` — per-worker beat files through the fsync'd ``fsio``
  seam + a monitor classifying the run healthy/degraded/lost-worker.
- :mod:`guard` — rolling loss/grad-norm statistics escalating
  skip → lower-LR → rollback (AMP-aware about loss-scale overflows).
- :mod:`rollback` — budget-bounded restore from the newest committed
  good checkpoint (``ElasticTrainState.restore_or``).

Everything the supervisor sees and does is recorded in
:class:`~paddle_tpu.supervisor.report.SupervisorReport` — the JSON
post-mortem a dead run leaves behind.

:class:`RunSupervisor` composes the four around ``hapi.Model.fit``:

>>> sup = RunSupervisor("runs/gpt3", save_interval_steps=100)
>>> model.fit(data, epochs=1, supervisor=sup)

State machine (docs/ARCHITECTURE.md "Run supervision"):
healthy → degraded (stale peers / skipped batches) → rollback
(escalated divergence or repeated step failure, budget-bounded) →
failed (budget exhausted: ``RollbackBudgetExceeded`` + report).

Env knobs: ``PTPU_WATCHDOG_SECS`` (step deadline, default 300),
``PTPU_HEARTBEAT_SECS`` (beat interval, default 10),
``PTPU_ROLLBACK_BUDGET`` (restores before failing loudly, default 2).
``begin_run`` also arms the live-monitoring layer (ISSUE 5): a
per-worker status server when ``PTPU_MONITOR_PORT`` is set and a crash
flight recorder (``PTPU_FLIGHT_BUFFER``) whose ring is dumped to
``<run_dir>/flight/`` on any abnormal exit — see
docs/ARCHITECTURE.md "Live monitoring".
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, List, Optional, Tuple

from ..framework.log import vlog
from .guard import DivergenceGuard, GuardAction
from .heartbeat import (HeartbeatMonitor, HeartbeatWriter, RunState,
                        heartbeat_dir)
from .integrity import IntegrityGuard, IntegrityVerdict, integrity_dir
from .report import SupervisorReport
from .rollback import RollbackBudgetExceeded, RollbackManager
from .watchdog import (StepTimeout, Watchdog, global_watchdog, guarded,
                       install_global)

__all__ = [
    "RunSupervisor", "SupervisorReport", "Watchdog", "StepTimeout",
    "HeartbeatWriter", "HeartbeatMonitor", "RunState", "DivergenceGuard",
    "GuardAction", "RollbackManager", "RollbackBudgetExceeded",
    "IntegrityGuard", "IntegrityVerdict", "integrity_dir",
    "install_global", "global_watchdog", "guarded", "heartbeat_dir",
]


class RunSupervisor:
    """One object wrapping a training run in the full health loop.

    ``elastic`` may be an existing ``ElasticTrainState``; otherwise one
    is created under ``<run_dir>/checkpoints``.  ``reseed`` (optional)
    is called with the restored start step after every rollback — the
    data-pipeline reseeding hook.
    """

    def __init__(self, run_dir: str, *, elastic=None,
                 save_interval_steps: int = 1000,
                 watchdog_secs: Optional[float] = None,
                 heartbeat_secs: Optional[float] = None,
                 rollback_budget: Optional[int] = None,
                 step_failure_budget: int = 1,
                 guard: Optional[DivergenceGuard] = None,
                 worker_id: Optional[int] = None,
                 expected_workers: Optional[int] = None,
                 reseed: Optional[Callable[[int], None]] = None,
                 report_path: Optional[str] = None,
                 sigterm_handler: bool = True, clock=time.time,
                 coordinator=None, integrity=None):
        os.makedirs(run_dir, exist_ok=True)
        self.run_dir = run_dir
        self.report = SupervisorReport(
            report_path if report_path is not None
            else os.path.join(run_dir, "supervisor_report.json"),
            clock=clock)
        if elastic is None:
            from ..distributed.elastic import ElasticTrainState
            elastic = ElasticTrainState(
                os.path.join(run_dir, "checkpoints"),
                save_interval_steps=save_interval_steps,
                install_sigterm_handler=sigterm_handler)
        self.elastic = elastic
        if hasattr(self.elastic, "set_event_sink"):
            self.elastic.set_event_sink(self.report.record)
        self.watchdog = Watchdog(timeout=watchdog_secs, report=self.report)
        self.heartbeat = HeartbeatWriter(
            run_dir, worker_id=worker_id, interval=heartbeat_secs,
            clock=clock)
        self.monitor = HeartbeatMonitor(
            run_dir, expected=expected_workers, clock=clock,
            report=self.report)
        self.guard = guard or DivergenceGuard(report=self.report)
        if self.guard.report is None:
            self.guard.report = self.report
        self.rollback = RollbackManager(
            self.elastic, budget=rollback_budget, report=self.report,
            reseed=reseed)
        # elastic resize (ISSUE 9): an optional ElasticCoordinator turns
        # lost-worker from "roll back at the same width" into "re-form
        # the mesh at the surviving width and continue"
        self.coordinator = coordinator
        if coordinator is not None and coordinator.event_sink is None:
            coordinator.event_sink = self.report.record
        # state-integrity guard (ISSUE 11): pass an IntegrityGuard, or
        # set PTPU_INTEGRITY_EVERY > 0 to get the default one; the guard
        # shares its TreeFingerprint with the elastic manager so the
        # checkpoint digest stamp and the cross-worker compare agree
        if integrity is None and int(
                os.environ.get("PTPU_INTEGRITY_EVERY", "0") or "0") > 0:
            integrity = IntegrityGuard(
                run_dir, worker_id=self.heartbeat.worker_id,
                expected=expected_workers, report=self.report,
                clock=clock)
        self.integrity = integrity
        if integrity is not None:
            if integrity.report is None:
                integrity.report = self.report
            if getattr(self.elastic, "fingerprint", None) is None:
                self.elastic.fingerprint = integrity.fingerprint
        self.pending_integrity: Optional[IntegrityVerdict] = None
        self.pending_resize: Optional[dict] = None
        self.step_failure_budget = int(step_failure_budget)
        self.pending_rollback: Optional[str] = None
        self.last_action: Optional[str] = None
        self.initial_state: Any = None
        self.gstep = 0
        self.consecutive_step_failures = 0
        self._clock = clock
        self._last_poll = 0.0
        self._prev_global: Optional[Watchdog] = None
        self._running = False
        self._loss_injectors: List[Callable[[int, float], float]] = []
        self._metrics_sink = None  # run-scoped JSONL writer (ISSUE 3)
        self.status_server = None  # live monitor HTTP thread (ISSUE 5)
        self.flight = None         # crash flight recorder (ISSUE 5)

    # -- lifecycle ---------------------------------------------------------
    def begin_run(self, initial_state: Any = None) -> "RunSupervisor":
        if not self._running:
            self._running = True
            if initial_state is not None:
                self.initial_state = initial_state
            if self.watchdog._closed:  # supervisor reused across runs
                self.watchdog = Watchdog(timeout=self.watchdog.timeout,
                                         report=self.report)
            # run-scoped telemetry: everything emitted while this run is
            # live — step breakdowns, collective latencies, and the
            # supervisor's own events — streams to
            # <run_dir>/metrics/worker-<i>.jsonl (ISSUE 3)
            from ..observability import MetricsWriter, get_registry
            from ..observability import metrics_dir as _metrics_dir
            try:
                self._metrics_sink = get_registry().add_sink(
                    MetricsWriter(_metrics_dir(self.run_dir),
                                  worker_id=self.heartbeat.worker_id))
            except OSError as e:
                vlog(0, "supervisor: metrics sink under %s unavailable: "
                     "%s", self.run_dir, e)
            # crash flight recorder (ISSUE 5): a bounded ring of the
            # newest records, dumped on signals/atexit/this supervisor's
            # fault path so a hard death keeps its last N events
            try:
                from ..observability.flight import FlightRecorder
                self.flight = get_registry().add_sink(FlightRecorder(
                    self.run_dir, worker_id=self.heartbeat.worker_id))
                self.flight.install()
            except Exception as e:
                vlog(0, "supervisor: flight recorder unavailable: %r", e)
                self.flight = None
            # per-worker status server (ISSUE 5), when PTPU_MONITOR_PORT
            # is set (base port + worker rank; 0 = ephemeral)
            from ..observability.monitor import maybe_start_server
            self.status_server = maybe_start_server(
                supervisor=self, worker_id=self.heartbeat.worker_id)
            self.report.record("run_start", run_dir=self.run_dir,
                               worker=self.heartbeat.worker_id,
                               watchdog_secs=self.watchdog.timeout,
                               heartbeat_secs=self.heartbeat.interval,
                               rollback_budget=self.rollback.budget)
            self.heartbeat.start()
            self._prev_global = install_global(self.watchdog)
        return self

    def end_run(self, status: str = "completed") -> None:
        if not self._running:
            return
        self._running = False
        self.heartbeat.stop()
        install_global(self._prev_global)
        self.watchdog.close()
        # final per-worker instrument snapshot onto this worker's JSONL
        # stream (ISSUE 4): carries the collective.<op>.ms histograms and
        # compile counters the run doctor needs for cross-worker
        # straggler/retrace attribution
        try:
            from ..observability import get_registry
            reg = get_registry()
            reg.emit("metrics.snapshot", step=self.gstep,
                     worker=self.heartbeat.worker_id,
                     snapshot=reg.snapshot())
        except Exception as e:
            vlog(1, "supervisor: final metrics snapshot failed: %r", e)
        self.report.record("run_end", status=status, step=self.gstep,
                           rollbacks=self.rollback.used,
                           timeouts=self.watchdog.timeouts,
                           bad_batches=self.guard.total_bad)
        if self.flight is not None:
            # the supervisor's own fault path: an abnormal end dumps the
            # black box NOW (the signal/atexit hooks cover deaths that
            # never reach end_run); a clean completion leaves no bundle
            if status != "completed":
                self.flight.dump(reason=f"end_run:{status}")
            self.flight.uninstall()
            from ..observability import get_registry
            get_registry().remove_sink(self.flight)
            self.flight = None
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None
        if self._metrics_sink is not None:
            from ..observability import get_registry
            get_registry().remove_sink(self._metrics_sink)  # flush+close
            self._metrics_sink = None

    def attach(self, model) -> "RunSupervisor":
        """Bind to a ``hapi.Model`` so ``train_batch`` consults the guard
        and arms the watchdog even outside ``fit``."""
        model._supervisor = self
        return self

    def __enter__(self) -> "RunSupervisor":
        return self.begin_run()

    def __exit__(self, exc_type, *exc) -> None:
        self.end_run("failed" if exc_type else "completed")

    # -- per-step protocol -------------------------------------------------
    def inject_loss(self, fn: Callable[[int, float], float]) -> None:
        """Test seam: ``fn(step, loss) -> loss`` runs on every host-side
        loss before the guard sees it (``testing.faults.diverge_after``
        and ``hang`` plug in here)."""
        self._loss_injectors.append(fn)

    def filter_loss(self, loss: float) -> float:
        for fn in self._loss_injectors:
            loss = fn(self.gstep, loss)
        return loss

    def guard_step(self, loss: float, grad_norm: Optional[float] = None,
                   amp_active: bool = False) -> str:
        """Guard verdict for this step's statistics; a ROLLBACK verdict is
        latched in ``pending_rollback`` for the driving loop to execute."""
        action = self.guard.observe(self.gstep, loss, grad_norm,
                                    amp_active=amp_active)
        self.last_action = action
        if action == GuardAction.ROLLBACK:
            self.pending_rollback = "divergence"
        return action

    def note_step_ok(self, state: Any = None) -> None:
        self.consecutive_step_failures = 0
        self.gstep += 1
        self.heartbeat.maybe_beat(self.gstep)
        self.maybe_poll()
        if state is not None:
            self.elastic.maybe_save(self.gstep, state)
            if self.integrity is not None:
                verdict = self.integrity.maybe_check(self.gstep, state)
                if (verdict is not None and not verdict.ok
                        and self.pending_integrity is None):
                    self.pending_integrity = verdict

    def note_step_failure(self, reason: str = "step-timeout") -> str:
        """SKIP while repeated failures stay inside the budget; beyond it
        the failing step is a symptom, not an accident → ROLLBACK."""
        self.consecutive_step_failures += 1
        self.report.record("step_failure", step=self.gstep, reason=reason,
                           consecutive=self.consecutive_step_failures)
        if self.consecutive_step_failures > self.step_failure_budget:
            self.pending_rollback = reason
            return GuardAction.ROLLBACK
        return GuardAction.SKIP

    def maybe_poll(self) -> None:
        """Heartbeat-health poll, throttled to half the stale window.
        With an elastic coordinator attached, a LOST_WORKER verdict
        latches a resize to the surviving width instead of leaving
        rollback-at-full-width as the only remedy (ISSUE 9)."""
        now = float(self._clock())
        if now - self._last_poll >= self.monitor.stale_after / 2.0:
            self._last_poll = now
            detail = self.monitor.poll()
            if (self.coordinator is not None
                    and self.pending_resize is None
                    and detail["state"] == RunState.LOST_WORKER):
                gone = sorted(set(detail["lost"]) | set(detail["missing"]))
                current = self.coordinator.dp or self.coordinator.max_dp
                target = self.coordinator.clamp(current - len(gone))
                if target != self.coordinator.dp:
                    self.request_resize(
                        target, reason="lost-worker:" + ",".join(
                            str(w) for w in gone))

    # -- elastic resize (ISSUE 9) ------------------------------------------
    def request_resize(self, new_dp: int, reason: str = "scale-signal"
                       ) -> None:
        """Latch a resize for the driving loop to execute (same protocol
        as ``pending_rollback``) — callable from a scale signal, a
        callback, or the lost-worker poll above."""
        if self.coordinator is None:
            raise RuntimeError("request_resize needs an ElasticCoordinator "
                               "(RunSupervisor(coordinator=...))")
        self.pending_resize = {"dp": int(new_dp), "reason": str(reason)}
        self.report.record("elastic.resize_requested", dp=int(new_dp),
                           reason=reason, step=self.gstep)

    def perform_resize(self, init_fn: Callable[[], Any],
                       template_fn: Callable[[], Any]) -> Tuple[Any, int]:
        """Execute the latched resize: quiesce → re-form the mesh →
        re-shard the last committed state → rewind to last_good_step —
        one checkpoint interval lost, not the run."""
        req = self.pending_resize or {"dp": self.coordinator.dp,
                                      "reason": "requested"}
        self.pending_resize = None
        state, start = self.coordinator.resize(
            req["dp"], template_fn, init_fn, reason=req["reason"])
        self.consecutive_step_failures = 0
        self.guard.reset_after_rollback()
        vlog(0, "supervisor: elastic resize rewound step counter %d → %d",
             self.gstep, start)
        self.gstep = start
        return state, start

    # -- state-integrity healing (ISSUE 11) --------------------------------
    def recheck_integrity(self, step: Optional[int] = None
                          ) -> Optional["IntegrityVerdict"]:
        """Fleet-barrier form of the integrity compare: re-vote after
        every member's boards landed (a worker whose ``note_step_ok``
        ran before its peers' saw an incomplete board set), latching a
        mismatch exactly like ``note_step_ok`` does."""
        if self.integrity is None or not self.integrity.enabled:
            return None
        verdict = self.integrity.recheck(step)
        if (verdict is not None and not verdict.ok
                and self.pending_integrity is None):
            self.pending_integrity = verdict
        return verdict

    def perform_integrity_heal(self, init_fn: Callable[[], Any],
                               template_fn: Callable[[], Any],
                               state: Any) -> Tuple[Any, int]:
        """Execute the latched integrity heal: majority members publish
        the resync offer and continue; suspects climb the
        resync → rollback → evict ladder.  Returns ``(state, start)`` —
        unchanged for the majority side."""
        verdict = self.pending_integrity
        self.pending_integrity = None
        if verdict is None or self.integrity is None:
            return state, self.gstep
        st, start, action = self.integrity.heal(
            self, verdict, init_fn, template_fn, state)
        if action in ("rollback", "evict", "resync"):
            self.consecutive_step_failures = 0
        if start != self.gstep:
            vlog(0, "supervisor: integrity heal (%s) rewound step "
                 "counter %d → %d", action, self.gstep, start)
            self.gstep = start
        return st, start

    def perform_rollback(self, init_fn: Callable[[], Any],
                         template_fn: Callable[[], Any],
                         reason: Optional[str] = None) -> Tuple[Any, int]:
        reason = reason or self.pending_rollback or "requested"
        state, start = self.rollback.rollback(init_fn, template_fn,
                                              reason=reason)
        self.pending_rollback = None
        self.consecutive_step_failures = 0
        self.guard.reset_after_rollback()
        vlog(0, "supervisor: rewound step counter %d → %d", self.gstep,
             start)
        self.gstep = start
        return state, start
