"""Auto-rollback to the last-good committed checkpoint (ISSUE 2).

The storage layer (PR 1) guarantees that ``ElasticTrainState`` always
holds a restorable chain of committed steps; this module decides *when*
to walk back down it.  On escalated divergence or repeated step failure
the :class:`RollbackManager` waits out any in-flight async save, restores
the newest committed good step through ``restore_or`` (which quarantines
anything corrupt on the way), rewinds the step counter to the restored
step, optionally reseeds the data pipeline, and lets training resume.

The whole mechanism is bounded by a **rollback budget**
(``PTPU_ROLLBACK_BUDGET``, default 2): a run that needs a third rollback
is broken, not unlucky, and :class:`RollbackBudgetExceeded` fails it
loudly with the post-mortem report path in the message.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional, Tuple

from ..framework.log import vlog

__all__ = ["RollbackManager", "RollbackBudgetExceeded"]

BUDGET_ENV = "PTPU_ROLLBACK_BUDGET"


def default_budget() -> int:
    return int(os.environ.get(BUDGET_ENV, "2"))


class RollbackBudgetExceeded(RuntimeError):
    """The run kept diverging/failing past its rollback budget."""


class RollbackManager:
    """Bounded restore-and-resume driver over an ``ElasticTrainState``.

    ``reseed``: optional callable invoked with the restored start step —
    the hook for reshuffling/reseeding the data pipeline so the resumed
    run does not replay the exact batch sequence that diverged.
    """

    def __init__(self, elastic, budget: Optional[int] = None, report=None,
                 reseed: Optional[Callable[[int], None]] = None):
        self.elastic = elastic
        self.budget = default_budget() if budget is None else int(budget)
        self.report = report
        self.reseed = reseed
        self.used = 0

    def remaining(self) -> int:
        return max(0, self.budget - self.used)

    def rollback(self, init_fn: Callable[[], Any],
                 template_fn: Callable[[], Any],
                 reason: str = "divergence") -> Tuple[Any, int]:
        """(restored_state, start_step) from the newest committed good
        checkpoint — ``(init_fn(), 0)`` when none survive.  Raises
        :class:`RollbackBudgetExceeded` once the budget is spent."""
        self.used += 1
        if self.used > self.budget:
            if self.report is not None:
                self.report.record("rollback_budget_exhausted",
                                   reason=reason, budget=self.budget)
                self.report.flush()
            where = getattr(self.report, "path", None)
            raise RollbackBudgetExceeded(
                f"rollback budget of {self.budget} exhausted ({reason}); "
                "the run is failing persistently, not transiently"
                + (f" — post-mortem report: {where}" if where else ""))
        # an async save may still be committing the very step we need
        try:
            self.elastic.wait()
        except Exception as e:
            vlog(0, "rollback: pending async save failed (%s) — restoring "
                 "from the last committed step anyway", e)
        target = self.elastic.last_good_step()
        vlog(0, "rollback: %s — restoring last good step %s (%d/%d used)",
             reason, target, self.used, self.budget)
        state, start = self.elastic.restore_or(init_fn, template_fn)
        if self.report is not None:
            self.report.record("rollback", reason=reason,
                               restored_step=start - 1 if start else None,
                               start_step=start, used=self.used,
                               budget=self.budget)
        if self.reseed is not None:
            self.reseed(start)
        return state, start
