"""Post-mortem event log for the run supervisor (ISSUE 2).

Every health event the supervisor observes — watchdog timeout, skipped
batch, LR backoff, heartbeat staleness, checkpoint quarantine, rollback,
budget exhaustion — lands here as one JSON record, and the whole log is
flushed durably (``utils/fsio.atomic_write_bytes``) after each record, so
a run that dies mid-incident still leaves a readable account of what the
supervisor saw and did.  The report is the contract between the run and
whoever (human or launcher) has to decide what to do with its corpse.
"""
from __future__ import annotations

import json
import time
from collections import Counter
from typing import Any, Dict, List, Optional

from ..framework.log import vlog
from ..utils import fsio

__all__ = ["SupervisorReport"]


class SupervisorReport:
    """Append-only, durably flushed JSON event log.

    >>> report = SupervisorReport("run/supervisor_report.json")
    >>> report.record("watchdog_timeout", label="train_batch", seconds=300)
    >>> report.counts()["watchdog_timeout"]
    1

    ``path=None`` keeps the log in memory only (unit tests, dry runs).
    The ``record`` signature doubles as the generic event-sink callable
    other layers accept (``ElasticTrainState(event_sink=report.record)``).
    """

    def __init__(self, path: Optional[str] = None, clock=time.time):
        self.path = path
        self.events: List[Dict[str, Any]] = []
        self._clock = clock

    def record(self, kind: str, **fields) -> Dict[str, Any]:
        event = {"kind": str(kind), "time": float(self._clock())}
        event.update(fields)
        self.events.append(event)
        vlog(1, "supervisor: event %s %s", kind, fields)
        self._mirror_to_metrics(event)
        self.flush()
        return event

    def _mirror_to_metrics(self, event: Dict[str, Any]) -> None:
        """Every supervisor event also lands on the telemetry timeline
        (ISSUE 3): a ``supervisor.<kind>`` record through whatever sinks
        are attached — so one JSONL stream interleaves step breakdowns
        with watchdog fires, guard verdicts, heartbeat transitions and
        rollbacks — plus a counter per kind for dashboards."""
        try:
            from ..observability import get_registry
            reg = get_registry()
            kind = event["kind"]
            reg.counter(f"supervisor.{kind}").inc()
            fields = {k: v for k, v in event.items()
                      if k not in ("kind", "time", "ts")}
            reg.emit(f"supervisor.{kind}", ts=event["time"], **fields)
        except Exception as e:
            # telemetry is best-effort; the durable report above is the
            # record of truth
            vlog(1, "supervisor: metrics mirror failed: %r", e)

    def flush(self) -> None:
        if self.path is None:
            return
        payload = json.dumps({"events": self.events}, indent=1,
                             default=str).encode("utf-8")
        try:
            fsio.atomic_write_bytes(self.path, payload)
        except OSError as e:
            # the report must never take the run down with it
            vlog(0, "supervisor: report flush to %s failed: %s",
                 self.path, e)

    def counts(self) -> Dict[str, int]:
        return dict(Counter(e["kind"] for e in self.events))

    def of_kind(self, kind: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e["kind"] == kind]

    def summary(self) -> str:
        counts = self.counts()
        body = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
        return f"supervisor report ({len(self.events)} events): {body or '—'}"

    @classmethod
    def load(cls, path: str) -> "SupervisorReport":
        report = cls(path=None)
        report.events = json.loads(fsio.read_bytes(path))["events"]
        report.path = path
        return report
