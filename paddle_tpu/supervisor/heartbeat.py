"""Per-worker heartbeats + staleness classification (ISSUE 2).

On an elastic pod, a worker that dies between checkpoints is invisible
to the storage layer — its last heartbeat is the only evidence.  Every
worker runs a :class:`HeartbeatWriter` that periodically writes a small
JSON beat file through the fsync'd ``utils/fsio`` seam (so the fault
harness can tear/fail heartbeat writes like any other durable write)
under ``<run_dir>/heartbeats/``; any process — the rank-0 supervisor,
the launcher, an external babysitter — runs a :class:`HeartbeatMonitor`
over the same directory and classifies the run:

    HEALTHY      every expected worker beat within ``stale_after``
    DEGRADED     someone is late (stale_after < age <= lost_after)
    LOST_WORKER  someone is gone (age > lost_after, or never appeared)

``distributed/launch`` polls this to log/act on membership loss, and the
run supervisor records every state transition in the post-mortem report.

Env knob: ``PTPU_HEARTBEAT_SECS`` (default 10) seeds the beat interval;
staleness defaults to 3 intervals, loss to 3× staleness.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..framework.log import vlog
from ..utils import fsio

__all__ = ["RunState", "HeartbeatWriter", "HeartbeatMonitor",
           "heartbeat_dir"]

DEFAULT_INTERVAL_ENV = "PTPU_HEARTBEAT_SECS"
_BEAT_PREFIX = "worker-"
_BEAT_SUFFIX = ".hb.json"


class RunState:
    HEALTHY = "healthy"
    DEGRADED = "degraded"
    LOST_WORKER = "lost-worker"


def default_interval() -> float:
    return float(os.environ.get(DEFAULT_INTERVAL_ENV, "10"))


def heartbeat_dir(run_dir: str) -> str:
    return os.path.join(run_dir, "heartbeats")


def _beat_path(run_dir: str, worker_id: int) -> str:
    return os.path.join(heartbeat_dir(run_dir),
                        f"{_BEAT_PREFIX}{int(worker_id)}{_BEAT_SUFFIX}")


class HeartbeatWriter:
    """Writes this worker's beat file; ``start()`` spawns a daemon thread
    beating every ``interval`` seconds, and the training loop may call
    ``beat(step=...)`` directly after each step for a fresher signal."""

    def __init__(self, run_dir: str, worker_id: Optional[int] = None,
                 interval: Optional[float] = None, clock=time.time):
        import jax
        self.run_dir = run_dir
        self.worker_id = (jax.process_index() if worker_id is None
                          else int(worker_id))
        self.interval = (default_interval() if interval is None
                         else float(interval))
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # beat() runs on both the daemon thread and the training loop
        # (maybe_beat); the beat state is shared and lock-guarded
        self._lock = threading.Lock()
        self.beats = 0                          # guarded_by: _lock
        self._last_step: Optional[int] = None   # guarded_by: _lock
        self._last_beat = 0.0                   # guarded_by: _lock
        #: world generation stamped into every beat when set (elastic
        #: fleets: lets any reader spot a zombie from an older world)
        self.generation: Optional[int] = None

    @property
    def path(self) -> str:
        return _beat_path(self.run_dir, self.worker_id)

    def beat(self, step: Optional[int] = None) -> None:
        # held across the write too: a concurrent loop-beat and
        # thread-beat must not interleave payload vs counter bumps
        with self._lock:
            if step is not None:
                self._last_step = int(step)
            payload = {"worker": self.worker_id, "pid": os.getpid(),
                       "time": float(self._clock()),
                       "step": self._last_step,
                       "beats": self.beats}
            if self.generation is not None:
                payload["generation"] = int(self.generation)
            os.makedirs(heartbeat_dir(self.run_dir), exist_ok=True)
            try:
                fsio.atomic_write_bytes(
                    self.path, json.dumps(payload).encode("utf-8"))
                self.beats += 1
                self._last_beat = payload["time"]
            except OSError as e:
                # a failed beat must not kill the worker it describes; the
                # monitor sees staleness, which is the correct signal anyway
                vlog(0, "heartbeat: write to %s failed: %s", self.path, e)

    def maybe_beat(self, step: Optional[int] = None) -> bool:
        """Beat only when half an interval has passed — the training loop
        can call this every step without fsync'ing every step."""
        with self._lock:
            if step is not None:
                self._last_step = int(step)  # freshest step even when skipping
            if float(self._clock()) - self._last_beat < self.interval / 2.0:
                return False
        self.beat(step)
        return True

    def start(self) -> "HeartbeatWriter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ptpu-heartbeat", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        self.beat()
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "HeartbeatWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class HeartbeatMonitor:
    """Classifies run health from the beat files under ``run_dir``.

    ``expected``: worker count the run was launched with (``None`` means
    "whoever has ever beaten") — a worker that never wrote a beat within
    ``lost_after`` of monitor construction counts as lost.  An elastic
    fleet (ISSUE 9) passes a *set of member ids* instead and updates it
    on every resize (``set_expected``): beats from retired workers'
    stale files stop counting against the run's health.
    """

    def __init__(self, run_dir: str, stale_after: Optional[float] = None,
                 lost_after: Optional[float] = None,
                 expected=None, clock=time.time,
                 report=None):
        self.run_dir = run_dir
        base = default_interval()
        self.stale_after = (3.0 * base if stale_after is None
                            else float(stale_after))
        self.lost_after = (3.0 * self.stale_after if lost_after is None
                           else float(lost_after))
        self.expected = expected
        self._clock = clock
        self.report = report
        self._born = float(clock())
        self._last_state: Optional[str] = None

    def _read_beats(self) -> Dict[int, Dict[str, Any]]:
        hb_dir = heartbeat_dir(self.run_dir)
        beats: Dict[int, Dict[str, Any]] = {}
        if not os.path.isdir(hb_dir):
            return beats
        for name in os.listdir(hb_dir):
            if not (name.startswith(_BEAT_PREFIX)
                    and name.endswith(_BEAT_SUFFIX)):
                continue
            try:
                payload = json.loads(
                    fsio.read_bytes(os.path.join(hb_dir, name)))
                beats[int(payload["worker"])] = payload
            except (OSError, ValueError, KeyError):
                continue  # torn/garbled beat reads as "no beat" → stale
        return beats

    def set_expected(self, expected) -> None:
        """Adopt a new membership (count or id set) — the elastic
        reconciler calls this on every resize."""
        self.expected = expected

    def _expected_ids(self):
        if self.expected is None:
            return None
        if isinstance(self.expected, int):
            return set(range(self.expected))
        return {int(w) for w in self.expected}

    def poll(self) -> Dict[str, Any]:
        """One classification pass → ``{"state", "workers", "stale",
        "lost", "missing"}``; records a ``run_state`` event on every
        transition."""
        now = float(self._clock())
        beats = self._read_beats()
        expected_ids = self._expected_ids()
        if expected_ids is not None:
            # a retired member's beat file outlives it; only current
            # members can make the run stale/lost
            beats = {w: p for w, p in beats.items() if w in expected_ids}
        stale, lost = [], []
        for wid, payload in beats.items():
            age = now - float(payload.get("time", 0.0))
            if age > self.lost_after:
                lost.append(wid)
            elif age > self.stale_after:
                stale.append(wid)
        missing = []
        if expected_ids is not None:
            unseen = expected_ids - set(beats)
            # an expected worker that has NEVER beaten is only lost once
            # the monitor has waited long enough for a first beat
            if now - self._born > self.lost_after:
                missing = sorted(unseen)
            elif now - self._born > self.stale_after:
                stale.extend(sorted(unseen))
        if lost or missing:
            state = RunState.LOST_WORKER
        elif stale:
            state = RunState.DEGRADED
        else:
            state = RunState.HEALTHY
        detail = {"state": state, "workers": sorted(beats),
                  "stale": sorted(stale), "lost": sorted(lost),
                  "missing": missing}
        if state != self._last_state:
            vlog(0 if state != RunState.HEALTHY else 1,
                 "heartbeat: run state %s → %s (stale=%s lost=%s "
                 "missing=%s)", self._last_state, state, stale, lost,
                 missing)
            if self.report is not None:
                self.report.record("run_state", **detail)
            self._last_state = state
        return detail
