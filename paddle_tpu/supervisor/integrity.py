"""State-integrity guard — detect, attribute and heal silent corruption
and replica desync (ISSUE 11).

PRs 1–9 handle failures that announce themselves (crashes, hangs, NaNs,
lost workers).  The failures that cost multi-week runs are the silent
ones: a bit flips in HBM, a replica's parameters drift from its dp
peers, or a reshard path quietly mangles state — and the run trains on
garbage until the loss betrays it.  Two landed designs make silent
divergence structurally possible: ZeRO-1 replicas hold different 1/dp
optimizer shards, and lossy int8 collectives keep rank-private
error-feedback residuals, so "identical" replicas legitimately disagree
on part of their state and naive bitwise comparison is wrong.

The :class:`IntegrityGuard` closes that gap with four cooperating
pieces, all built on ``distributed/fingerprint.py``'s ZeRO-1-aware tree
digest (rank-private leaves excluded with accounting):

1. **Periodic fingerprint** — every ``PTPU_INTEGRITY_EVERY`` steps the
   live state is digested in-graph (one scalar readback) and published
   to ``<run_dir>/integrity/worker-<i>.fp.json`` through the fsync'd
   ``fsio`` seam (same channel discipline as heartbeats).
2. **Cross-worker compare + attribution** — the guard reads every
   member's board, compares digests at the newest step all members have
   published, and majority-votes: the minority workers are the
   suspects.  A 2-way split with no majority blames nobody and reports
   ``ambiguous`` (both sides get audited by the doctor instead).
3. **Replay audit** — re-run the last microbatch from the stashed
   pre-step state with identical inputs, twice.  Replays that disagree
   with each other → software **nondeterminism**; replays that agree
   with each other but not with the live state → hardware **SDC** (the
   state was damaged outside the computed path); replays that match the
   live state → clean **desync** (the divergence happened earlier or
   upstream — data, collectives).  Stashing is two references per step
   (jax arrays are immutable), so the audit costs nothing until it runs.
4. **Healing ladder** (``PTPU_INTEGRITY_ACTION``, default ``resync``)
   wired into the supervisor's escalation protocol::

       resync    suspect adopts the majority state published under
                 <run_dir>/integrity/resync-step-N/ (majority side
                 writes it once); rank-private leaves reset to zeros
         │ no source in time / repeat offense
         ▼
       rollback  RollbackManager → newest digest-verified checkpoint
         │ strikes exhausted (suspect keeps desyncing) + coordinator
         ▼
       evict     ElasticCoordinator shrink: the fleet re-forms at
                 dp-1 without the bad worker (one interval lost)

   ``report`` detects and records but never heals (forensics mode).

An SDC costs one integrity interval, not the job.  Everything surfaces
through ``integrity.*`` counters/gauges, the ``/statusz`` integrity
section, and the ``desync`` / ``sdc_suspect`` doctor verdicts.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed.fingerprint import (DEFAULT_EXCLUDE, Fingerprint,
                                       TreeFingerprint, is_rank_private)
from ..framework.log import vlog
from ..utils import fsio

__all__ = ["IntegrityGuard", "IntegrityVerdict", "integrity_dir",
           "default_interval", "default_action", "INTERVAL_ENV",
           "ACTION_ENV"]

INTERVAL_ENV = "PTPU_INTEGRITY_EVERY"
ACTION_ENV = "PTPU_INTEGRITY_ACTION"

_BOARD_PREFIX = "worker-"
_BOARD_SUFFIX = ".fp.json"
_RESYNC_PREFIX = "resync-step-"
_HISTORY = 8          # (step, digest) pairs kept per board file
_RESYNC_KEEP = 2      # newest resync checkpoints kept on disk

_ACTIONS = ("report", "resync", "rollback", "evict")


def default_interval() -> int:
    return int(os.environ.get(INTERVAL_ENV, "50"))


def default_action() -> str:
    return os.environ.get(ACTION_ENV, "resync")


def integrity_dir(run_dir: str) -> str:
    return os.path.join(run_dir, "integrity")


def _board_path(run_dir: str, worker_id: int) -> str:
    return os.path.join(integrity_dir(run_dir),
                        f"{_BOARD_PREFIX}{int(worker_id)}{_BOARD_SUFFIX}")


def _reset_rank_private(tree, exclude: Sequence[str]):
    """Zero every rank-private leaf (adopting another replica's EF
    residuals would be wrong — they describe ITS quantization errors)."""
    import jax

    def _zero(path, leaf):
        parts = []
        for k in path:
            parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
        if is_rank_private("/".join(parts), exclude):
            return np.zeros_like(np.asarray(leaf))
        return leaf

    return jax.tree_util.tree_map_with_path(_zero, tree)


class IntegrityVerdict(dict):
    """A compare outcome — a dict (JSON/report-friendly) with attribute
    sugar: ``{"ok", "step", "digests", "majority", "suspects",
    "ambiguous"}``."""

    @property
    def ok(self) -> bool:
        return bool(self["ok"])

    @property
    def suspects(self) -> List[int]:
        return list(self["suspects"])


class IntegrityGuard:
    """Per-worker integrity state machine (one per RunSupervisor).

    ``fingerprint`` may be a shared :class:`TreeFingerprint` (the
    supervisor hands the same instance to ``ElasticTrainState`` so the
    checkpoint stamp and the cross-worker compare use one digest).
    ``expected`` is the member-id set taking part in the vote (count or
    iterable; ``None`` = whoever has published).  ``strike_budget`` is
    how many desyncs a worker may heal by resync before the ladder
    escalates past it.
    """

    def __init__(self, run_dir: str, *, worker_id: int = 0,
                 every: Optional[int] = None, action: Optional[str] = None,
                 exclude: Sequence[str] = DEFAULT_EXCLUDE,
                 expected=None, report=None,
                 fingerprint: Optional[TreeFingerprint] = None,
                 strike_budget: int = 1, resync_timeout: float = 10.0,
                 clock=time.time):
        self.run_dir = run_dir
        self.worker_id = int(worker_id)
        self.every = default_interval() if every is None else int(every)
        self.action = (default_action() if action is None
                       else str(action))
        if self.action not in _ACTIONS:
            raise ValueError(f"unknown integrity action {self.action!r} "
                             f"(one of {_ACTIONS})")
        self.fingerprint = fingerprint or TreeFingerprint(exclude)
        self.expected = expected
        self.report = report
        self.strike_budget = int(strike_budget)
        self.resync_timeout = float(resync_timeout)
        self.generation: Optional[int] = None
        self._clock = clock
        self._history: List[Tuple[int, str]] = []
        self.last_fingerprint: Optional[Fingerprint] = None
        self.last_verdict: Optional[IntegrityVerdict] = None
        self.checks = 0
        self.mismatches = 0
        self.strikes: Dict[int, int] = {}
        #: newest step a heal already handled — boards keep the stale
        #: mismatching digests until the next publish, and re-latching
        #: the same verdict would climb the ladder a second time
        self.resolved_step: Optional[int] = None
        #: replay-audit stash: (step, pre_state, inputs) references
        self._stash: Optional[Tuple[int, Any, Any]] = None
        #: ``fn(state, inputs) -> state`` — a deterministic re-run of one
        #: train step, registered by the training loop for the audit
        self.replay_fn: Optional[Callable[[Any, Any], Any]] = None

    @property
    def enabled(self) -> bool:
        return self.every > 0

    # -- plumbing -----------------------------------------------------------
    def _record(self, kind: str, **fields) -> None:
        if self.report is not None:
            try:
                self.report.record(kind, **fields)
            except Exception as e:
                vlog(0, "integrity: report sink failed for %s: %s", kind, e)
        try:
            from ..observability import get_registry
            get_registry().emit(kind, worker=self.worker_id, **fields)
        except Exception as e:
            vlog(1, "integrity: metrics emit failed: %r", e)

    def _metrics(self, counters: Sequence[str] = (), **gauges) -> None:
        try:
            from ..observability import get_registry
            reg = get_registry()
            for name in counters:
                reg.counter(f"integrity.{name}").inc()
            for name, value in gauges.items():
                reg.gauge(f"integrity.{name}").set(float(value))
        except Exception as e:
            vlog(1, "integrity: metrics failed: %r", e)

    def _expected_ids(self) -> Optional[set]:
        if self.expected is None:
            return None
        if isinstance(self.expected, int):
            return set(range(self.expected))
        return {int(w) for w in self.expected}

    def set_expected(self, expected) -> None:
        """Adopt new membership (elastic resize / eviction)."""
        self.expected = expected

    # -- publication channel ------------------------------------------------
    def publish(self, step: int, fpr: Fingerprint) -> None:
        """Write this worker's digest board (newest ``_HISTORY`` entries
        — peers at slightly different steps still find a common step)."""
        self._history = ([(int(step), fpr.hex())] + self._history)[:_HISTORY]
        payload = {"worker": self.worker_id, "time": float(self._clock()),
                   "digests": [{"step": s, "digest": d}
                               for s, d in self._history],
                   "excluded": len(fpr.excluded)}
        if self.generation is not None:
            payload["generation"] = int(self.generation)
        os.makedirs(integrity_dir(self.run_dir), exist_ok=True)
        try:
            fsio.atomic_write_bytes(
                _board_path(self.run_dir, self.worker_id),
                json.dumps(payload).encode("utf-8"))
        except OSError as e:
            # like a failed heartbeat: absence is itself a signal
            vlog(0, "integrity: board write failed: %s", e)

    def _read_boards(self) -> Dict[int, Dict[int, str]]:
        """{worker: {step: digest}} from every board file."""
        d = integrity_dir(self.run_dir)
        out: Dict[int, Dict[int, str]] = {}
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            if not (name.startswith(_BOARD_PREFIX)
                    and name.endswith(_BOARD_SUFFIX)):
                continue
            try:
                payload = json.loads(fsio.read_bytes(os.path.join(d, name)))
                hist: Dict[int, str] = {}
                for e in payload["digests"]:  # newest-first: a re-publish
                    hist.setdefault(int(e["step"]), str(e["digest"]))
                out[int(payload["worker"])] = hist  # shadows a stale entry
            except (OSError, ValueError, KeyError, TypeError):
                continue  # torn read → that worker just has no board yet
        return out

    # -- compare + attribution ---------------------------------------------
    def compare(self, step: Optional[int] = None) -> IntegrityVerdict:
        """Majority-vote the boards at the newest step every expected
        member has published (or exactly ``step`` when given)."""
        boards = self._read_boards()
        expected = self._expected_ids()
        if expected is not None:
            boards = {w: h for w, h in boards.items() if w in expected}
        members = sorted(expected if expected is not None else boards)
        common: Optional[int] = step
        if common is None:
            steps = [set(h) for h in boards.values()]
            if expected is not None and set(boards) != expected:
                steps = []  # someone hasn't published at all yet
            shared = set.intersection(*steps) if steps else set()
            common = max(shared) if shared else None
        if common is None:
            return IntegrityVerdict(
                ok=True, step=None, digests={}, majority=None,
                suspects=[], ambiguous=False, members=members)
        digests = {w: h[common] for w, h in boards.items() if common in h}
        votes: Dict[str, List[int]] = {}
        for w, dgt in digests.items():
            votes.setdefault(dgt, []).append(w)
        if len(votes) <= 1:
            return IntegrityVerdict(
                ok=True, step=common, digests=digests,
                majority=next(iter(votes), None), suspects=[],
                ambiguous=False, members=members)
        ranked = sorted(votes.items(), key=lambda kv: (-len(kv[1]), kv[0]))
        top, runner = ranked[0], ranked[1]
        ambiguous = len(top[1]) == len(runner[1])
        suspects = ([] if ambiguous else
                    sorted(w for d, ws in ranked[1:] for w in ws))
        return IntegrityVerdict(
            ok=False, step=common, digests=digests,
            majority=None if ambiguous else top[0],
            suspects=suspects, ambiguous=ambiguous, members=members)

    # -- the per-interval check --------------------------------------------
    def stash_replay(self, step: int, state, inputs) -> None:
        """Keep references to this step's (pre-state, inputs) — the
        replay audit's raw material.  Two pointer assignments per step."""
        self._stash = (int(step), state, inputs)

    def maybe_check(self, step: int, state) -> Optional[IntegrityVerdict]:
        """Digest + publish + compare on interval boundaries.  Returns
        the verdict when a check ran (mismatch verdicts carry suspects
        for the supervisor to latch), else None."""
        if not self.enabled or step <= 0 or step % self.every != 0:
            return None
        fpr = self.fingerprint.digest(state)
        self.last_fingerprint = fpr
        self.checks += 1
        self.publish(step, fpr)
        self._metrics(counters=["checks"], last_step=step,
                      interval=self.every, digest=fpr.tree)
        return self._adjudicate(self.compare(step))

    def recheck(self, step: Optional[int] = None
                ) -> Optional[IntegrityVerdict]:
        """Re-run the compare after peers published (a fleet barrier):
        a worker whose ``maybe_check`` ran before its peers' saw an
        incomplete board set and voted on a stale common step.  Full
        strike/record accounting, same as ``maybe_check``, minus the
        digest + publish; a verdict identical to the last one is
        returned without double-counting."""
        if not self.enabled:
            return None
        verdict = self.compare(step)
        if (not verdict.ok and self.resolved_step is not None
                and verdict["step"] is not None
                and verdict["step"] <= self.resolved_step):
            return None  # stale boards from a step a heal already handled
        if (self.last_verdict is not None
                and dict(verdict) == dict(self.last_verdict)):
            return self.last_verdict
        return self._adjudicate(verdict)

    def _adjudicate(self, verdict: IntegrityVerdict) -> IntegrityVerdict:
        self.last_verdict = verdict
        self._metrics(workers=len(verdict["digests"]),
                      suspects=len(verdict["suspects"]))
        if verdict.ok:
            self._record("integrity.check", step=verdict["step"],
                         digest=(self.last_fingerprint.hex()
                                 if self.last_fingerprint else None),
                         workers=len(verdict["digests"]), ok=True)
            return verdict
        self.mismatches += 1
        for w in (verdict.suspects or verdict["digests"]):
            if not verdict["ambiguous"] or w in verdict.suspects:
                self.strikes[w] = self.strikes.get(w, 0) + 1
        self._metrics(counters=["mismatches"])
        self._record("integrity.desync", step=verdict["step"],
                     digests=dict(verdict["digests"]),
                     majority=verdict["majority"],
                     suspects=verdict.suspects,
                     ambiguous=verdict["ambiguous"])
        vlog(0, "integrity: DESYNC at step %s — digests %s, suspects %s%s",
             verdict["step"], verdict["digests"], verdict.suspects,
             " (ambiguous: no majority)" if verdict["ambiguous"] else "")
        return verdict

    # -- replay audit -------------------------------------------------------
    def audit(self, replay_fn: Optional[Callable[[Any, Any], Any]] = None
              ) -> Dict[str, Any]:
        """Re-run the stashed microbatch twice with identical inputs and
        classify this replica (see module docstring):

        - ``nondeterminism`` — the two replays disagree;
        - ``sdc_suspect``    — replays agree with each other, not with
          the live digest: state damaged outside the computed path;
        - ``desync``         — replays reproduce the live state: this
          replica computes consistently, the divergence is upstream.
        """
        replay_fn = replay_fn or self.replay_fn
        if replay_fn is None or self._stash is None:
            return {"verdict": "unavailable",
                    "reason": ("no replay_fn registered"
                               if replay_fn is None else "nothing stashed")}
        step, pre_state, inputs = self._stash
        d1 = self.fingerprint.digest(replay_fn(pre_state, inputs)).hex()
        d2 = self.fingerprint.digest(replay_fn(pre_state, inputs)).hex()
        live = (self.last_fingerprint.hex()
                if self.last_fingerprint is not None else None)
        if d1 != d2:
            verdict = "nondeterminism"
        elif live is not None and d1 != live:
            verdict = "sdc_suspect"
        else:
            verdict = "desync"
        out = {"verdict": verdict, "step": step, "replay": d1,
               "replay2": d2, "live": live}
        self._metrics(counters=["audits"])
        self._record("integrity.audit", **out)
        vlog(0, "integrity: replay audit at step %d → %s "
             "(replay=%s/%s live=%s)", step, verdict, d1, d2, live)
        return out

    # -- healing ladder -----------------------------------------------------
    def _resync_path(self, step: int) -> str:
        return os.path.join(integrity_dir(self.run_dir),
                            f"{_RESYNC_PREFIX}{int(step)}")

    def offer_resync(self, step: int, state) -> str:
        """Majority side: publish the known-good state once (idempotent
        across majority members — first writer wins) and gc old offers."""
        from ..distributed.checkpoint import save_sharded
        path = self._resync_path(step)
        done = os.path.join(path, "COMMITTED")
        if os.path.exists(done):
            return path
        fpr = self.fingerprint.digest(state)
        meta = fpr.meta()
        meta["exclude"] = list(self.fingerprint.exclude)
        save_sharded(state, path, integrity=meta)
        fsio.write_bytes(done, b"")
        fsio.fsync_dir(integrity_dir(self.run_dir))
        self._gc_resync()
        self._record("integrity.resync_offered", step=step,
                     digest=fpr.hex(), path=path)
        return path

    def _gc_resync(self) -> None:
        import shutil
        d = integrity_dir(self.run_dir)
        offers = sorted(
            (int(n[len(_RESYNC_PREFIX):]), n) for n in os.listdir(d)
            if n.startswith(_RESYNC_PREFIX)
            and n[len(_RESYNC_PREFIX):].isdigit())
        for _s, name in offers[:-_RESYNC_KEEP]:
            shutil.rmtree(os.path.join(d, name), ignore_errors=True)

    def take_resync(self, step: int, template_fn: Callable[[], Any]
                    ) -> Optional[Any]:
        """Suspect side: wait for a majority offer and adopt it (digest-
        verified by ``load_sharded``; rank-private leaves reset).  None
        when no offer lands inside ``resync_timeout``."""
        from ..distributed.checkpoint import load_sharded
        path = self._resync_path(step)
        done = os.path.join(path, "COMMITTED")
        deadline = float(self._clock()) + self.resync_timeout
        while not os.path.exists(done):
            if float(self._clock()) >= deadline:
                return None
            time.sleep(0.05)
        state = load_sharded(path, template_fn())
        return _reset_rank_private(state, self.fingerprint.exclude)

    def heal(self, supervisor, verdict: IntegrityVerdict,
             init_fn: Callable[[], Any], template_fn: Callable[[], Any],
             state) -> Tuple[Any, int, str]:
        """Run the ladder for a latched mismatch verdict; returns
        ``(state, start_step, action_taken)``.  Majority members serve
        the resync offer and continue; suspects climb
        resync → rollback → evict as far as circumstance requires."""
        step = int(verdict["step"])
        self.resolved_step = max(step, self.resolved_step or 0)
        suspect = self.worker_id in verdict.suspects
        audit = (self.audit() if suspect else None)
        rung = self.action
        if rung == "report":
            self._record("integrity.heal", step=step, action="report",
                         suspect=suspect)
            return state, supervisor.gstep, "report"
        if not suspect and not verdict["ambiguous"]:
            # healthy majority: serve the known-good state, keep going
            if rung == "resync":
                self.offer_resync(step, state)
            self._record("integrity.heal", step=step, action="offer",
                         suspect=False)
            return state, supervisor.gstep, "offer"
        # ambiguous splits can't name a donor → everyone rolls back
        if verdict["ambiguous"] and rung == "resync":
            rung = "rollback"
        strikes = self.strikes.get(self.worker_id, 1)
        if rung == "resync" and strikes > self.strike_budget:
            rung = "rollback"  # repeat offender: resync isn't sticking
        if rung == "resync":
            healed = self.take_resync(step, template_fn)
            if healed is not None:
                # shadow the stale board entry with the adopted state's
                # digest — peers comparing at this step must now agree
                fpr = self.fingerprint.digest(healed)
                self.last_fingerprint = fpr
                self.publish(step, fpr)
                self._metrics(counters=["resyncs"])
                self._record("integrity.heal", step=step, action="resync",
                             suspect=True, audit=audit,
                             strikes=strikes)
                return healed, supervisor.gstep, "resync"
            vlog(0, "integrity: no resync offer within %.1fs — "
                 "escalating to rollback", self.resync_timeout)
            rung = "rollback"
        if rung == "evict" or (strikes > self.strike_budget + 1
                               and supervisor.coordinator is not None):
            coord = supervisor.coordinator
            if coord is not None:
                target = coord.clamp((coord.dp or coord.max_dp) - 1)
                self._metrics(counters=["evictions"])
                self._record("integrity.heal", step=step, action="evict",
                             suspect=True, audit=audit, new_dp=target)
                supervisor.request_resize(
                    target, reason=f"integrity-evict:{self.worker_id}")
                st, start = supervisor.perform_resize(init_fn, template_fn)
                return st, start, "evict"
            rung = "rollback"  # nothing to shrink: degrade
        self._metrics(counters=["rollbacks"])
        self._record("integrity.heal", step=step, action="rollback",
                     suspect=True, audit=audit, strikes=strikes)
        st, start = supervisor.perform_rollback(
            init_fn, template_fn, reason=f"integrity:{step}")
        return st, start, "rollback"
