"""paddle.incubate analog (reference: python/paddle/incubate — LookAhead /
ModelAverage optimizers, incubate.nn fused transformer layers,
softmax_mask_fuse ops)."""
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import sparsity  # noqa: F401 (ASP n:m structured pruning)
from .graph_ops import graph_send_recv  # noqa: F401
from ..nn.functional import (  # noqa: F401
    softmax_mask_fuse_upper_triangle)

__all__ = ["nn", "optimizer", "sparsity", "graph_send_recv",
           "softmax_mask_fuse_upper_triangle"]
