"""paddle.incubate analog (reference: python/paddle/incubate — LookAhead /
ModelAverage optimizers, incubate.nn fused transformer layers,
softmax_mask_fuse ops)."""
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import sparsity  # noqa: F401 (ASP n:m structured pruning)
from .graph_ops import (graph_send_recv, graph_khop_sampler,  # noqa: F401
                        graph_sample_neighbors, graph_reindex,
                        segment_sum, segment_mean, segment_max,
                        segment_min)
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..nn.functional import (  # noqa: F401
    softmax_mask_fuse_upper_triangle)


def softmax_mask_fuse(x, mask):
    """softmax(x + mask) fused (reference incubate.softmax_mask_fuse —
    fused_softmax_mask_op); XLA fuses the add into the softmax."""
    import jax
    import jax.numpy as jnp
    xf = jnp.asarray(x).astype(jnp.float32) + jnp.asarray(mask).astype(
        jnp.float32)
    return jax.nn.softmax(xf, axis=-1).astype(jnp.asarray(x).dtype)


__all__ = ["nn", "optimizer", "sparsity", "graph_send_recv",
           "softmax_mask_fuse_upper_triangle", "softmax_mask_fuse",
           "LookAhead", "ModelAverage", "graph_khop_sampler",
           "graph_sample_neighbors", "graph_reindex", "segment_sum",
           "segment_mean", "segment_max", "segment_min"]
