"""ASP — Automatic SParsity (n:m structured pruning).

Reference: python/paddle/fluid/contrib/sparsity/{asp.py,utils.py}
(ASPHelper asp.py:289, decorate :117, prune_model :156; mask algorithms
utils.py:181 get_mask_1d, :314 get_mask_2d_greedy, :422 get_mask_2d_best).

The reference's *purpose* is Ampere sparse-tensor-core speedup; the
*capability* is n:m structured pruning plus an optimizer guard that keeps
the pattern through training.  TPUs have no 2:4 sparse MXU mode, so the
speedup half is N/A here (documented); the pruning capability — mask
computation, model pruning, sparsity-preserving optimizer decoration,
pattern checkers — is implemented in full.  Masks are computed on host
numpy (one-off, offline); the training-time guard is a single fused
elementwise multiply inside the jitted update.
"""
from __future__ import annotations

import itertools
from enum import Enum
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..framework.errors import enforce

__all__ = [
    "MaskAlgo", "CheckMethod", "calculate_density", "get_mask_1d",
    "get_mask_2d_greedy", "get_mask_2d_best", "check_mask_1d",
    "check_mask_2d", "create_mask", "check_sparsity", "decorate",
    "prune_model", "set_excluded_layers", "reset_excluded_layers",
    "reset_masks",
]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo: MaskAlgo) -> "CheckMethod":
        return (CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D
                else CheckMethod.CHECK_2D)


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference utils.py:87)."""
    a = np.asarray(x)
    return float(np.count_nonzero(a)) / a.size


# -- mask algorithms (host numpy; masks are offline artifacts) -------------
def _pad_cols(mat: np.ndarray, m: int) -> np.ndarray:
    pad = (-mat.shape[1]) % m
    if pad:
        mat = np.concatenate(
            [mat, np.zeros((mat.shape[0], pad), mat.dtype)], axis=1)
    return mat


def get_mask_1d(mat, n: int, m: int) -> np.ndarray:
    """Zero the n smallest-magnitude entries of every m consecutive values
    along each row (reference utils.py:181; n:m = "at least n zeros per
    1 x m block", so 2:4 keeps the 2 largest of every 4)."""
    mat = np.asarray(mat)
    h, w = mat.shape
    padded = _pad_cols(np.abs(mat), m).reshape(-1, m)
    drop = np.argsort(padded, axis=1)[:, :n]
    mask = np.ones_like(padded)
    np.put_along_axis(mask, drop, 0.0, axis=1)
    return mask.reshape(h, -1)[:, :w]


def get_mask_2d_greedy(mat, n: int, m: int) -> np.ndarray:
    """Greedy m x m tile pruning: keep entries in descending magnitude,
    leaving at least n zeros per row AND per column of the tile
    (utils.py:314)."""
    mat = np.asarray(mat)
    h, w = mat.shape
    ph, pw = -h % m, -w % m
    a = np.abs(np.pad(mat, ((0, ph), (0, pw))))
    keep = m - n                      # n zeros per row/col => m-n kept
    mask = np.zeros_like(a)
    for bi in range(0, a.shape[0], m):
        for bj in range(0, a.shape[1], m):
            tile = a[bi:bi + m, bj:bj + m]
            order = np.dstack(np.unravel_index(
                np.argsort(-tile, axis=None), (m, m)))[0]
            rows = np.zeros(m, np.int64)
            cols = np.zeros(m, np.int64)
            for r, c in order:
                if rows[r] < keep and cols[c] < keep:
                    mask[bi + r, bj + c] = 1.0
                    rows[r] += 1
                    cols[c] += 1
    return mask[:h, :w]


def _valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m x m binary patterns with exactly m-n ones per row and column
    — i.e. n zeros per row and column (utils.py:384)."""
    keep = m - n
    rows = [np.array(p) for p in itertools.combinations(range(m), keep)]
    row_vecs = []
    for p in rows:
        v = np.zeros(m)
        v[list(p)] = 1.0
        row_vecs.append(v)
    patterns = []
    for combo in itertools.product(range(len(row_vecs)), repeat=m):
        pat = np.stack([row_vecs[i] for i in combo])
        if (pat.sum(0) == keep).all():
            patterns.append(pat)
    return np.stack(patterns)


_PATTERN_CACHE: Dict[tuple, np.ndarray] = {}


def get_mask_2d_best(mat, n: int, m: int) -> np.ndarray:
    """Exhaustive-pattern m x m tile pruning: per tile, the valid pattern
    maximizing retained magnitude (utils.py:422)."""
    mat = np.asarray(mat)
    key = (n, m)
    if key not in _PATTERN_CACHE:
        _PATTERN_CACHE[key] = _valid_2d_patterns(n, m)
    patterns = _PATTERN_CACHE[key]                  # (P, m, m)
    h, w = mat.shape
    ph, pw = -h % m, -w % m
    a = np.abs(np.pad(mat, ((0, ph), (0, pw))))
    H, W = a.shape
    tiles = a.reshape(H // m, m, W // m, m).transpose(0, 2, 1, 3)
    scores = np.einsum("ijxy,pxy->ijp", tiles, patterns)
    best = patterns[np.argmax(scores, axis=-1)]     # (H/m, W/m, m, m)
    mask = best.transpose(0, 2, 1, 3).reshape(H, W)
    return mask[:h, :w]


def check_mask_1d(mat, n: int, m: int) -> bool:
    """Every m consecutive row-entries hold at least n zeros — i.e.
    <= (m - n) nonzeros (utils.py:137)."""
    mat = np.asarray(mat)
    groups = _pad_cols((mat != 0).astype(np.float64), m).reshape(-1, m)
    return bool((groups.sum(1) <= m - n).all())


def check_mask_2d(mat, n: int, m: int) -> bool:
    """At least n zeros per row AND per column of every m x m tile — i.e.
    <= (m - n) nonzeros each way (utils.py:264; this is the documented
    condition, applied strictly to both axes)."""
    mat = np.asarray(mat)
    h, w = mat.shape
    nz = (np.pad(mat, ((0, -h % m), (0, -w % m))) != 0).astype(np.float64)
    H, W = nz.shape
    tiles = nz.reshape(H // m, m, W // m, m).transpose(0, 2, 1, 3)
    keep = m - n
    return bool((tiles.sum(3) <= keep).all() and (tiles.sum(2) <= keep).all())


def _as_2d(t: np.ndarray) -> np.ndarray:
    """Weight view the masks act on: 2-D as-is; conv kernels (O, I, H, W)
    flatten to (O, I*H*W) — the reference's supported-layer reshape."""
    if t.ndim == 2:
        return t
    return t.reshape(t.shape[0], -1)


def create_mask(tensor, func_name: MaskAlgo = MaskAlgo.MASK_1D,
                n: int = 2, m: int = 4) -> np.ndarray:
    """n:m mask for a parameter tensor (utils.py:475)."""
    t = np.asarray(tensor)
    enforce(t.ndim >= 2, f"ASP supports >=2-D weights, got shape {t.shape}")
    fn = globals()[MaskAlgo(func_name).value]
    mask2d = fn(_as_2d(t), n, m)
    return mask2d.reshape(t.shape).astype(t.dtype)


def check_sparsity(tensor, func_name: CheckMethod = CheckMethod.CHECK_1D,
                   n: int = 2, m: int = 4) -> bool:
    t = np.asarray(tensor)
    fn = globals()[CheckMethod(func_name).value]
    return fn(_as_2d(t), n, m)


# -- model-level API -------------------------------------------------------
_EXCLUDED: List[str] = []
_MASKS: Dict[str, jnp.ndarray] = {}


def set_excluded_layers(param_names, main_program=None) -> None:
    """Exclude parameters (by state_dict name prefix) from pruning
    (asp.py:38; the main_program arg is accepted for signature parity —
    there is one program here)."""
    _EXCLUDED.extend(param_names)


def reset_excluded_layers(main_program=None) -> None:
    _EXCLUDED.clear()


def reset_masks() -> None:
    """Clear the registered pruning masks.  Call between pruning different
    models in one process: the registry is keyed by parameter name, and two
    models easily share names like "0.weight"."""
    _MASKS.clear()


def _supported(name: str, value) -> bool:
    if getattr(value, "ndim", 0) < 2:
        return False                       # biases, norms
    # exact name or dotted-prefix match only — substring matching would
    # make "0.weight" also exclude "10.weight"
    return not any(name == ex or name.startswith(ex + ".")
                   for ex in _EXCLUDED)


def prune_model(model, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d",
                with_mask: bool = True) -> Dict[str, np.ndarray]:
    """Prune every supported weight of ``model`` to the n:m pattern and
    (with_mask) register masks so a decorated optimizer preserves the
    pattern through training (asp.py:156).
    """
    algo = {"mask_1d": MaskAlgo.MASK_1D,
            "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
            "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
    masks: Dict[str, np.ndarray] = {}
    for name, p in model.named_parameters():
        if not _supported(name, p.value):
            continue
        mask = create_mask(np.asarray(p.value), algo, n, m)
        p.value = p.value * jnp.asarray(mask, p.value.dtype)
        masks[name] = mask
        if with_mask:
            _MASKS[name] = jnp.asarray(mask)
    return masks


class OptimizerWithSparsityGuarantee:
    """Wraps a functional optimizer so every update re-applies the
    registered masks (asp.py:571): weight decay / momentum would otherwise
    densify pruned entries."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def init(self, params):
        return self._inner.init(params)

    def apply_gradients(self, grads, params, state, **kw):
        new_params, new_state = self._inner.apply_gradients(
            grads, params, state, **kw)
        if _MASKS:
            # preserve the mapping type — swapping OrderedDict for dict
            # changes the pytree treedef the optimizer state was built with
            new_params = type(new_params)(
                (k, v * _MASKS[k].astype(v.dtype) if k in _MASKS else v)
                for k, v in new_params.items())
        return new_params, new_state


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    """asp.py:117 — returns the sparsity-preserving wrapper."""
    return OptimizerWithSparsityGuarantee(optimizer)
