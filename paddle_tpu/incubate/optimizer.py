"""Incubate optimizers (reference: incubate/optimizer/lookahead.py:26
LookAhead, modelaverage.py:28 ModelAverage).

Both wrap an inner optimizer and keep extra parameter EMAs/snapshots in
their own state pytree, following this framework's functional
init/apply_gradients contract — the whole update stays one jittable step.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.errors import enforce

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k steps forward, one step back (Zhang et al. 2019).

    Every ``k`` inner steps: slow += alpha * (fast - slow); fast = slow.
    """

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        enforce(0.0 <= alpha <= 1.0, "alpha must be in [0, 1]")
        enforce(k >= 1, "k must be >= 1")
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k

    def init(self, params):
        return {"inner": self.inner.init(params),
                "slow": jax.tree_util.tree_map(
                    lambda p: jnp.asarray(p, jnp.float32), params),
                "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, grads, params, state, lr=None):
        fast, inner_state = self.inner.apply_gradients(
            grads, params, state["inner"], lr=lr)
        step = state["step"] + 1
        sync = (step % self.k == 0)

        def _blend(slow, f):
            new_slow = slow + self.alpha * (jnp.asarray(f, jnp.float32)
                                            - slow)
            slow_out = jnp.where(sync, new_slow, slow)
            f_out = jnp.where(sync, new_slow.astype(jnp.asarray(f).dtype),
                              jnp.asarray(f))
            return slow_out, f_out

        flat_slow, treedef = jax.tree_util.tree_flatten(state["slow"])
        flat_fast = treedef.flatten_up_to(fast)
        pairs = [_blend(s, f) for s, f in zip(flat_slow, flat_fast)]
        new_slow = treedef.unflatten([p[0] for p in pairs])
        new_fast = treedef.unflatten([p[1] for p in pairs])
        return new_fast, {"inner": inner_state, "slow": new_slow,
                          "step": step}


class ModelAverage:
    """Maintain a windowed average of parameters for evaluation (reference
    ModelAverage).  The window at update ``t`` is
    ``clip(rate * t, min_average_window, max_average_window)`` — the
    reference's growing-window rule — realized as a streaming sum whose
    old mass decays once the window saturates.

    ``apply_gradients`` updates the running average alongside the inner
    step; ``average()`` returns the averaged parameters (the reference's
    ``apply()`` context swaps them in — here, functionally)."""

    def __init__(self, inner_optimizer, average_window_rate: float = 0.15,
                 min_average_window: int = 1,
                 max_average_window: Optional[int] = None):
        self.inner = inner_optimizer
        self.rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window or 10000

    def _window(self, count):
        w = jnp.ceil(self.rate * count.astype(jnp.float32))
        return jnp.clip(w, self.min_window, self.max_window)

    def init(self, params):
        return {"inner": self.inner.init(params),
                "sum": jax.tree_util.tree_map(
                    lambda p: jnp.zeros_like(p, jnp.float32), params),
                "count": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, grads, params, state, lr=None):
        new_params, inner_state = self.inner.apply_gradients(
            grads, params, state["inner"], lr=lr)
        count = state["count"] + 1
        window = self._window(count)
        # decay old mass once the sample count exceeds the current window
        keep = jnp.where(count.astype(jnp.float32) > window,
                         1.0 - 1.0 / window, 1.0)
        new_sum = jax.tree_util.tree_map(
            lambda s, p: keep * s + jnp.asarray(p, jnp.float32),
            state["sum"], new_params)
        return new_params, {"inner": inner_state, "sum": new_sum,
                            "count": count}

    def average(self, state, params):
        """Averaged parameters, cast back to each param's dtype."""
        eff = jnp.maximum(jnp.minimum(
            state["count"].astype(jnp.float32),
            self._window(state["count"])), 1.0)
        return jax.tree_util.tree_map(
            lambda s, p: (s / eff).astype(jnp.asarray(p).dtype),
            state["sum"], params)
