"""Incubate optimizers (reference: incubate/optimizer/lookahead.py:26
LookAhead, modelaverage.py:28 ModelAverage).

Both wrap an inner optimizer and keep extra parameter EMAs/snapshots in
their own state pytree, following this framework's functional
init/apply_gradients contract — the whole update stays one jittable step.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.errors import enforce

__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]


class LookAhead:
    """k steps forward, one step back (Zhang et al. 2019).

    Every ``k`` inner steps: slow += alpha * (fast - slow); fast = slow.
    """

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        enforce(0.0 <= alpha <= 1.0, "alpha must be in [0, 1]")
        enforce(k >= 1, "k must be >= 1")
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k

    def init(self, params):
        return {"inner": self.inner.init(params),
                "slow": jax.tree_util.tree_map(
                    lambda p: jnp.asarray(p, jnp.float32), params),
                "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, grads, params, state, lr=None):
        fast, inner_state = self.inner.apply_gradients(
            grads, params, state["inner"], lr=lr)
        step = state["step"] + 1
        sync = (step % self.k == 0)

        def _blend(slow, f):
            new_slow = slow + self.alpha * (jnp.asarray(f, jnp.float32)
                                            - slow)
            slow_out = jnp.where(sync, new_slow, slow)
            f_out = jnp.where(sync, new_slow.astype(jnp.asarray(f).dtype),
                              jnp.asarray(f))
            return slow_out, f_out

        flat_slow, treedef = jax.tree_util.tree_flatten(state["slow"])
        flat_fast = treedef.flatten_up_to(fast)
        pairs = [_blend(s, f) for s, f in zip(flat_slow, flat_fast)]
        new_slow = treedef.unflatten([p[0] for p in pairs])
        new_fast = treedef.unflatten([p[1] for p in pairs])
        return new_fast, {"inner": inner_state, "slow": new_slow,
                          "step": step}


class ModelAverage:
    """Maintain a windowed average of parameters for evaluation (reference
    ModelAverage).  The window at update ``t`` is
    ``clip(rate * t, min_average_window, max_average_window)`` — the
    reference's growing-window rule — realized as a streaming sum whose
    old mass decays once the window saturates.

    ``apply_gradients`` updates the running average alongside the inner
    step; ``average()`` returns the averaged parameters (the reference's
    ``apply()`` context swaps them in — here, functionally)."""

    def __init__(self, inner_optimizer, average_window_rate: float = 0.15,
                 min_average_window: int = 1,
                 max_average_window: Optional[int] = None):
        self.inner = inner_optimizer
        self.rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window or 10000

    def _window(self, count):
        w = jnp.ceil(self.rate * count.astype(jnp.float32))
        return jnp.clip(w, self.min_window, self.max_window)

    def init(self, params):
        return {"inner": self.inner.init(params),
                "sum": jax.tree_util.tree_map(
                    lambda p: jnp.zeros_like(p, jnp.float32), params),
                "count": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, grads, params, state, lr=None):
        new_params, inner_state = self.inner.apply_gradients(
            grads, params, state["inner"], lr=lr)
        count = state["count"] + 1
        window = self._window(count)
        # decay old mass once the sample count exceeds the current window
        keep = jnp.where(count.astype(jnp.float32) > window,
                         1.0 - 1.0 / window, 1.0)
        new_sum = jax.tree_util.tree_map(
            lambda s, p: keep * s + jnp.asarray(p, jnp.float32),
            state["sum"], new_params)
        return new_params, {"inner": inner_state, "sum": new_sum,
                            "count": count}

    def average(self, state, params):
        """Averaged parameters, cast back to each param's dtype."""
        eff = jnp.maximum(jnp.minimum(
            state["count"].astype(jnp.float32),
            self._window(state["count"])), 1.0)
        return jax.tree_util.tree_map(
            lambda s, p: (s / eff).astype(jnp.asarray(p).dtype),
            state["sum"], params)


class DistributedFusedLamb:
    """Sharded fused LAMB (reference incubate/optimizer/
    distributed_fused_lamb.py:27 + the fused CUDA op
    operators/optimizers/distributed_fused_lamb_op.cu).

    TPU-native design: every parameter is flattened into ONE fp32 master
    buffer (the multi-tensor-apply analog — a single vectorized update
    chain instead of a per-tensor op zoo), with static segment ids giving
    each parameter its own LAMB trust ratio via segment reductions.  The
    flat master/moment buffers are sharded over the dp/sharding mesh axis
    (the reference's nproc-way state partition, here a NamedSharding that
    GSPMD turns into a reduce-scattered update + all-gather), padded to
    the axis size.  Supports ClipGradByGlobalNorm semantics
    (max_global_grad_norm), exclude_from_weight_decay_fn, and a
    found_inf-style skip via ``set_scale`` + nonfinite detection.
    """

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None,
                 clip_after_allreduce: bool = True,
                 is_grad_scaled_by_nranks: bool = True,
                 alignment: int = 128,
                 use_master_param_norm: bool = True):
        self._lr = learning_rate
        self._wd = float(lamb_weight_decay or 0.0)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._exclude = exclude_from_weight_decay_fn
        # clip on the globally-reduced gradient and fp32-master param
        # norms are what the GSPMD formulation computes BY CONSTRUCTION;
        # the opposite settings cannot be honored, so reject them loudly
        enforce(clip_after_allreduce,
                "clip_after_allreduce=False is not supported: under GSPMD "
                "the gradient is globally reduced before any optimizer "
                "math runs")
        enforce(use_master_param_norm,
                "use_master_param_norm=False is not supported: trust "
                "ratios are computed on the fp32 master buffer")
        self._grad_scaled_by_nranks = bool(is_grad_scaled_by_nranks)
        self._parameters = list(parameters) if parameters is not None \
            else None
        self._state = None
        if grad_clip is not None:
            from ..optimizer import ClipGradByGlobalNorm
            enforce(isinstance(grad_clip, ClipGradByGlobalNorm),
                    "Only ClipGradByGlobalNorm is supported in "
                    "DistributedFusedLamb")
            self._max_gnorm = float(grad_clip.clip_norm)
        else:
            self._max_gnorm = -1.0
        self._alignment = int(alignment)
        self._scale = None

    def set_scale(self, scale):
        """AMP hook (reference _set_scale): grads are divided by ``scale``
        and the step is skipped when any grad is nonfinite."""
        self._scale = scale

    # -- flat layout --------------------------------------------------------
    def _shard_axis(self):
        from ..distributed.topology import get_mesh
        mesh = get_mesh()
        if mesh is None:
            return None, 1
        axis = "sharding" if "sharding" in mesh.axis_names else (
            "dp" if "dp" in mesh.axis_names else None)
        return (axis, mesh.shape[axis]) if axis else (None, 1)

    def _layout(self, params):
        """Static flat layout, cached per (treedef, shapes, dtypes) —
        rebuilding the O(N) segment-id array every step would dominate for
        the 1.3B-scale models this optimizer targets.  The cache holds
        only metadata (shapes/dtypes/offsets/seg), never array leaves, so
        no parameter memory is pinned."""
        import math
        import numpy as np
        _, treedef = jax.tree_util.tree_flatten(params)
        leaves = jax.tree_util.tree_leaves(params)
        shapes = tuple(tuple(jnp.shape(p)) for p in leaves)
        dtypes = tuple(str(jnp.asarray(p).dtype) for p in leaves)
        key = (treedef, shapes, dtypes)
        cached = getattr(self, "_layout_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        offsets = [0]
        for s in sizes:
            offsets.append(offsets[-1] + s)
        total = offsets[-1]
        # pad so the flat buffers divide BOTH the alignment and the mesh
        # sharding axis (else _shard would silently replicate)
        _, axis_n = self._shard_axis()
        mult = math.lcm(max(self._alignment, 1), axis_n)
        pad = (-total) % mult
        seg = np.empty(total + pad, np.int32)
        for i, (o, s) in enumerate(zip(offsets[:-1], sizes)):
            seg[o:o + s] = i
        seg[total:] = len(sizes)              # padding segment
        out = (treedef, shapes, dtypes, sizes, offsets, total, pad,
               jnp.asarray(seg))
        self._layout_cache = (key, out)
        return out

    def _flatten(self, tree, total, pad):
        flat = jax.tree_util.tree_leaves(tree)
        vec = jnp.concatenate(
            [jnp.ravel(jnp.asarray(x)).astype(jnp.float32) for x in flat])
        return jnp.pad(vec, (0, pad))

    def _shard(self, vec):
        from ..distributed.topology import get_mesh
        axis, axis_n = self._shard_axis()
        if axis is None:
            return vec
        enforce(vec.shape[0] % axis_n == 0,
                f"flat buffer {vec.shape[0]} not divisible by mesh axis "
                f"{axis}={axis_n} (layout padding bug)")
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(vec, NamedSharding(get_mesh(), P(axis)))

    def init(self, params):
        (treedef, shapes, dtypes, sizes, offsets, total, pad,
         seg) = self._layout(params)
        master = self._shard(self._flatten(params, total, pad))
        zeros = self._shard(jnp.zeros_like(master))
        return {"master": master, "moment1": zeros, "moment2": zeros,
                "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, grads, params, state, lr=None):
        (treedef, shapes, dtypes, sizes, offsets, total, pad,
         seg) = self._layout(params)
        nseg = len(sizes)
        g = self._flatten(grads, total, pad)
        found_inf = ~jnp.all(jnp.isfinite(g))
        if self._scale is not None:
            g = g / jnp.asarray(self._scale, jnp.float32)
        if not self._grad_scaled_by_nranks:
            # reference semantics: grads arrive SUMMED over ranks and the
            # optimizer applies the 1/nranks itself
            _, axis_n = self._shard_axis()
            if axis_n > 1:
                g = g / float(axis_n)
        if self._max_gnorm > 0:
            gnorm = jnp.sqrt(jnp.sum(jnp.square(g)))
            g = g * jnp.minimum(1.0, self._max_gnorm
                                / jnp.maximum(gnorm, 1e-12))

        from ..optimizer import LRScheduler
        step = state["step"] + 1
        if lr is not None:
            lr_t = jnp.asarray(lr, jnp.float32)
        elif isinstance(self._lr, LRScheduler):
            lr_t = self._lr(step - 1)
        else:
            lr_t = jnp.asarray(self._lr, jnp.float32)
        m = self._b1 * state["moment1"] + (1 - self._b1) * g
        v = self._b2 * state["moment2"] + (1 - self._b2) * jnp.square(g)
        mhat = m / (1 - self._b1 ** step.astype(jnp.float32))
        vhat = v / (1 - self._b2 ** step.astype(jnp.float32))
        master = state["master"]
        upd = mhat / (jnp.sqrt(vhat) + self._eps)
        # per-parameter weight decay mask (exclude_from_weight_decay_fn
        # gets the parameter's tree path string, reference semantics)
        import numpy as np
        wd_mask = np.ones(nseg + 1, np.float32)
        wd_mask[nseg] = 0.0
        if self._exclude is not None:
            # dotted names ("layers.0.bias"), matching the base
            # Optimizer's apply_decay_param_fun path convention
            def _dotted(kp):
                return ".".join(str(getattr(k, "key",
                                            getattr(k, "idx", k)))
                                for k in kp)
            paths = [_dotted(kp) for kp, _ in
                     jax.tree_util.tree_flatten_with_path(params)[0]]
            for i, name in enumerate(paths):
                if self._exclude(name):
                    wd_mask[i] = 0.0
        upd = upd + self._wd * jnp.asarray(wd_mask)[seg] * master

        # LAMB trust ratio per parameter segment (segment reductions are
        # the fused analog of the reference's per-param norm kernels)
        pnorm2 = jax.ops.segment_sum(jnp.square(master), seg,
                                     num_segments=nseg + 1)
        unorm2 = jax.ops.segment_sum(jnp.square(upd), seg,
                                     num_segments=nseg + 1)
        pnorm = jnp.sqrt(pnorm2)
        unorm = jnp.sqrt(unorm2)
        ratio = jnp.where((pnorm > 0) & (unorm > 0),
                          pnorm / jnp.maximum(unorm, 1e-12), 1.0)
        new_master = master - lr_t * ratio[seg] * upd

        skip = found_inf
        out = {
            "master": jnp.where(skip, master, new_master),
            "moment1": jnp.where(skip, state["moment1"], m),
            "moment2": jnp.where(skip, state["moment2"], v),
            "step": jnp.where(skip, state["step"], step),
        }
        # unflatten back to the original pytree/dtypes
        new_flat = []
        vec = out["master"]
        for shp, dt, o, s in zip(shapes, dtypes, offsets[:-1], sizes):
            seg_vals = jax.lax.dynamic_slice(vec, (o,), (s,))
            new_flat.append(seg_vals.reshape(shp).astype(dt))
        return jax.tree_util.tree_unflatten(treedef, new_flat), out

    def update(self, grads, params, state):
        return self.apply_gradients(grads, params, state)

    # -- stateful (dygraph-parity) path -------------------------------------
    def step(self, grads=None):
        """Eager convenience over bound parameters (reference scripts pass
        ``parameters=`` and drive ``step()``)."""
        enforce(self._parameters is not None,
                "stateful step() needs parameters= at construction")
        # same key scheme as Optimizer._param_keys: real names (so
        # exclude_from_weight_decay_fn matches what the model calls the
        # parameter), deduped, synthetic only as a last resort
        if getattr(self, "_param_key_list", None) is None:
            keys, seen = [], set()
            for i, p in enumerate(self._parameters):
                k = p.name if p.name else f"param_{i}"
                if k in seen:
                    k = f"{k}#{i}"
                seen.add(k)
                keys.append(k)
            self._param_key_list = keys
        keys = self._param_key_list
        values = dict(zip(keys, (p.value for p in self._parameters)))
        if grads is None:
            grads = [p._grad for p in self._parameters]
        gdict = dict(zip(keys, grads))
        if self._state is None:
            self._state = self.init(values)
        new_values, self._state = self.apply_gradients(gdict, values,
                                                       self._state)
        for p, k in zip(self._parameters, keys):
            p.value = new_values[k]
            p._grad = None

    def clear_grad(self):
        if self._parameters:
            for p in self._parameters:
                p._grad = None
